"""dbxlint concurrency layer: whole-package lock model + four rules.

The per-module, per-class lock rules (rounds 3-11) kept catching real
races one advisory pass late — the quota-charge check-then-act, the
PagePool scrape stall — because a per-function view provably cannot see
cross-module orderings (the same reason "Automatic Full Compilation …
to Cloud TPUs" insists on a whole-program view, PAPERS.md). This module
builds ONE model of the whole lint target and derives every concurrency
rule from it:

- a **cross-module call graph**: bare names resolve through the lexical
  scope tree, ``self.m()`` through the class (and bases), ``self.attr.m()``
  through attribute types inferred from ``self.attr = ClassName(...)``
  constructor assignments, ``alias.f()`` through the import map, local
  ``var = ClassName(...)`` through function-local typing. Unresolvable
  calls (dict methods, dynamic dispatch) are simply not edges — the
  resolver is precision-first, never name-splatter (``self._entries.pop``
  must not resolve to ``ByteLRU.pop``);
- **per-function held-lock sets**: a fixpoint over (function, entry
  held-set) contexts. ``with <lock>:`` adds the lock — identified at
  class level, like Linux lockdep's lock classes: ``threading.Lock/RLock``
  attributes key ``(module, class, attr)``, module-level locks
  ``(module, None, name)`` — and calls propagate the current held set
  into the callee as a new entry context. Public functions (no leading
  underscore on function or class) additionally get the empty context:
  anyone may call them lock-free. Private helpers get ONLY their real
  call sites' contexts — which is what turns "``prepare()`` holds the
  lock" suppressions into proofs;
- the **global lock-acquisition-order graph**: an edge ``A -> B`` for
  every site that acquires ``B`` while holding ``A`` (in any context).

Rules derived from the model:

- ``lock-order``: cycles in the order graph (ABBA deadlock risk) and
  nested re-acquisition of a non-reentrant ``Lock`` already held on a
  caller path (self-deadlock by construction);
- ``lock-discipline`` (interprocedural): a guarded field — mutated at
  least once with the owner's lock held, constructor bodies exempt —
  mutated on ANY reachable path that does not hold the lock. A helper
  whose every caller holds the lock is clean, provably;
- ``atomicity``: check-then-act across a lock release — a guarded field
  read into a local under the lock, a branch on that local outside it,
  and a re-acquired write to the same field (the PR-8 quota-charge bug
  class). Re-validating the field under the second acquisition (the
  double-checked pattern) is the fix and reads as clean;
- ``lock-blocking``: a blocking or device-sync call (``sleep``,
  ``subprocess``, ``block_until_ready``, ``jax.device_get``,
  ``.result()``, ``.wait()``, ``open``/``makedirs``) executed while any
  lock is held, interprocedurally (the PR-9 PagePool scrape-stall class:
  one slow syscall under an index lock starves every scrape).

The runtime twin (actual acquisition edges under ``DBX_LOCKDEP=1``)
lives in :mod:`.lockdep`.
"""

from __future__ import annotations

import ast
import dataclasses
import os

from .ast_rules import (_DEVICE_SYNC, _FUNC_NODES, _MUTATORS, _build_scopes,
                        _dotted, _is_timeout_wait, _self_attr,
                        _terminal_name)
from .core import Finding, LintContext, PyFile

# Calls that block (or synchronize the device) and must never run under a
# lock: every other thread contending on it stalls for the full syscall /
# transfer, and a lock held across a wait can complete a deadlock cycle
# the order graph alone cannot see. File OPENS are included (path
# resolution / NFS under a hot-path lock); plain writes/fsync are not —
# the journal's serialized durable append is that discipline's point.
# Bounded queue/thread waits (`.get`/`.put`/`.join` with ``timeout=``,
# the round-14 pipeline handoff vocabulary) are detected by keyword in
# the leaf walk: a producer parking on a full handoff while holding an
# accounting lock stalls — or deadlocks against — its consumer.
_BLOCKING_UNDER_LOCK = ({"sleep", "input", "result", "wait", "open",
                         "makedirs"} | _DEVICE_SYNC)
_BLOCKING_MODULES = {"subprocess"}

# Per-function entry-context cap: past this the function is clearly on
# every path and more contexts add nothing but work.
_MAX_CONTEXTS = 12

# LockId: (module rel path, owning class name or None, attribute/name).
LockId = tuple


def _short_lock(lock: LockId) -> str:
    mod, cls, attr = lock
    stem = os.path.splitext(os.path.basename(mod))[0]
    return f"{stem}.{cls}.{attr}" if cls else f"{stem}.{attr}"


def _lock_kind(node: ast.AST) -> str | None:
    """``"Lock"``/``"RLock"`` when ``node`` is a lock-factory call."""
    if isinstance(node, ast.Call):
        t = _terminal_name(node.func)
        if t in ("Lock", "RLock"):
            return t
    return None


# ---------------------------------------------------------------------------
# Model data
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Func:
    idx: int
    pf: PyFile
    mod: "_Module"
    node: ast.AST
    qual: str
    cls: "_Class | None"
    scope: object                   # ast_rules._Scope (bare-name resolution)
    public: bool


@dataclasses.dataclass
class _Class:
    mod: "_Module"
    name: str
    node: ast.ClassDef
    methods: dict = dataclasses.field(default_factory=dict)
    locks: dict = dataclasses.field(default_factory=dict)   # attr -> kind
    # attr -> candidate constructor-call func exprs (resolved lazily).
    attr_ctors: dict = dataclasses.field(default_factory=dict)
    bases: list = dataclasses.field(default_factory=list)   # base exprs


@dataclasses.dataclass
class _Module:
    rel: str
    dotted: str
    pf: PyFile
    classes: dict = dataclasses.field(default_factory=dict)
    funcs: dict = dataclasses.field(default_factory=dict)   # top-level only
    locks: dict = dataclasses.field(default_factory=dict)   # name -> kind
    globals: set = dataclasses.field(default_factory=set)
    imports_mod: dict = dataclasses.field(default_factory=dict)
    imports_sym: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class LockModel:
    modules: dict = dataclasses.field(default_factory=dict)  # dotted -> _Module
    funcs: list = dataclasses.field(default_factory=list)
    by_node: dict = dataclasses.field(default_factory=dict)  # id(ast) -> _Func
    # (lockA, lockB) -> list[(rel, line, qual)]: B acquired holding A.
    edges: dict = dataclasses.field(default_factory=dict)
    # (lock, rel, line, qual, origin): re-acquisition of a held plain Lock.
    self_nest: list = dataclasses.field(default_factory=list)
    # (func, kind, owner, field, line, heldset, origin)
    mutations: list = dataclasses.field(default_factory=list)
    # (func, line, call, heldset, origin)
    blocking: list = dataclasses.field(default_factory=list)
    entry: dict = dataclasses.field(default_factory=dict)    # idx -> set[ctx]
    origin: dict = dataclasses.field(default_factory=dict)   # (idx,ctx)->str
    # idx -> (local_types, local_shadows): body-only facts, computed once
    # per function however many entry contexts re-walk it.
    fn_cache: dict = dataclasses.field(default_factory=dict)
    guarded_attr: dict = dataclasses.field(default_factory=dict)
    guarded_global: dict = dataclasses.field(default_factory=dict)

    def add_edge(self, a: LockId, b: LockId, rel: str, line: int,
                 qual: str) -> None:
        self.edges.setdefault((a, b), []).append((rel, line, qual))


def _module_dotted(rel: str) -> str:
    parts = rel.replace(os.sep, "/").split("/")
    parts[-1] = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
    if parts[-1] == "__init__":
        parts.pop()
    return ".".join(parts)


def get_model(ctx: LintContext) -> LockModel:
    """The (cached) lock model for this lint invocation — built once,
    shared by every concurrency rule."""
    model = getattr(ctx, "_lock_model", None)
    if model is None:
        model = _build_model(ctx)
        ctx._lock_model = model
    return model


# ---------------------------------------------------------------------------
# Build pass 1: modules, classes, functions, imports
# ---------------------------------------------------------------------------

def _build_model(ctx: LintContext) -> LockModel:
    from .core import PACKAGE_NAME

    model = LockModel()
    for pf in ctx.files:
        rel = pf.rel
        mod = _Module(rel=rel, dotted=_module_dotted(rel), pf=pf)
        model.modules[mod.dotted] = mod
        _scan_module(model, mod, PACKAGE_NAME)
    _resolve_imports(model)
    _fixpoint(model)
    _finalize_guarded(model)
    return model


def _scan_module(model: LockModel, mod: _Module, pkg_name: str) -> None:
    pf = mod.pf
    _, scopes = _build_scopes(pf.tree)
    scope_by_node = {id(s.node): s for s in scopes}

    # Imports (resolved against the module table in pass 2).
    is_init = os.path.basename(pf.rel) == "__init__.py"
    pkg_parts = mod.dotted.split(".") if mod.dotted else []
    if not is_init:
        pkg_parts = pkg_parts[:-1]
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.name
                if name == pkg_name or name.startswith(pkg_name + "."):
                    inner = name[len(pkg_name):].lstrip(".")
                    mod.imports_mod[alias.asname
                                    or name.split(".")[-1]] = inner
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_parts[:len(pkg_parts) - (node.level - 1)] \
                    if node.level - 1 <= len(pkg_parts) else None
                if base is None:
                    continue
            elif node.module and (node.module == pkg_name
                                  or node.module.startswith(pkg_name + ".")):
                base = node.module[len(pkg_name):].lstrip(".").split(".")
                base = [p for p in base if p]
                for alias in node.names:
                    mod.imports_sym[alias.asname or alias.name] = (
                        ".".join(base), alias.name)
                continue
            else:
                continue
            target = base + (node.module.split(".") if node.module else [])
            for alias in node.names:
                local = alias.asname or alias.name
                # `from . import panel_store` imports a MODULE; `from
                # .tenancy import ByteLRU` a symbol. Disambiguated in
                # pass 2 once every module is known; record both forms.
                mod.imports_sym[local] = (".".join(target), alias.name)

    # Classes (EVERY ClassDef, nested-in-function/-class included — a
    # lock-owning class defined inside a factory must not lint blind),
    # top-level functions, module locks/globals. Only top-level classes
    # enter the name-resolution table; each class's attribute scan stops
    # at nested ClassDef subtrees so an inner class's `self._lock` is
    # never credited to the outer class's lock set.
    all_classes: list[_Class] = []
    top_level_cls = {id(s) for s in pf.tree.body
                     if isinstance(s, ast.ClassDef)}
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        cls = _Class(mod=mod, name=node.name, node=node,
                     bases=list(node.bases))
        all_classes.append(cls)
        if id(node) in top_level_cls:
            mod.classes[node.name] = cls
        for sub in _class_own_nodes(node):
            if isinstance(sub, ast.Assign):
                kind = _lock_kind(sub.value)
                for t in sub.targets:
                    a = _self_attr(t)
                    if a is None:
                        continue
                    if kind:
                        cls.locks[a] = kind
                    else:
                        ctors = _ctor_candidates(sub.value)
                        if ctors:
                            cls.attr_ctors.setdefault(a, []).extend(ctors)
    for stmt in pf.tree.body:
        if isinstance(stmt, ast.Assign):
            kind = _lock_kind(stmt.value)
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    if kind:
                        mod.locks[t.id] = kind
                    else:
                        mod.globals.add(t.id)
        elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target,
                                                            ast.Name):
            mod.globals.add(stmt.target.id)
    mod.globals -= set(mod.locks)

    # Every function-like scope becomes a _Func (nested defs included —
    # they are resolvable through the scope tree; lambdas are not
    # walked as functions of their own).
    class_of_method = {}
    for cls in all_classes:
        for m in cls.node.body:
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                class_of_method[id(m)] = cls
    for scope in scopes:
        node = scope.node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        cls = class_of_method.get(id(node))
        public = not node.name.startswith("_") or (
            node.name.startswith("__") and node.name.endswith("__"))
        if cls is not None and cls.name.startswith("_"):
            public = False
        fi = _Func(idx=len(model.funcs), pf=pf, mod=mod, node=node,
                   qual=scope.qualname, cls=cls, scope=scope, public=public)
        model.funcs.append(fi)
        model.by_node[id(node)] = fi
        model.entry[fi.idx] = set()
        if cls is not None:
            cls.methods[node.name] = fi
        elif scope.parent is not None and getattr(
                scope.parent, "qualname", None) == "<module>":
            mod.funcs[node.name] = fi


def _class_own_nodes(cls_node: ast.ClassDef):
    """Walk a class's subtree WITHOUT descending into nested ClassDefs
    (their assignments belong to them) — function bodies are included
    (``__init__`` is where lock/attr assignments live)."""
    stack = list(ast.iter_child_nodes(cls_node))
    while stack:
        node = stack.pop()
        if isinstance(node, ast.ClassDef):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _ctor_candidates(value: ast.AST) -> list:
    """Constructor-call func exprs inside an attribute assignment's value
    — unwrapping the ``a or B()`` / ``a if c else B()`` idioms so
    ``self._journal = journal or Journal(None)`` still types."""
    out = []
    stack = [value]
    while stack:
        v = stack.pop()
        if isinstance(v, ast.Call):
            out.append(v.func)
        elif isinstance(v, ast.BoolOp):
            stack.extend(v.values)
        elif isinstance(v, ast.IfExp):
            stack.extend([v.body, v.orelse])
    return out


def _resolve_imports(model: LockModel) -> None:
    """Split ``from X import name`` records into module vs symbol imports
    now that the module table is complete."""
    for mod in model.modules.values():
        for local, (target, name) in list(mod.imports_sym.items()):
            cand = f"{target}.{name}" if target else name
            if cand in model.modules:
                mod.imports_mod[local] = cand
                del mod.imports_sym[local]


def _resolve_symbol(model: LockModel, dotted: str, name: str,
                    depth: int = 0):
    """``("class", _Class)`` / ``("func", _Func)`` for ``dotted.name``,
    following re-export chains (package ``__init__``) a few hops."""
    if depth > 4:
        return None
    m = model.modules.get(dotted)
    if m is None:
        return None
    if name in m.classes:
        return ("class", m.classes[name])
    if name in m.funcs:
        return ("func", m.funcs[name])
    hit = m.imports_sym.get(name)
    if hit is not None:
        return _resolve_symbol(model, hit[0], hit[1], depth + 1)
    return None


# ---------------------------------------------------------------------------
# Resolution helpers (class members, locks, callees)
# ---------------------------------------------------------------------------

def _base_classes(model: LockModel, cls: _Class, depth: int = 0):
    for b in cls.bases:
        k = _class_of_expr(model, b, cls.mod)
        if k is not None and depth < 4:
            yield k
            yield from _base_classes(model, k, depth + 1)


def _class_of_expr(model: LockModel, expr: ast.AST,
                   mod: _Module) -> _Class | None:
    if isinstance(expr, ast.Name):
        if expr.id in mod.classes:
            return mod.classes[expr.id]
        hit = mod.imports_sym.get(expr.id)
        if hit is not None:
            r = _resolve_symbol(model, hit[0], hit[1])
            if r is not None and r[0] == "class":
                return r[1]
        return None
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        target = mod.imports_mod.get(expr.value.id)
        if target is not None:
            r = _resolve_symbol(model, target, expr.attr)
            if r is not None and r[0] == "class":
                return r[1]
    return None


def _method_of(model: LockModel, cls: _Class, name: str) -> _Func | None:
    m = cls.methods.get(name)
    if m is not None:
        return m
    for base in _base_classes(model, cls):
        m = base.methods.get(name)
        if m is not None:
            return m
    return None


def _lock_attr_of(model: LockModel, cls: _Class,
                  attr: str) -> tuple[_Class, str] | None:
    """The class DEFINING lock attribute ``attr`` (self or a base) — lock
    identity belongs to the defining class, Linux-lockdep style."""
    if attr in cls.locks:
        return (cls, cls.locks[attr])
    for base in _base_classes(model, cls):
        if attr in base.locks:
            return (base, base.locks[attr])
    return None


def _attr_type(model: LockModel, cls: _Class, attr: str) -> _Class | None:
    ctors = cls.attr_ctors.get(attr)
    if ctors:
        for f in ctors:
            k = _class_of_expr(model, f, cls.mod)
            if k is not None:
                return k
    for base in _base_classes(model, cls):
        k = _attr_type(model, base, attr)
        if k is not None:
            return k
    return None


def _class_has_locks(model: LockModel, cls: _Class) -> bool:
    if cls.locks:
        return True
    return any(base.locks for base in _base_classes(model, cls))


def _owner_locks(model: LockModel, cls: _Class) -> frozenset:
    out = {(cls.mod.rel, cls.name, a) for a in cls.locks}
    for base in _base_classes(model, cls):
        out |= {(base.mod.rel, base.name, a) for a in base.locks}
    return frozenset(out)


def _lock_in_expr(model: LockModel, expr: ast.AST,
                  fi: _Func) -> tuple[LockId, str] | None:
    a = _self_attr(expr)
    if a is not None and fi.cls is not None:
        hit = _lock_attr_of(model, fi.cls, a)
        if hit is not None:
            owner, kind = hit
            return ((owner.mod.rel, owner.name, a), kind)
        return None
    if isinstance(expr, ast.Name) and expr.id in fi.mod.locks:
        return ((fi.mod.rel, None, expr.id), fi.mod.locks[expr.id])
    return None


def _local_types(model: LockModel, fi: _Func) -> dict:
    """Function-local ``var = ClassName(...)`` typing (single pass; last
    binding wins, good enough for construction-then-use bodies)."""
    out: dict = {}
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            k = _class_of_expr(model, node.value.func, fi.mod)
            if k is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = k
    return out


def _callees(model: LockModel, call: ast.Call, fi: _Func,
             local_types: dict) -> list[_Func]:
    f = call.func
    if isinstance(f, ast.Name):
        hit = fi.scope.resolve(f.id)
        if hit is not None:
            target = model.by_node.get(id(hit.node))
            return [target] if target is not None else []
        k = _class_of_expr(model, f, fi.mod)
        if k is not None:
            init = _method_of(model, k, "__init__")
            return [init] if init is not None else []
        sym = fi.mod.imports_sym.get(f.id)
        if sym is not None:
            r = _resolve_symbol(model, sym[0], sym[1])
            if r is not None and r[0] == "func":
                return [r[1]]
        return []
    if not isinstance(f, ast.Attribute):
        return []
    base = f.value
    if isinstance(base, ast.Name):
        if base.id == "self" and fi.cls is not None:
            m = _method_of(model, fi.cls, f.attr)
            return [m] if m is not None else []
        k = local_types.get(base.id)
        if k is not None:
            m = _method_of(model, k, f.attr)
            return [m] if m is not None else []
        target = fi.mod.imports_mod.get(base.id)
        if target is not None:
            r = _resolve_symbol(model, target, f.attr)
            if r is not None and r[0] == "func":
                return [r[1]]
            if r is not None and r[0] == "class":
                init = _method_of(model, r[1], "__init__")
                return [init] if init is not None else []
        return []
    if (isinstance(base, ast.Attribute) and isinstance(base.value, ast.Name)
            and base.value.id == "self" and fi.cls is not None):
        k = _attr_type(model, fi.cls, base.attr)
        if k is not None:
            m = _method_of(model, k, f.attr)
            return [m] if m is not None else []
    return []


# ---------------------------------------------------------------------------
# Build pass 2: (function, entry-held-set) fixpoint
# ---------------------------------------------------------------------------

def _local_shadows(fn: ast.AST) -> set:
    """Names any plain assignment makes function-local (Python scoping:
    mutations then target the shadow, not a guarded module global)."""
    declared_global = {
        name for node in ast.walk(fn)
        if isinstance(node, ast.Global) for name in node.names}
    return {
        t.id
        for node in ast.walk(fn)
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.For))
        for t in (node.targets if isinstance(node, ast.Assign)
                  else [node.target])
        if isinstance(t, ast.Name)
    } - declared_global


def _fixpoint(model: LockModel) -> None:
    work: list[tuple[_Func, frozenset]] = []

    def seed(fi: _Func, ctx: frozenset, origin: str):
        key = (fi.idx, ctx)
        if ctx in model.entry[fi.idx] \
                or len(model.entry[fi.idx]) >= _MAX_CONTEXTS:
            return
        model.entry[fi.idx].add(ctx)
        model.origin.setdefault(key, origin)
        work.append((fi, ctx))

    for fi in model.funcs:
        if fi.public:
            seed(fi, frozenset(), "a lock-free public entry")
    processed = 0
    while work:
        fi, ctx = work.pop()
        processed += 1
        if processed > 50000:     # runaway guard; never hit in practice
            break
        _walk_func(model, fi, ctx, seed)
    # Private functions with no in-package callers still get walked once
    # lock-free: their with-blocks must contribute order edges and their
    # mutations must be judged exactly like the pre-interprocedural rule.
    for fi in model.funcs:
        if not model.entry[fi.idx]:
            seed(fi, frozenset(), "a caller outside the analyzed package")
    while work:
        fi, ctx = work.pop()
        _walk_func(model, fi, ctx, seed)


def _walk_func(model: LockModel, fi: _Func, entry: frozenset, seed) -> None:
    cached = model.fn_cache.get(fi.idx)
    if cached is None:
        cached = model.fn_cache[fi.idx] = (_local_types(model, fi),
                                           _local_shadows(fi.node))
    local_types, shadows = cached
    origin = model.origin.get((fi.idx, entry), "")
    check_attrs = (fi.cls is not None
                   and _class_has_locks(model, fi.cls)
                   and fi.node.name != "__init__")
    check_globals = bool(fi.mod.locks)

    def record_mutation(kind, owner, field, line, held):
        model.mutations.append((fi, kind, owner, field, line,
                                frozenset(held), origin))

    def leaf(node, held):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                             ast.Delete)):
            targets = (node.targets if isinstance(node, (ast.Assign,
                                                         ast.Delete))
                       else [node.target])
            for t in targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                a = _self_attr(base)
                if a is not None and check_attrs:
                    record_mutation("attr", fi.cls, a, node.lineno, held)
                elif (isinstance(base, ast.Name) and check_globals
                      and base.id in fi.mod.globals
                      and base.id not in shadows):
                    record_mutation("global", fi.mod, base.id, node.lineno,
                                    held)
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                a = _self_attr(f.value)
                if a is not None and check_attrs:
                    record_mutation("attr", fi.cls, a, node.lineno, held)
                elif (isinstance(f.value, ast.Name) and check_globals
                      and f.value.id in fi.mod.globals
                      and f.value.id not in shadows):
                    record_mutation("global", fi.mod, f.value.id,
                                    node.lineno, held)
            if held:
                term = _terminal_name(f)
                dotted = _dotted(f) or ""
                if (term in _BLOCKING_UNDER_LOCK
                        or _is_timeout_wait(node, term)
                        or dotted.split(".")[0] in _BLOCKING_MODULES):
                    model.blocking.append((fi, node.lineno, dotted or term,
                                           frozenset(held), origin))
            for callee in _callees(model, node, fi, local_types):
                seed(callee, frozenset(held),
                     f"`{fi.qual}` "
                     + (f"holding {', '.join(sorted(_short_lock(h) for h in held))}"
                        if held else "lock-free"))

    def visit(node, held):
        if isinstance(node, _FUNC_NODES):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in node.items:
                # The context expressions evaluate (and may call) BEFORE
                # the locks they denote are taken.
                for sub in ast.walk(item.context_expr):
                    if not isinstance(sub, _FUNC_NODES):
                        leaf(sub, held)
                hit = _lock_in_expr(model, item.context_expr, fi)
                if hit is None:
                    continue
                lock, kind = hit
                line = item.context_expr.lineno
                if lock in held or lock in acquired:
                    if kind == "Lock":
                        model.self_nest.append(
                            (lock, fi.pf.rel, line, fi.qual, origin))
                    continue
                for h in held:
                    model.add_edge(h, lock, fi.pf.rel, line, fi.qual)
                for h in acquired:
                    model.add_edge(h, lock, fi.pf.rel, line, fi.qual)
                acquired.append(lock)
            inner = held | frozenset(acquired)
            for stmt in node.body:
                visit(stmt, inner)
            return
        leaf(node, held)
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fi.node.body:
        visit(stmt, entry)


def _finalize_guarded(model: LockModel) -> None:
    """Guardedness inference over the whole fixpoint: a field is guarded
    when SOME mutation of it ran with one of the owner's locks held —
    including mutations in helpers whose callers held the lock, which
    the per-function view could not credit."""
    for fi, kind, owner, field, _line, held, _origin in model.mutations:
        if kind == "attr":
            if held & _owner_locks(model, owner):
                model.guarded_attr.setdefault(
                    (owner.mod.rel, owner.name), set()).add(field)
        else:
            if held & {(owner.rel, None, n) for n in owner.locks}:
                model.guarded_global.setdefault(owner.rel, set()).add(field)


# ---------------------------------------------------------------------------
# Rule: lock-order
# ---------------------------------------------------------------------------

def _sccs(adj: dict) -> list[set]:
    """Tarjan strongly-connected components (iterative) over the lock
    order graph; only multi-node SCCs can carry cycles here (self-edges
    are filtered at edge insertion)."""
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    out: list[set] = []
    counter = [0]

    def strongconnect(v):
        call_stack = [(v, iter(adj.get(v, ())))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while call_stack:
            node, it = call_stack[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    call_stack.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            call_stack.pop()
            if call_stack:
                parent = call_stack[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == node:
                        break
                out.append(comp)

    for v in adj:
        if v not in index:
            strongconnect(v)
    return out


class LockOrderRule:
    """Cycles in the global lock-acquisition-order graph + re-acquisition
    of a held non-reentrant lock (module docstring)."""

    name = "lock-order"
    doc = "lock-acquisition-order cycle or nested re-acquisition"

    def check(self, ctx: LintContext) -> list[Finding]:
        model = get_model(ctx)
        out: list[Finding] = []
        adj: dict = {}
        for (a, b) in model.edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        cyclic = [c for c in _sccs(adj) if len(c) > 1]
        for comp in cyclic:
            names = " <-> ".join(sorted(_short_lock(c) for c in comp))
            for (a, b), sites in sorted(model.edges.items(),
                                        key=lambda kv: str(kv[0])):
                if a not in comp or b not in comp:
                    continue
                rev = model.edges.get((b, a), [])
                rev_at = (f" (reverse order at {rev[0][0]}:{rev[0][1]})"
                          if rev else "")
                for rel, line, qual in sites:
                    out.append(Finding(
                        self.name, rel, line,
                        f"lock-order cycle [{names}]: `{_short_lock(b)}` "
                        f"is acquired in `{qual}` while "
                        f"`{_short_lock(a)}` is held{rev_at} — "
                        "inconsistent acquisition order can deadlock; "
                        "pick one global order and stick to it"))
        for lock, rel, line, qual, origin in model.self_nest:
            out.append(Finding(
                self.name, rel, line,
                f"`{_short_lock(lock)}` is re-acquired in `{qual}` while "
                f"already held (reached via {origin}) — threading.Lock "
                "is non-reentrant, this self-deadlocks; use RLock or "
                "hoist the acquisition"))
        # One finding per site (a site can participate in several
        # contexts; the report is per line, like every other rule).
        seen: set = set()
        deduped = []
        for f in out:
            key = (f.path, f.line, f.message)
            if key not in seen:
                seen.add(key)
                deduped.append(f)
        return deduped


# ---------------------------------------------------------------------------
# Rule: lock-discipline (interprocedural)
# ---------------------------------------------------------------------------

class LockDisciplineRule:
    """Guarded-field mutations on a lock-free reachable path.

    A field is *guarded* when the class (or module) that owns a
    ``threading.Lock``/``RLock`` mutates it at least once while that
    lock is held — directly or via a caller, constructor bodies exempt.
    Any mutation of the same field on a path that does NOT hold the lock
    is a discipline violation. Interprocedural since round 12: a helper
    whose every in-package caller holds the lock is PROVABLY clean (the
    PagePool ``prepare()`` helpers), while a helper reachable lock-free
    (a public name, or one lock-free caller) is flagged with the
    offending path.
    """

    name = "lock-discipline"
    doc = "guarded-field mutation on a lock-free path"

    def check(self, ctx: LintContext) -> list[Finding]:
        model = get_model(ctx)
        flagged: dict = {}
        for fi, kind, owner, field, line, held, origin in model.mutations:
            if kind == "attr":
                if field not in model.guarded_attr.get(
                        (owner.mod.rel, owner.name), ()):
                    continue
                if held & _owner_locks(model, owner):
                    continue
                key = (fi.pf.rel, line, field)
                via = (f" (reached via {origin})"
                       if fi.cls is not None and fi.qual and origin
                       and not fi.public else "")
                flagged.setdefault(key, Finding(
                    self.name, fi.pf.rel, line,
                    f"`self.{field}` is mutated under `{owner.name}`'s "
                    f"lock elsewhere but mutated here without holding "
                    f"it{via}"))
            else:
                if field not in model.guarded_global.get(owner.rel, ()):
                    continue
                if held & {(owner.rel, None, n) for n in owner.locks}:
                    continue
                key = (fi.pf.rel, line, field)
                flagged.setdefault(key, Finding(
                    self.name, fi.pf.rel, line,
                    f"module global `{field}` is mutated under the module "
                    f"lock elsewhere but mutated here without holding it"))
        return list(flagged.values())


# ---------------------------------------------------------------------------
# Rule: atomicity
# ---------------------------------------------------------------------------

class AtomicityRule:
    """Check-then-act on a guarded field across a lock release.

    The shape: a ``with lock:`` block reads a guarded field into a
    local, the lock is released, a branch tests that local, and a later
    ``with lock:`` block writes the same field — the written value may
    act on state another thread changed in the window (the PR-8
    quota-charge race: charge computed from a pre-window read let an
    at-quota tenant take one extra batch per concurrent poll). The
    double-checked fix — re-reading the field under the second
    acquisition — reads as clean.
    """

    name = "atomicity"
    doc = "check-then-act on a guarded field across lock release"

    def check(self, ctx: LintContext) -> list[Finding]:
        model = get_model(ctx)
        out: list[Finding] = []
        for fi in model.funcs:
            out.extend(self._check_func(model, fi))
        # dedupe (functions are walked once here, but stay defensive)
        seen: set = set()
        deduped = []
        for f in out:
            if (f.path, f.line) not in seen:
                seen.add((f.path, f.line))
                deduped.append(f)
        return deduped

    def _guarded_fields(self, model: LockModel, fi: _Func,
                        lock: LockId) -> set:
        if lock[1] is not None and fi.cls is not None:
            return model.guarded_attr.get((lock[0], lock[1]), set())
        if lock[1] is None:
            return model.guarded_global.get(lock[0], set())
        return set()

    def _field_of(self, node: ast.AST, fi: _Func, lock: LockId):
        if lock[1] is not None:
            return _self_attr(node)
        if isinstance(node, ast.Name):
            return node.id
        return None

    def _check_func(self, model: LockModel, fi: _Func) -> list[Finding]:
        regions: dict = {}   # lock -> [(with_node, start, end)]
        for node in ast.walk(fi.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    hit = _lock_in_expr(model, item.context_expr, fi)
                    if hit is None:
                        continue
                    end = max((getattr(n, "lineno", node.lineno)
                               for n in ast.walk(node)),
                              default=node.lineno)
                    regions.setdefault(hit[0], []).append(
                        (node, node.lineno, end))
        out: list[Finding] = []
        conds = None   # computed once per function, only when needed
        for lock, regs in regions.items():
            if len(regs) < 2:
                continue
            guarded = self._guarded_fields(model, fi, lock)
            if not guarded:
                continue
            regs.sort(key=lambda r: r[1])
            if conds is None:
                conds = [n for n in ast.walk(fi.node)
                         if isinstance(n, (ast.If, ast.While, ast.IfExp))]
            for i, (a_node, a_start, a_end) in enumerate(regs):
                reads = self._region_reads(a_node, fi, lock, guarded)
                if not reads:
                    continue
                for b_node, b_start, _b_end in regs[i + 1:]:
                    if b_start <= a_end:
                        continue   # nested/overlapping: same critical sect.
                    writes = self._region_writes(b_node, fi, lock, guarded)
                    common = {f for f in writes if f in
                              {fld for fld, _ in reads.values()}}
                    if not common:
                        continue
                    if self._revalidates(b_node, fi, lock, common):
                        continue
                    read_names = {n for n, (fld, _) in reads.items()
                                  if fld in common}
                    branch = self._deciding_branch(conds, read_names,
                                                   a_end, b_start, b_node)
                    if branch is None:
                        continue
                    field = sorted(common)[0]
                    rline = min(line for fld, line in reads.values()
                                if fld == field)
                    wline = writes[field]
                    prefix = "self." if lock[1] is not None else ""
                    out.append(Finding(
                        self.name, fi.pf.rel, wline,
                        f"check-then-act across `{_short_lock(lock)}` "
                        f"release in `{fi.qual}`: `{prefix}{field}` was "
                        f"read under the lock at line {rline}, the "
                        f"decision at line {branch.lineno} ran unlocked, "
                        f"and this re-acquired write may act on a stale "
                        f"value — hold the lock across the decision or "
                        f"re-validate `{prefix}{field}` under it"))
        return out

    def _region_reads(self, region: ast.AST, fi: _Func, lock: LockId,
                      guarded: set) -> dict:
        """name -> (field, line) for locals assigned inside the region
        from expressions reading a guarded field."""
        out: dict = {}
        for node in ast.walk(region):
            if isinstance(node, _FUNC_NODES):
                continue
            if not isinstance(node, ast.Assign):
                continue
            fields = [f for sub in ast.walk(node.value)
                      for f in [self._field_of(sub, fi, lock)]
                      if f in guarded]
            if not fields:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = (fields[0], node.lineno)
        return out

    def _region_writes(self, region: ast.AST, fi: _Func, lock: LockId,
                       guarded: set) -> dict:
        out: dict = {}
        for node in ast.walk(region):
            if isinstance(node, _FUNC_NODES):
                continue
            targets = []
            if isinstance(node, (ast.Assign, ast.Delete)):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr in _MUTATORS):
                targets = [node.func.value]
            for t in targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                f = self._field_of(base, fi, lock)
                if f in guarded:
                    out.setdefault(f, node.lineno)
        return out

    def _revalidates(self, region: ast.AST, fi: _Func, lock: LockId,
                     fields: set) -> bool:
        """True when the region re-reads one of ``fields`` in a test
        (the double-checked pattern) before writing."""
        for node in ast.walk(region):
            if isinstance(node, (ast.If, ast.While, ast.IfExp,
                                 ast.Assert)):
                for sub in ast.walk(node.test):
                    if self._field_of(sub, fi, lock) in fields:
                        return True
        return False

    @staticmethod
    def _deciding_branch(conds, read_names: set, a_end: int, b_start: int,
                         b_node):
        """A conditional strictly after region A that tests a name bound
        from the guarded read, positioned before (or enclosing) region
        B."""
        if not read_names:
            return None
        b_ids = {id(n) for n in ast.walk(b_node)}
        for cnd in conds:
            if cnd.lineno <= a_end:
                continue
            if cnd.lineno > b_start and id(b_node) not in \
                    {id(x) for x in ast.walk(cnd)}:
                continue
            for sub in ast.walk(cnd.test):
                if isinstance(sub, ast.Name) and sub.id in read_names:
                    if id(cnd) not in b_ids:
                        return cnd
        return None


# ---------------------------------------------------------------------------
# Rule: lock-blocking
# ---------------------------------------------------------------------------

class LockBlockingRule:
    """Blocking / device-sync calls while a lock is held (module
    docstring) — interprocedural: a helper that sleeps is flagged when
    any caller path reaches it with a lock held."""

    name = "lock-blocking"
    doc = "blocking or device-sync call while holding a lock"

    def check(self, ctx: LintContext) -> list[Finding]:
        model = get_model(ctx)
        flagged: dict = {}
        for fi, line, call, held, origin in model.blocking:
            key = (fi.pf.rel, line)
            locks = ", ".join(sorted(_short_lock(h) for h in held))
            via = (f" (reached via {origin})"
                   if origin and not origin.startswith("a lock-free")
                   else "")
            flagged.setdefault(key, Finding(
                self.name, fi.pf.rel, line,
                f"blocking call `{call}` in `{fi.qual}` runs while "
                f"holding {locks}{via}: every contending thread stalls "
                "for its full duration (and a wait under a lock can "
                "complete a deadlock) — move it outside the critical "
                "section"))
        return list(flagged.values())
