"""Abstract interpretation over jaxprs: the shared IR traversal + the
numerics-provenance lattice behind dbxcert (:mod:`.certify`) and the
kernel-hygiene rule (:mod:`.jaxpr_rules`).

Every distributed guarantee in this repo — content-addressed dispatch,
journal replay reproducing digests, carry-append parity,
substrate-vs-substrate equivalence — reduces to a numerics contract that
used to live as prose ("selection-only => bit-identical", "one
association boundary", "f32 sums of exact small ints merge bit-exactly").
This module makes those contracts *computable*: one walk over a traced
``ClosedJaxpr`` assigns every variable an :class:`AbsVal` and propagates
it through all primitives, including ``scan``/``while``/``cond``/``pjit``
sub-jaxprs (loop carries to a fixpoint).

Provenance classes, ordered by :data:`CLASS_NAMES` (join = max):

- **exact** — no float accumulation on the value path: data movement,
  elementwise float arithmetic in a fixed op order, integer/bool work.
  Bit-identical given bit-identical inputs, on any substrate.
- **selection** — float data reaches the value only through comparison
  operands, select/where predicates, gather/scatter indices, or
  ``sign``-style discretizers: the magnitude is drawn from a discrete
  set, so reassociating substrates cannot move it (the compose/latch
  position machines). The boundary census below still records the
  knife-edge exposure of its *predicates*.
- **int-exact** — f32 accumulation of provably integer-valued summands
  (bool casts, positions in {-1,0,1}, their abs/diffs): f32 integer
  sums associate exactly (within the documented |sum| < 2^24 head-room),
  so splits/merges are bit-exact in any order.
- **float-accum** — real f32 accumulation; every accumulation *site* on
  the dependency cone is counted into the boundary census (below).
- **nondet** — order-nondeterministic even for a fixed program and
  inputs: scatter-add with possibly-duplicate indices, unordered
  cross-replica psums. Never admissible on a digest path.

Association-boundary census: the ``sites`` set names every
accumulation site on a value's dependency cone —

- reassociating reduction primitives (``reduce_sum``/``cumsum``/
  ``dot_general``/``reduce_window_sum``/...),
- ``add`` equations whose two operands share float lineage (the
  Hillis–Steele shift-doubling ladders ``ops.fused._cumsum_last`` /
  ``_cumsum0`` and the blocked equity carries are *structural*
  reassociations with no reduce primitive — an add of two partial
  results of the same stream is a summation-tree merge),
- loop carries updated arithmetically from themselves (scan/while
  equations whose carry-out depends on carry-in through float
  arithmetic — the "scan-carry site" of the certified contract).

``len(sites)`` is the *boundary count* pinned per output in
``numerics.contract.json``; a kernel edit that silently adds (or drops)
an association boundary changes the count and fails the drift gate with
the introducing equation chain (:attr:`AbsVal.chain`, built from jaxpr
``source_info``).

Weak-type provenance: ``weak`` mirrors the aval's ``weak_type`` and
:attr:`AbsVal.weak_chain` records the introducing equation chain — the
same chain discipline as class escalations, replacing a bare "output is
weakly typed" flag with the path that produced it.

The traversal is also the single walker for kernel hygiene: host
callbacks, f64/c128 avals and nondet primitives anywhere in the nested
program are collected on the :class:`Analysis` result (one walk, N
rules).
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Provenance classes, join = max over this order.
EXACT, SELECTION, INT_EXACT, FLOAT_ACCUM, NONDET = range(5)
CLASS_NAMES = ("exact", "selection", "int-exact", "float-accum", "nondet")

_MAX_CHAIN = 6          # provenance frames kept per value (first + recent)
_MAX_CONST_CHECK = 4096  # integrality check cap for baked const arrays
_LOOP_FIXPOINT_CAP = 8   # lattice height is small; this is a safety net

# Host round-trips inside traced programs (kernel-hygiene vocabulary).
CALLBACK_PRIMS = {
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call",
}

# Reassociating accumulation primitives: one census site each. reduce_max
# and friends are deliberately absent — min/max/and/or return one of
# their operands bitwise, so evaluation order cannot move the result.
_REDUCE_SITE_PRIMS = {
    "reduce_sum", "reduce_prod", "cumsum", "cumprod", "cumlogsumexp",
    "dot_general", "conv_general_dilated", "reduce_window_sum",
}
# Sum-shaped reductions stay exact when every summand is integer-valued.
_INT_EXACT_REDUCES = {"reduce_sum", "cumsum", "dot_general", "add_any"}

# Order-nondeterministic primitives (fixed program + inputs can still
# produce different bits run to run).
_NONDET_PRIMS = {
    "scatter-add", "scatter_add", "scatter-mul", "scatter_mul",
    "psum", "psum2", "all_reduce", "reduce_scatter",
}

_CMP_PRIMS = {"lt", "le", "gt", "ge", "eq", "ne", "is_finite"}
# Discretizers: float in, discrete value out — selection edges like
# comparisons (the magnitude left standing is a member of a fixed set).
_SIGN_PRIMS = {"sign"}
_ARG_REDUCES = {"argmax", "argmin"}

# Pure data movement / value selection: integral-preserving and no
# arithmetic applied to lineage.
_MOVE_PRIMS = {
    "reshape", "broadcast_in_dim", "transpose", "concatenate", "squeeze",
    "expand_dims", "rev", "slice", "dynamic_slice", "dynamic_update_slice",
    "pad", "copy", "copy_p", "stop_gradient", "reduce_precision", "gather",
    "select_n", "max", "min", "reduce_max", "reduce_min", "reduce_and",
    "reduce_or", "cummax", "cummin", "clamp", "device_put", "iota",
    "split", "real", "imag",
}
# Arithmetic that maps integer-valued operands to integer values
# (nextafter is deliberately absent: nextafter(2.0, 3.0) is 2.0000002).
_INT_PRESERVING_ARITH = {
    "add", "sub", "mul", "neg", "abs", "rem", "add_any",
    "floor", "ceil", "round", "sort",
}
# Index-like operand positions (selection edges) per primitive: data
# operands are listed; everything else is an index/predicate.
_VALUE_OPERANDS = {
    "select_n": None,           # special-cased (pred + cases)
    "gather": (0,),
    "dynamic_slice": (0,),
    "dynamic_update_slice": (0, 1),
    "scatter": (0, 2),
    "scatter-add": (0, 2),
    "scatter_add": (0, 2),
    "scatter-mul": (0, 2),
    "scatter_mul": (0, 2),
    "take": (0,),
    "take_along_axis": (0,),
}


# Primitives with dedicated first-order transfer rules: a helper jaxpr
# in their params (scatter's update_jaxpr, sort comparators) must not
# divert them onto the generic operand-join fallback.
_CLASSIFIED_PRIMS = (_CMP_PRIMS | _SIGN_PRIMS | _ARG_REDUCES
                     | _NONDET_PRIMS | _REDUCE_SITE_PRIMS
                     | set(_VALUE_OPERANDS))


@dataclasses.dataclass(frozen=True)
class AbsVal:
    """Lattice value of one jaxpr variable.

    ``lineage`` holds float-source tokens reachable on the *value* path
    (cut at comparisons/discretizers and index/predicate edges);
    ``alineage`` is the subset that crossed at least one float arithmetic
    op — the self-overlap test for structural reassociation and for
    arithmetic loop carries. ``sites`` is the full-cone association
    census (flows through every edge, including predicates: a selection
    output's census is its knife-edge exposure)."""

    dtype: str = ""
    weak: bool = False
    cls: int = EXACT
    integral: bool = False
    lineage: frozenset = frozenset()
    alineage: frozenset = frozenset()
    sites: frozenset = frozenset()
    chain: tuple = ()
    weak_chain: tuple = ()

    @property
    def class_name(self) -> str:
        return CLASS_NAMES[self.cls]

    @property
    def boundaries(self) -> int:
        return len(self.sites)


@dataclasses.dataclass
class Analysis:
    """One-walk result over a ClosedJaxpr: per-output lattice values plus
    the kernel-hygiene collections (callbacks, f64 leaks, nondet sites)
    gathered on the same traversal."""

    out_vals: list
    callbacks: list          # [(prim, frame)] — deduped by prim name
    f64: list                # [(dtype, prim, frame)] — first site only
    nondet_sites: list       # [(prim, frame)] — deduped by equation site
    n_eqns: int = 0

    _callback_names: set = dataclasses.field(default_factory=set)
    _nondet_seen: set = dataclasses.field(default_factory=set)


def _dtype_integral(dtype: str) -> bool:
    return dtype.startswith(("int", "uint", "bool"))


def _is_float(dtype: str) -> bool:
    return dtype.startswith(("float", "bfloat", "complex"))


def _value_integral(value) -> bool:
    """True when a baked value is provably integer-valued (small arrays
    only — a huge table is conservatively non-integral)."""
    try:
        a = np.asarray(value)
    except Exception:
        return False
    if a.size == 0 or a.size > _MAX_CONST_CHECK:
        return False
    if a.dtype.kind in "biu":
        return True
    if a.dtype.kind != "f":
        return False
    finite = np.isfinite(a)
    return bool(np.all(finite) and np.all(a == np.round(a)))


def _frame(eqn) -> str:
    """``file:line (fn)`` of the equation's user source, best-effort."""
    try:
        from jax._src import source_info_util

        fr = source_info_util.user_frame(eqn.source_info)
        if fr is not None:
            return (f"{fr.file_name}:{fr.start_line} "
                    f"({fr.function_name})")
    except Exception:
        pass
    return "?"


def _cap_chain(chain: tuple) -> tuple:
    if len(chain) <= _MAX_CHAIN:
        return chain
    return chain[:1] + chain[-(_MAX_CHAIN - 1):]


def _join(vals, *, dtype: str, weak: bool) -> AbsVal:
    """Plain value-edge join: class max, integral and, set unions."""
    cls = EXACT
    integral = True
    lineage = frozenset()
    alineage = frozenset()
    sites = frozenset()
    chain: tuple = ()
    weak_chain: tuple = ()
    for v in vals:
        if v.cls > cls:
            cls, chain = v.cls, v.chain
        integral = integral and v.integral
        lineage |= v.lineage
        alineage |= v.alineage
        sites |= v.sites
        if v.weak and not weak_chain:
            weak_chain = v.weak_chain
    return AbsVal(dtype=dtype, weak=weak, cls=cls, integral=integral,
                  lineage=lineage, alineage=alineage, sites=sites,
                  chain=chain, weak_chain=weak_chain)


def _aval_info(aval) -> tuple:
    return (str(getattr(aval, "dtype", "")),
            bool(getattr(aval, "weak_type", False)))


def _atom_val(atom, env):
    if hasattr(atom, "val"):        # Literal
        dtype, weak = _aval_info(atom.aval)
        return AbsVal(dtype=dtype, weak=weak,
                      integral=_dtype_integral(dtype)
                      or _value_integral(atom.val))
    return env[atom]


def _const_val(var, value) -> AbsVal:
    """Baked consts are bit-fixed — exact, no lineage token."""
    dtype, weak = _aval_info(var.aval)
    return AbsVal(dtype=dtype, weak=weak,
                  integral=_dtype_integral(dtype) or _value_integral(value))


def _input_val(aval, token, *, integral: bool | None = None) -> AbsVal:
    dtype, weak = _aval_info(aval)
    if integral is None:
        integral = _dtype_integral(dtype)
    lineage = frozenset({token}) if _is_float(dtype) else frozenset()
    return AbsVal(dtype=dtype, weak=weak, integral=bool(integral),
                  lineage=lineage)


def _selection_contrib(v: AbsVal) -> int:
    """Class a predicate/index operand contributes through a selection
    edge: nondet taints across (a nondet selector makes the selected
    value nondet across runs), everything else launders to selection
    when float data is actually involved."""
    if v.cls == NONDET:
        return NONDET
    if v.lineage or v.cls > EXACT:
        return SELECTION
    return EXACT


def _weak_of(out_aval, invals, fr) -> tuple:
    """(weak, weak_chain) for one produced value: an outvar weak with no
    weak operand is an introduction site; otherwise the chain is
    inherited from the first weak operand. ``fr`` is the lazy frame
    thunk — source_info resolution only happens on the weak path."""
    dtype, weak = _aval_info(out_aval)
    del dtype
    if not weak:
        return False, ()
    for v in invals:
        if v.weak:
            return True, _cap_chain(v.weak_chain + (fr(),))
    return True, (fr(),)


# ---------------------------------------------------------------------------
# The shared traversal (also the kernel-hygiene walker)
# ---------------------------------------------------------------------------

def as_jaxprs(v) -> list:
    """Jaxprs nested in an arbitrary eqn param value (ClosedJaxpr,
    Jaxpr, or containers thereof) — the generic-discovery half of the
    old kernel-hygiene walker, now the single shared implementation."""
    out = []
    if hasattr(v, "jaxpr"):            # ClosedJaxpr
        out.append(v.jaxpr)
    elif hasattr(v, "eqns"):           # Jaxpr
        out.append(v)
    elif isinstance(v, (tuple, list)):
        for item in v:
            out.extend(as_jaxprs(item))
    return out


def analyze(closed, *, integral_inputs=None) -> Analysis:
    """Analyze a ``ClosedJaxpr``: returns per-output :class:`AbsVal`s
    plus the hygiene collections. ``integral_inputs`` optionally marks
    flattened inputs (by position) as provably integer-valued — the
    carry contract's hints (e.g. ``pos_last`` in {-1,0,1})."""
    jaxpr = closed.jaxpr
    an = Analysis(out_vals=[], callbacks=[], f64=[], nondet_sites=[])
    const_vals = [_const_val(v, c)
                  for v, c in zip(jaxpr.constvars, closed.consts)]
    in_vals = []
    for i, v in enumerate(jaxpr.invars):
        hint = None
        if integral_inputs is not None and i < len(integral_inputs) \
                and integral_inputs[i]:
            hint = True
        in_vals.append(_input_val(v.aval, ("in", i), integral=hint))
    an.out_vals = _eval_jaxpr(jaxpr, const_vals, in_vals, "", an)
    return an


def _eval_jaxpr(jaxpr, const_vals, in_vals, path: str, an: Analysis):
    env: dict = {}
    for v, val in zip(jaxpr.constvars, const_vals):
        env[v] = val
    for v, val in zip(jaxpr.invars, in_vals):
        env[v] = val
    for i, eqn in enumerate(jaxpr.eqns):
        an.n_eqns += 1
        site = f"{path}{i}"
        invals = [_atom_val(a, env) for a in eqn.invars]
        outs = _transfer(eqn, invals, site, an)
        for v, val in zip(eqn.outvars, outs):
            env[v] = val
    return [_atom_val(a, env) for a in jaxpr.outvars]


def _sub_const_vals(sub) -> list:
    """Const seeds for a nested jaxpr: ClosedJaxpr consts carry values
    (integrality checkable); bare Jaxpr constvars seed exact."""
    if hasattr(sub, "consts"):
        return [_const_val(v, c)
                for v, c in zip(sub.jaxpr.constvars, sub.consts)]
    return [AbsVal(dtype=_aval_info(v.aval)[0],
                   integral=_dtype_integral(_aval_info(v.aval)[0]))
            for v in sub.constvars]


def _inner(sub):
    return sub.jaxpr if hasattr(sub, "jaxpr") else sub


def _transfer(eqn, invals, site: str, an: Analysis) -> list:
    prim = eqn.primitive.name
    frame = None

    def fr():
        nonlocal frame
        if frame is None:
            frame = f"{prim} @ {_frame(eqn)}"
        return frame

    # Hygiene collections ride the same walk regardless of class logic.
    if prim in CALLBACK_PRIMS and prim not in an._callback_names:
        an._callback_names.add(prim)
        an.callbacks.append((prim, fr()))
    if not an.f64:
        for v in eqn.outvars:
            dt = str(getattr(getattr(v, "aval", None), "dtype", ""))
            if dt in ("float64", "complex128"):
                an.f64.append((dt, prim, fr()))
                break

    # Higher-order primitives with precise sub-jaxpr semantics.
    if prim == "scan":
        return _transfer_scan(eqn, invals, site, an, fr)
    if prim == "while":
        return _transfer_while(eqn, invals, site, an, fr)
    if prim == "cond":
        return _transfer_cond(eqn, invals, site, an)
    sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr") \
        or eqn.params.get("fun_jaxpr")
    if sub is not None and hasattr(_inner(sub), "eqns") \
            and len(_inner(sub).invars) == len(invals) \
            and len(_inner(sub).outvars) == len(eqn.outvars):
        outs = _eval_jaxpr(_inner(sub), _sub_const_vals(sub), invals,
                           site + ".", an)
        # Re-stamp dtype/weak from the call's own outvars (pjit can
        # weaken/strengthen at the boundary).
        return [dataclasses.replace(
                    o, dtype=_aval_info(v.aval)[0],
                    weak=_aval_info(v.aval)[1],
                    weak_chain=(o.weak_chain or ((fr(),)
                                if _aval_info(v.aval)[1] else ())))
                for o, v in zip(outs, eqn.outvars)]

    # Generic sub-jaxpr discovery (pallas kernels, custom calls with
    # mismatched arity, helper jaxprs like scatter's update_jaxpr): walk
    # them for hygiene findings always; classified first-order prims then
    # proceed to their own transfer, everything else falls back to an
    # operand join — imprecise but safe (certified cones never hit it).
    nested = as_jaxprs(list(eqn.params.values()))
    if nested:
        for k, sj in enumerate(nested):
            seeds = [AbsVal(dtype=_aval_info(v.aval)[0],
                            integral=_dtype_integral(
                                _aval_info(v.aval)[0]))
                     for v in sj.invars]
            consts = [AbsVal(dtype=_aval_info(v.aval)[0])
                      for v in sj.constvars]
            _eval_jaxpr(sj, consts, seeds, f"{site}.g{k}.", an)
        if prim not in _CLASSIFIED_PRIMS:
            return [_join(invals, dtype=_aval_info(v.aval)[0],
                          weak=_aval_info(v.aval)[1])
                    for v in eqn.outvars]

    return _transfer_first_order(eqn, prim, invals, site, an, fr)


def _transfer_first_order(eqn, prim, invals, site, an, fr) -> list:
    outs = []
    for v in eqn.outvars:
        dtype, weak_aval = _aval_info(v.aval)
        weak, weak_chain = _weak_of(v.aval, invals, fr)
        del weak_aval
        all_sites = frozenset().union(*(x.sites for x in invals)) \
            if invals else frozenset()

        if prim in _CMP_PRIMS or prim in _SIGN_PRIMS \
                or prim in _ARG_REDUCES:
            cls = max([_selection_contrib(x) for x in invals],
                      default=EXACT)
            chain = ()
            for x in invals:
                if x.cls == NONDET:
                    chain = x.chain
                    break
            outs.append(AbsVal(dtype=dtype, weak=weak, cls=cls,
                               integral=True, sites=all_sites,
                               chain=chain, weak_chain=weak_chain))
            continue

        if prim in _NONDET_PRIMS:
            value_ix = _VALUE_OPERANDS.get(prim)
            data = ([invals[i] for i in value_ix if i < len(invals)]
                    if value_ix else list(invals))
            base = _join(data, dtype=dtype, weak=weak)
            if _is_float(dtype) and not base.integral:
                # Dedup by equation site: loop bodies re-evaluate under
                # the fixpoint iteration (same site string every pass).
                if site not in an._nondet_seen:
                    an._nondet_seen.add(site)
                    an.nondet_sites.append((prim, fr()))
                outs.append(dataclasses.replace(
                    base, cls=NONDET, sites=all_sites,
                    chain=_cap_chain(base.chain + (fr(),)),
                    weak_chain=weak_chain))
            else:
                cls = max(base.cls,
                          INT_EXACT if _is_float(dtype) else EXACT)
                outs.append(dataclasses.replace(
                    base, cls=cls, sites=all_sites,
                    weak_chain=weak_chain))
            continue

        if prim in _REDUCE_SITE_PRIMS:
            base = _join(invals, dtype=dtype, weak=weak)
            if not _is_float(dtype):
                outs.append(dataclasses.replace(base, sites=all_sites,
                                                weak_chain=weak_chain))
            elif base.integral and prim in _INT_EXACT_REDUCES:
                outs.append(dataclasses.replace(
                    base, cls=max(base.cls, INT_EXACT), sites=all_sites,
                    alineage=base.alineage | base.lineage,
                    weak_chain=weak_chain))
            else:
                outs.append(dataclasses.replace(
                    base, cls=max(base.cls, FLOAT_ACCUM),
                    integral=False,
                    sites=all_sites | {f"{site}:{prim}"},
                    alineage=base.alineage | base.lineage,
                    chain=_cap_chain(base.chain + (fr(),)),
                    weak_chain=weak_chain))
            continue

        if prim == "select_n":
            pred, cases = invals[0], invals[1:]
            base = _join(cases, dtype=dtype, weak=weak)
            cls = max(base.cls, _selection_contrib(pred))
            outs.append(dataclasses.replace(
                base, cls=cls, sites=all_sites, weak_chain=weak_chain))
            continue

        value_ix = _VALUE_OPERANDS.get(prim)
        if value_ix is not None:
            data = [invals[i] for i in value_ix if i < len(invals)]
            idx = [x for i, x in enumerate(invals) if i not in value_ix]
            base = _join(data, dtype=dtype, weak=weak)
            cls = max([base.cls] + [_selection_contrib(x) for x in idx])
            outs.append(dataclasses.replace(
                base, cls=cls, sites=all_sites, weak_chain=weak_chain))
            continue

        # Default: value join. Moves preserve integrality and apply no
        # arithmetic; arithmetic marks every lineage token arith-crossed
        # and an `add` of overlapping float lineages is a structural
        # reassociation site (summation-tree merge).
        base = _join(invals, dtype=dtype, weak=weak)
        if _dtype_integral(dtype):
            integral = True
        elif prim in _MOVE_PRIMS or prim == "convert_element_type":
            integral = base.integral
        elif prim in _INT_PRESERVING_ARITH:
            integral = base.integral
        else:
            integral = False
        alineage = base.alineage
        sites = all_sites
        cls = base.cls
        chain = base.chain
        if prim not in _MOVE_PRIMS and prim != "convert_element_type" \
                and _is_float(dtype):
            alineage = alineage | base.lineage
            if prim in ("add", "add_any") and len(invals) == 2 \
                    and not integral \
                    and (invals[0].lineage & invals[1].lineage):
                sites = sites | {f"{site}:{prim}"}
                cls = max(cls, FLOAT_ACCUM)
                # Every counted site joins the chain: a census change's
                # introducing equation must be reportable even when the
                # class was already float-accum.
                chain = _cap_chain(chain + (fr(),))
        outs.append(AbsVal(dtype=dtype, weak=weak, cls=cls,
                           integral=integral, lineage=base.lineage,
                           alineage=alineage, sites=sites, chain=chain,
                           weak_chain=weak_chain))
    return outs


def _strip_tokens(v: AbsVal, tokens: frozenset) -> AbsVal:
    if not (v.lineage & tokens or v.alineage & tokens):
        return v
    return dataclasses.replace(v, lineage=v.lineage - tokens,
                               alineage=v.alineage - tokens)


def _loop_carry(body, body_const_vals, const_invals, init_vals, xs_vals,
                site: str, an: Analysis, fr, *, n_carry: int):
    """Shared scan/while carry analysis: taint each carry slot, iterate
    the body to a fixpoint, then classify arithmetic self-dependence
    (carry-out depending on carry-in through float arithmetic) as one
    association site per slot — the scan-carry census entry."""
    taints = [frozenset({("carry", site, j)}) for j in range(n_carry)]
    all_taints = frozenset().union(*taints) if taints else frozenset()
    carry = list(init_vals)
    raw = carry
    for _ in range(_LOOP_FIXPOINT_CAP):
        seeded = [dataclasses.replace(c, lineage=c.lineage | taints[j])
                  for j, c in enumerate(carry)]
        out = _eval_jaxpr(body, body_const_vals,
                          const_invals + seeded + xs_vals,
                          site + ".", an)
        raw = out[:n_carry]
        merged = [_join([carry[j], _strip_tokens(raw[j], all_taints)],
                        dtype=carry[j].dtype, weak=carry[j].weak
                        or raw[j].weak)
                  for j in range(n_carry)]
        if merged == carry:
            break
        carry = merged
    # Arithmetic self-dependence => accumulation across iterations.
    final = []
    for j in range(n_carry):
        c = carry[j]
        if taints[j] & raw[j].alineage and _is_float(c.dtype):
            if c.integral:
                c = dataclasses.replace(c, cls=max(c.cls, INT_EXACT))
            else:
                c = dataclasses.replace(
                    c, cls=max(c.cls, FLOAT_ACCUM),
                    sites=c.sites | {f"{site}#carry{j}"},
                    chain=_cap_chain(c.chain + (fr(),)))
        final.append(c)
    return final, all_taints


def _transfer_scan(eqn, invals, site, an, fr) -> list:
    p = eqn.params
    body = p["jaxpr"]
    n_c, n_carry = p["num_consts"], p["num_carry"]
    const_invals = invals[:n_c]
    init_vals = invals[n_c:n_c + n_carry]
    xs_vals = invals[n_c + n_carry:]
    body_consts = _sub_const_vals(body)
    carry, all_taints = _loop_carry(
        _inner(body), body_consts, const_invals, init_vals, xs_vals,
        site, an, fr, n_carry=n_carry)
    # Final pass with the settled carries to produce the ys.
    out = _eval_jaxpr(_inner(body), body_consts,
                      const_invals + carry + xs_vals, site + ".", an)
    result = []
    for j, v in enumerate(eqn.outvars):
        dtype, weak = _aval_info(v.aval)
        if j < n_carry:
            val = _join([carry[j], _strip_tokens(out[j], all_taints)],
                        dtype=dtype, weak=weak)
        else:
            val = dataclasses.replace(_strip_tokens(out[j], all_taints),
                                      dtype=dtype, weak=weak)
        result.append(val)
    return result


def _transfer_while(eqn, invals, site, an, fr) -> list:
    p = eqn.params
    cond, body = p["cond_jaxpr"], p["body_jaxpr"]
    n_cc, n_bc = p["cond_nconsts"], p["body_nconsts"]
    cond_consts = invals[:n_cc]
    body_consts_in = invals[n_cc:n_cc + n_bc]
    init_vals = invals[n_cc + n_bc:]
    carry, all_taints = _loop_carry(
        _inner(body), _sub_const_vals(body), body_consts_in, init_vals,
        [], site, an, fr, n_carry=len(init_vals))
    # The trip count itself is data-dependent through the cond: every
    # carry output takes the cond predicate's selection contribution.
    cond_out = _eval_jaxpr(_inner(cond), _sub_const_vals(cond),
                           cond_consts + carry, site + ".c", an)
    pred = _selection_contrib(cond_out[0]) if cond_out else EXACT
    result = []
    for j, v in enumerate(eqn.outvars):
        dtype, weak = _aval_info(v.aval)
        c = _strip_tokens(carry[j], all_taints)
        result.append(dataclasses.replace(
            c, dtype=dtype, weak=weak, cls=max(c.cls, pred),
            sites=c.sites | (cond_out[0].sites if cond_out
                             else frozenset())))
    return result


def _transfer_cond(eqn, invals, site, an) -> list:
    branches = eqn.params["branches"]
    index, operands = invals[0], invals[1:]
    per_branch = []
    for k, br in enumerate(branches):
        per_branch.append(_eval_jaxpr(
            _inner(br), _sub_const_vals(br), operands,
            f"{site}.b{k}.", an))
    idx_contrib = _selection_contrib(index)
    result = []
    for j, v in enumerate(eqn.outvars):
        dtype, weak = _aval_info(v.aval)
        val = _join([bo[j] for bo in per_branch], dtype=dtype, weak=weak)
        result.append(dataclasses.replace(
            val, cls=max(val.cls, idx_contrib),
            sites=val.sites | index.sites))
    return result
