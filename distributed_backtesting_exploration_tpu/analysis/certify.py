"""dbxcert — the jaxpr dataflow numerics certifier.

The repo's numerics contracts ("selection-only => bit-identical across
substrates", "one association boundary", "f32 sums of exact small ints
merge bit-exactly", "scenario digests are pure functions of the spec")
used to live as DESIGN.md prose enforced by sampled parity tests; the two
weak-type escapes shipped so far were found by runtime probes after
manual hunting. This module machine-checks them, the proto-drift pattern
applied to numerics:

1. **Trace** every certified cone: all registered streaming families
   (= the fused families' scan/recurrent duals) × epilogue substrates
   (``scan:8``/``ladder``) × both streaming forms (``build_carry`` /
   ``append_step``, the scan-form/recurrent-form pair that must not
   drift) plus the digest-relevant cones (scenario synthesis, wire
   splice).
2. **Analyze** each trace with :mod:`.dataflow`: every labeled output
   gets a provenance class (exact / selection / int-exact / float-accum
   / nondet), an association-boundary census, and weak-type provenance.
3. **Pin** the result as a CANONICAL machine-readable table — sorted
   keys, no timestamps — committed as ``numerics.contract.json`` at the
   repo root, and **diff** it in CI: a kernel edit that silently adds an
   association boundary, drops a selection-only guarantee, or introduces
   a nondet primitive into a digest path fails the gate with the
   introducing equation chain.

Ships as three dbxlint rules on the shared engine —

- ``substrate-contract``: live classes/census vs the committed table
  (any mismatch, missing row, or new row is a drift finding),
- ``weak-type-provenance``: weak-typed outputs on certified cones,
  reported with the introducing equation chain,
- ``digest-determinism``: no nondet primitive/class on a digest cone;
  the splice cone must stay pure data movement (*exact*, zero census)

— plus the ``dbxcert`` CLI / ``dbxlint --certify`` mode (exit 0 clean,
1 findings, 2 contract drift; ``--update`` regenerates the table).
Suppressions use the standard inline dbxlint directive at the finding's
anchor line (the chain's introducing equation).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import re
import sys
import time

from . import dataflow
from .core import Finding, LintContext

CONTRACT_BASENAME = "numerics.contract.json"
SCHEMA = 1
# "scan:8" pins the production multi-block carry chain (a bare "scan"
# re-blocks to one block in interpret mode); "ladder" is the full-length
# fallback substrate — the same pair kernel-hygiene sweeps.
SUBSTRATES = ("scan:8", "ladder")
FORMS = ("build_carry", "append_step")
DIGEST_KEYS = ("digest/scenario_synth", "digest/scenario_fused",
               "digest/splice")

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def contract_path() -> str:
    """Committed contract table location: ``DBX_CONTRACT_PATH`` override,
    else ``numerics.contract.json`` at the repo root (the package dir's
    parent — beside pyproject, like the proto contract beside its pb2)."""
    override = os.environ.get("DBX_CONTRACT_PATH")
    if override:
        return override
    return os.path.join(os.path.dirname(_PKG_DIR), CONTRACT_BASENAME)


def row_key(family: str, substrate: str, form: str) -> str:
    return f"{family}@{substrate}#{form}"


@dataclasses.dataclass
class RowResult:
    """One certified cone: the contract-table row plus the reporting
    detail (lattice values with chains) that never enters the canonical
    bytes — chains carry file:line and would churn the table."""

    key: str
    outputs: dict        # label -> {"class","boundaries","dtype","weak"}
    vals: dict           # label -> dataflow.AbsVal
    nondet: list         # [(prim, frame)]
    wall_s: float = 0.0


def _key_name(entry) -> str:
    for attr in ("key", "idx", "name"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def certify_callable(key: str, fn, args, integral_keys=frozenset()
                     ) -> RowResult:
    """Trace ``fn(*args)`` and classify every labeled output. ``fn`` must
    return a dict (stable labels for the table); ``integral_keys`` names
    input-dict keys the analyzer may assume integer-valued."""
    import jax
    from jax import tree_util as jtu

    t0 = time.perf_counter()
    closed, shapes = jax.make_jaxpr(fn, return_shape=True)(*args)
    out_paths = jtu.tree_flatten_with_path(shapes)[0]
    labels = ["/".join(_key_name(p) for p in path)
              for path, _ in out_paths]
    in_paths = jtu.tree_flatten_with_path(tuple(args))[0]
    integral_inputs = [bool(path) and _key_name(path[-1]) in integral_keys
                      for path, _ in in_paths]
    an = dataflow.analyze(closed, integral_inputs=integral_inputs)
    if len(labels) != len(an.out_vals):
        raise AssertionError(
            f"{key}: {len(labels)} labels vs {len(an.out_vals)} outputs")
    outputs = {}
    vals = {}
    for label, v in zip(labels, an.out_vals):
        outputs[label] = {"class": v.class_name,
                          "boundaries": v.boundaries,
                          "dtype": v.dtype, "weak": bool(v.weak)}
        vals[label] = v
    return RowResult(key=key, outputs=outputs, vals=vals,
                     nondet=list(an.nondet_sites),
                     wall_s=time.perf_counter() - t0)


def stream_families() -> list:
    from ..streaming import recurrent

    return sorted(recurrent._STREAM_FAMILIES)


def streaming_row(family: str, substrate: str, form: str) -> RowResult:
    from ..streaming import recurrent

    fn, args, integral_keys = recurrent.certify_probe(
        family, form=form, epilogue=substrate)
    return certify_callable(row_key(family, substrate, form), fn, args,
                            integral_keys)


def digest_rows() -> list:
    from ..ops import fused
    from ..scenarios import synth
    from ..utils import data as data_mod

    rows = []
    for key, probe in (("digest/scenario_synth", synth.certify_probe),
                       ("digest/scenario_fused",
                        fused.scenario_certify_probe),
                       ("digest/splice", data_mod.splice_cone_probe)):
        fn, args, integral_keys = probe()
        rows.append(certify_callable(key, fn, args, integral_keys))
    return rows


def timed_rows(families=None) -> tuple:
    """``(rows, walls)``: every certified row plus per-family certifier
    wall seconds (probe build + trace + analysis; the bench's
    ``certify_wall_s`` instrument). ``families=None`` = the full
    registry; digest cones always run, timed under ``"digest"``."""
    rows = {}
    walls = {}
    for family in (families if families is not None
                   else stream_families()):
        t0 = time.perf_counter()
        for substrate in SUBSTRATES:
            for form in FORMS:
                r = streaming_row(family, substrate, form)
                rows[r.key] = r
        walls[family] = time.perf_counter() - t0
    t0 = time.perf_counter()
    for r in digest_rows():
        rows[r.key] = r
    walls["digest"] = time.perf_counter() - t0
    return rows, walls


def build_rows(families=None) -> dict:
    return timed_rows(families)[0]


_CACHE: dict = {}


def cached_rows() -> dict:
    """The full certified row set, computed once per process — the three
    certify rules, the CI gate and the CLI all share one trace pass."""
    if "rows" not in _CACHE:
        _CACHE["rows"] = build_rows()
    return _CACHE["rows"]


def clear_cache() -> None:
    _CACHE.clear()


# ---------------------------------------------------------------------------
# Canonical table + drift diff
# ---------------------------------------------------------------------------

def table_from_rows(rows: dict) -> dict:
    return {"schema": SCHEMA,
            "rows": {k: {"outputs": rows[k].outputs}
                     for k in sorted(rows)}}


def canonical_bytes(table: dict) -> bytes:
    """THE byte form of the committed table: sorted keys, fixed
    separators, trailing newline, no timestamps — identical traces must
    produce identical bytes across runs and processes."""
    return (json.dumps(table, sort_keys=True, indent=1,
                       separators=(",", ": ")) + "\n").encode()


def load_contract(path: str | None = None) -> dict | None:
    """Committed table, or ``None`` when MISSING. An unreadable/corrupt
    table raises ``ValueError`` — it must never be conflated with
    missing, or the "run --update" advice would overwrite the only
    record of what was pinned, silently re-baselining real drift."""
    path = path or contract_path()
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
    except FileNotFoundError:
        return None
    except OSError as e:     # exists but unreadable (perms, a directory)
        raise ValueError(f"{path}: {e}") from None
    try:
        return json.loads(raw.decode("utf-8"))
    except ValueError as e:
        raise ValueError(f"{path}: {e}") from None


def _fmt_chain(chain: tuple) -> str:
    return " -> ".join(chain) if chain else "(no chain recorded)"


_FRAME_RE = re.compile(r"@ (.+?):(\d+)")


def anchor_of(chain: tuple) -> tuple:
    """``(relpath, line)`` of the chain's introducing equation when it
    points inside the package; ``(None, 0)`` otherwise. Findings anchor
    here so the standard inline suppression directive applies at the
    equation that introduced the property."""
    for frame in chain:
        m = _FRAME_RE.search(frame)
        if not m:
            continue
        path, line = m.group(1), int(m.group(2))
        if os.path.isabs(path) and path.startswith(_PKG_DIR + os.sep):
            return os.path.relpath(path, _PKG_DIR), line
    return None, 0


def diff_rows(committed: dict, rows: dict, *, full: bool = False) -> list:
    """Structural diff of live ``rows`` against the ``committed`` table.
    Each entry: row key, output label, field, was/now, and (for
    escalations) the live introducing equation chain. ``full`` also
    reports committed rows the live trace no longer produces and live
    rows the table does not pin."""
    out = []
    pinned = committed.get("rows", {})
    for key in sorted(rows):
        live = rows[key]
        if key not in pinned:
            out.append({"row": key, "output": None, "field": "row",
                        "was": None, "now": "present", "chain": (),
                        "message": f"row `{key}` is not pinned by the "
                                   f"committed contract table"})
            continue
        want = pinned[key].get("outputs", {})
        for label in sorted(set(want) | set(live.outputs)):
            if label not in live.outputs:
                out.append({"row": key, "output": label,
                            "field": "output", "was": "present",
                            "now": None, "chain": (),
                            "message": f"{key}: output `{label}` pinned "
                                       f"by the contract is gone"})
                continue
            now = live.outputs[label]
            if label not in want:
                out.append({"row": key, "output": label,
                            "field": "output", "was": None,
                            "now": "present",
                            "chain": live.vals[label].chain,
                            "message": f"{key}: output `{label}` is not "
                                       f"pinned by the contract"})
                continue
            pin = want[label]
            for field in ("class", "boundaries", "dtype", "weak"):
                if pin.get(field) != now.get(field):
                    v = live.vals[label]
                    chain = (v.weak_chain if field == "weak"
                             else v.chain)
                    out.append({
                        "row": key, "output": label, "field": field,
                        "was": pin.get(field), "now": now.get(field),
                        "chain": chain,
                        "message": (
                            f"{key}: output `{label}` {field} "
                            f"{pin.get(field)!r} -> {now.get(field)!r}"
                            f" — introduced by: {_fmt_chain(chain)}")})
    if full:
        for key in sorted(set(pinned) - set(rows)):
            out.append({"row": key, "output": None, "field": "row",
                        "was": "present", "now": None, "chain": (),
                        "message": f"committed contract row `{key}` is "
                                   f"no longer produced by the certifier"})
    return out


# ---------------------------------------------------------------------------
# The three dbxlint rules (shared engine, shared trace pass)
# ---------------------------------------------------------------------------

class _CertifyRule:
    def applicable(self, ctx: LintContext) -> bool:
        # Like kernel-hygiene: the certified registries belong to the
        # installed package — an arbitrary lint target has none, and the
        # engine reports the rule as skipped rather than silently clean.
        return ctx.package

    def _anchored(self, rule: str, chain: tuple, message: str,
                  ctx: LintContext) -> Finding:
        path, line = anchor_of(chain)
        if path is None:
            path = os.path.relpath(contract_path(), ctx.root)
            line = 1
        return Finding(rule, path, line, message)


class SubstrateContractRule(_CertifyRule):
    """Diff the live certified classes/census against the committed
    ``numerics.contract.json`` — the proto-drift pattern for numerics."""

    name = "substrate-contract"
    doc = ("certified provenance classes + association-boundary census "
           "vs the committed numerics.contract.json")

    def check(self, ctx: LintContext) -> list:
        if not self.applicable(ctx):
            return []
        rel = os.path.relpath(contract_path(), ctx.root)
        try:
            committed = load_contract()
        except ValueError as e:
            return [Finding(self.name, rel, 1,
                            f"committed numerics contract table is "
                            f"unparseable ({e}) — restore it from git "
                            "history before touching `--update` (a "
                            "regenerate would silently re-baseline any "
                            "real drift)")]
        if committed is None:
            return [Finding(self.name, rel, 1,
                            "no committed numerics contract table at "
                            f"{contract_path()} — run `dbxcert --update` "
                            "(or `dbxlint --certify --update-contract`) "
                            "and commit the result")]
        findings = []
        for d in diff_rows(committed, cached_rows(), full=True):
            findings.append(self._anchored(self.name, d["chain"],
                                           d["message"], ctx))
        return findings


class WeakTypeProvenanceRule(_CertifyRule):
    """Weak-typed outputs on certified cones, with the introducing
    equation chain (kernel-hygiene's bare flag, upgraded: the chain
    names the Python-scalar promotion that escaped)."""

    name = "weak-type-provenance"
    doc = ("weak-typed outputs on certified cones, reported with the "
           "introducing equation chain")

    def check(self, ctx: LintContext) -> list:
        if not self.applicable(ctx):
            return []
        findings = []
        for key in sorted(cached_rows()):
            row = cached_rows()[key]
            for label in sorted(row.outputs):
                if not row.outputs[label]["weak"]:
                    continue
                v = row.vals[label]
                findings.append(self._anchored(
                    self.name, v.weak_chain,
                    f"{key}: output `{label}` is weakly typed — "
                    f"introduced by: {_fmt_chain(v.weak_chain)}; anchor "
                    f"the dtype with an explicit jnp.float32 cast", ctx))
        return findings


class DigestDeterminismRule(_CertifyRule):
    """Digest-relevant cones must stay deterministic: no nondet
    primitive/class anywhere, and the wire-splice cone must remain pure
    data movement (class *exact*, zero boundary census) — the property
    that makes replayed chains reproduce the digests the first run
    stamped."""

    name = "digest-determinism"
    doc = ("nondet primitives/classes on digest-relevant cones; splice "
           "must stay pure data movement")

    def check(self, ctx: LintContext) -> list:
        if not self.applicable(ctx):
            return []
        findings = []
        rows = cached_rows()
        for key in DIGEST_KEYS:
            row = rows.get(key)
            if row is None:
                findings.append(self._anchored(
                    self.name, (),
                    f"digest cone `{key}` was not certified — its probe "
                    f"failed to build or is unregistered", ctx))
                continue
            for prim, frame in row.nondet:
                findings.append(self._anchored(
                    self.name, (frame,),
                    f"{key}: nondeterministic primitive `{prim}` on a "
                    f"digest path ({frame}) — content addresses would "
                    f"stop being pure functions of the spec", ctx))
            for label in sorted(row.outputs):
                rec = row.outputs[label]
                v = row.vals[label]
                if rec["class"] == "nondet":
                    findings.append(self._anchored(
                        self.name, v.chain,
                        f"{key}: output `{label}` is nondet-class — "
                        f"introduced by: {_fmt_chain(v.chain)}", ctx))
                elif key == "digest/splice" and (
                        rec["class"] != "exact" or rec["boundaries"]):
                    findings.append(self._anchored(
                        self.name, v.chain,
                        f"{key}: output `{label}` is "
                        f"{rec['class']}/{rec['boundaries']} boundaries "
                        f"— the splice must stay pure data movement "
                        f"(introduced by: {_fmt_chain(v.chain)})", ctx))
        return findings


def certify_rules() -> list:
    return [SubstrateContractRule(), WeakTypeProvenanceRule(),
            DigestDeterminismRule()]


# ---------------------------------------------------------------------------
# CLI (`dbxcert`, also `dbxlint --certify`)
# ---------------------------------------------------------------------------

def run_certify(*, update: bool = False) -> dict:
    """Run the certifier over the package: regenerate the table (written
    to the committed path when ``update``), run the three certify rules
    with standard suppressions, split drift (substrate-contract) from
    semantic findings. Exit-code contract: 0 clean / 1 findings /
    2 table drift."""
    from . import core

    if update:
        data = canonical_bytes(table_from_rows(cached_rows()))
        with open(contract_path(), "wb") as fh:
            fh.write(data)
    findings, suppressed, _ctx = core.lint_path(_PKG_DIR, certify_rules())
    drift = [f for f in findings if f.rule == SubstrateContractRule.name]
    other = [f for f in findings if f.rule != SubstrateContractRule.name]
    return {
        "contract": contract_path(),
        "rows": len(cached_rows()),
        "updated": bool(update),
        "drift": [dataclasses.asdict(f) for f in drift],
        "findings": [dataclasses.asdict(f) for f in other],
        "suppressed": suppressed,
    }


def exit_code(result: dict) -> int:
    if result["drift"]:
        return 2
    if result["findings"]:
        return 1
    return 0


def render_text(result: dict, *, prog: str = "dbxcert") -> None:
    """THE text rendering of a ``run_certify`` result — shared by the
    ``dbxcert`` script and ``dbxlint --certify`` so the two documented
    entry points to the same machinery cannot drift apart."""
    for f in result["drift"] + result["findings"]:
        print(f"{f['path']}:{f['line']}: [{f['rule']}] {f['message']}")
    state = ("drift" if result["drift"]
             else "findings" if result["findings"] else "clean")
    tail = f" ({result['suppressed']} suppressed)" \
        if result["suppressed"] else ""
    print(f"{prog}: {state} — {result['rows']} certified rows vs "
          f"{result['contract']}"
          f"{' (updated)' if result['updated'] else ''}{tail}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dbxcert",
        description="jaxpr dataflow numerics certifier: machine-checked "
                    "bit-exactness contracts, weak-type provenance, and "
                    "digest-determinism audit (exit 0 clean / 1 findings "
                    "/ 2 contract drift)")
    ap.add_argument("--update", "-u", action="store_true",
                    help="regenerate numerics.contract.json from the "
                         "live trace (then commit it)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)
    result = run_certify(update=args.update)
    if args.format == "json":
        print(json.dumps(result, indent=2))
    else:
        render_text(result)
    return exit_code(result)


if __name__ == "__main__":
    sys.exit(main())
