"""Runtime lockdep: the dynamic twin of :mod:`.locks` (``DBX_LOCKDEP=1``).

Static analysis sees every PATH; it cannot see which paths actually run,
and it cannot see locks that meet only through dynamic dispatch. This
module records what the fleet's threads really do, Linux-lockdep style:

- ``install()`` replaces ``threading.Lock``/``RLock`` with factories
  that wrap locks CREATED FROM THIS PACKAGE's modules in an
  instrumented shim (the creating frame's ``__name__`` decides — third
  party and stdlib locks pass through untouched, so gRPC/jax/logging
  internals cost nothing and cannot pollute the tables). A lock's
  *class* is its creation site (``module.Class:line``) — all instances
  of one site share one node in the order graph, exactly the
  granularity the static rules reason at;
- every **blocking** acquire taken while other instrumented locks are
  held records an acquisition-order edge into a bounded table
  (``DBX_LOCKDEP_MAX_EDGES``, default 4096; overflow is counted, never
  silently dropped). A new edge that closes a cycle in the class graph
  is an ``order-cycle`` violation — reported at the first offending
  acquire, BEFORE any thread actually deadlocks. Non-blocking
  (``acquire(False)``) probes record nothing: a trylock cannot
  deadlock. Re-acquiring the same *instance* of a plain Lock is a
  ``self-deadlock`` violation (reported, then the real acquire is
  allowed to proceed — surfacing the hang's cause is the job);
- blocking calls are sanitized too: ``time.sleep``,
  ``jax.block_until_ready`` and ``concurrent.futures.Future.result``
  are patched (plus gRPC's unary-unary client call, best-effort) to
  flag a ``blocking`` violation when the calling thread holds any
  instrumented lock — the runtime form of the ``lock-blocking`` rule;
- per-lock-class **held durations** (max/total/count) accumulate in a
  bounded table keyed by the same creation sites.

Findings land on three surfaces: the obs JSONL event log
(``lockdep_violation`` events), the metrics registry
(``dbx_lockdep_edges`` gauge, ``dbx_lockdep_violations_total{kind}``)
and :func:`report` (what the tests assert on).

**Exemptions** (``_EXEMPT_MODULES``): locks created by ``obs.registry``
and ``obs.events`` stay raw. Every counter increment and every JSONL
line takes one of those locks — instrumenting them would put a
metrics-path edge under every lock in the package (self-edges flooding
the table from lockdep's OWN reporting), and a Counter/Gauge lock is a
two-instruction leaf by construction.

Zero cost when off: nothing is patched until :func:`install` runs, and
``uninstall()`` restores every patched symbol (already-created shims
keep working but stop recording — ``_active`` gates every hook).

**Coverage boundary**: only locks created AFTER install are wrapped.
Instance locks are — the queue/store/cache locks all construct inside
``main()``/fixtures, after the hook — but the package's few
MODULE-LEVEL locks (``sched.tenancy._BUCKET_LOCK``, ``obs.trace``'s
ring locks, ``runtime._core._lock``) are created at import, which
precedes every install hook, and stay raw. Those are exactly the locks
the STATIC rules see best (module locks need no type inference), so
the division of labor is deliberate: static analysis owns module-level
orderings, lockdep owns the instance locks dynamic dispatch hides.

The tier-1 in-process gRPC integration fixtures run under the shim via
the ``tests/conftest.py`` env hook, so every dispatcher/worker test
doubles as a race harness; ``bench.py`` records the ``direct_dispatch``
floor with the shim on so its overhead is a tracked number.
"""

from __future__ import annotations

import os
import sys
import threading
import time

PACKAGE_PREFIX = "distributed_backtesting_exploration_tpu"

# Modules whose locks stay raw (module docstring): the metrics/event
# reporting path itself.
_EXEMPT_MODULES = ("obs.registry", "obs.events")

_DEFAULT_MAX_EDGES = 4096
_MAX_VIOLATIONS = 256

# Real factories/functions captured at import, before any patching.
_RealLock = threading.Lock
_RealRLock = threading.RLock
_real_sleep = time.sleep

_active = False
_installed = False
_saved: dict = {}

# Model-checker seam (analysis/modelcheck): when set, every instrumented
# lock crossing reports to the controlled scheduler — ``hook("acquire",
# key)`` BEFORE a blocking acquire (the preemption point: the scheduler
# may park this thread and run another), ``hook("acquired", key)`` after
# the acquire succeeds and ``hook("release", key)`` after the release
# (ownership tracking — the scheduler must never switch to a thread that
# would block on a parked thread's lock). None (the default) costs one
# global read per crossing.
_schedule_hook = None


def set_schedule_hook(fn) -> None:
    """Install (or with None, remove) the controlled-scheduler hook."""
    global _schedule_hook
    _schedule_hook = fn


def max_edges() -> int:
    return int(os.environ.get("DBX_LOCKDEP_MAX_EDGES",
                              _DEFAULT_MAX_EDGES))


def enabled() -> bool:
    """The ``DBX_LOCKDEP`` opt-in knob (read lazily, never at import)."""
    return os.environ.get("DBX_LOCKDEP") == "1"


class _State:
    """All lockdep bookkeeping, guarded by one RAW lock."""

    def __init__(self):
        self.lock = _RealLock()
        self.edges: dict = {}          # (a, b) -> count
        self.adj: dict = {}            # a -> set of b
        self.edge_sites: dict = {}     # (a, b) -> first (thread, when)
        self.dropped_edges = 0
        self.violations: list = []     # bounded list of dicts
        self.violation_keys: set = set()
        self.held_stats: dict = {}     # class -> [count, total_s, max_s]
        self.local = threading.local()  # .held = [(class, instance, t0)]


_state = _State()
_counters: dict = {}


def _held(create: bool = True):
    held = getattr(_state.local, "held", None)
    if held is None:
        if not create:
            return []
        held = _state.local.held = []
    return held


def _class_key_from_frame(depth: int) -> str | None:
    """Creation-site lock class for the factory caller, or None when the
    creator is not this package (or is exempt)."""
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return None
    mod = frame.f_globals.get("__name__", "")
    if not (mod == PACKAGE_PREFIX or mod.startswith(PACKAGE_PREFIX + ".")):
        return None
    short = mod[len(PACKAGE_PREFIX):].lstrip(".") or "<pkg>"
    if short in _EXEMPT_MODULES:
        return None
    owner = frame.f_locals.get("self")
    cls = type(owner).__name__ if owner is not None else None
    return (f"{short}.{cls}:{frame.f_lineno}" if cls
            else f"{short}:{frame.f_lineno}")


def _record_violation(kind: str, **detail) -> None:
    key = (kind, tuple(sorted(str(v) for v in detail.values())))
    with _state.lock:
        if key in _state.violation_keys:
            return
        _state.violation_keys.add(key)
        if len(_state.violations) < _MAX_VIOLATIONS:
            _state.violations.append(
                {"kind": kind, "thread": threading.current_thread().name,
                 **detail})
    c = _counters.get(kind)
    if c is not None:
        c.inc()
    try:
        from .. import obs

        obs.events.emit("lockdep_violation", kind=kind, **detail)
    except Exception:
        pass   # reporting must never take the process down
    try:
        from ..obs import flight

        # A lock-order violation is a latent-deadlock incident: capture
        # the black box while the offending acquire's context is still
        # in the ring. The DEFERRED path is mandatory here — this hook
        # runs at the acquire site with the offending locks held, so a
        # plain trigger() would add the recorder's own lock to the
        # order graph being reported.
        flight.trigger_deferred("lockdep", subject=kind, **detail)
    except Exception:
        pass


def _find_path(src: str, dst: str) -> list | None:
    """DFS over the class adjacency for a path src -> dst (caller holds
    ``_state.lock``)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _state.adj.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _before_blocking_acquire(lock: "_LockdepLock") -> None:
    held = _held(create=False)
    if not held:
        return
    for hcls, hobj, _t0 in held:
        if hobj is lock and not lock._reentrant:
            _record_violation("self-deadlock", lock=lock.key)
            continue
        if hobj is lock:
            continue
        key = (hcls, lock.key)
        with _state.lock:
            n = _state.edges.get(key)
            if n is not None:
                _state.edges[key] = n + 1
                continue
            if len(_state.edges) >= max_edges():
                _state.dropped_edges += 1
                continue
            # New edge: does the REVERSE direction already have a path?
            cycle = _find_path(lock.key, hcls)
            _state.edges[key] = 1
            _state.adj.setdefault(hcls, set()).add(lock.key)
            _state.adj.setdefault(lock.key, set())
        if cycle is not None:
            _record_violation(
                "order-cycle",
                path=" -> ".join([hcls] + cycle),
                acquiring=lock.key, holding=hcls)


def _push(lock: "_LockdepLock") -> None:
    _held().append((lock.key, lock, time.monotonic()))


def _pop(lock: "_LockdepLock") -> None:
    held = _held(create=False)
    for i in range(len(held) - 1, -1, -1):
        if held[i][1] is lock:
            _cls, _obj, t0 = held.pop(i)
            dur = time.monotonic() - t0
            with _state.lock:
                s = _state.held_stats.setdefault(lock.key, [0, 0.0, 0.0])
                s[0] += 1
                s[1] += dur
                if dur > s[2]:
                    s[2] = dur
            return


class _LockdepLock:
    """Instrumented wrapper around one real lock instance."""

    __slots__ = ("_lock", "key", "_reentrant")

    def __init__(self, real, key: str, reentrant: bool):
        self._lock = real
        self.key = key
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if _active and blocking:
            if _schedule_hook is not None:
                _schedule_hook("acquire", self.key)
            _before_blocking_acquire(self)
        ok = self._lock.acquire(blocking, timeout)
        if ok and _active:
            _push(self)
            if blocking and _schedule_hook is not None:
                _schedule_hook("acquired", self.key)
        return ok

    def release(self):
        self._lock.release()
        if _active:
            _pop(self)
            if _schedule_hook is not None:
                _schedule_hook("release", self.key)

    def locked(self):
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        # Condition/queue interop: delegate anything else to the real
        # lock (and let AttributeError propagate for probes like
        # _release_save so callers take their documented fallbacks).
        return getattr(self._lock, name)


def _lock_factory():
    if not _active:
        return _RealLock()
    key = _class_key_from_frame(2)
    if key is None:
        return _RealLock()
    return _LockdepLock(_RealLock(), key, reentrant=False)


def _rlock_factory():
    if not _active:
        return _RealRLock()
    key = _class_key_from_frame(2)
    if key is None:
        return _RealRLock()
    return _LockdepLock(_RealRLock(), key, reentrant=True)


def check_blocking(what: str) -> None:
    """Flag a ``blocking`` violation when the calling thread holds any
    instrumented lock — the public hook the patched blocking calls (and
    any subsystem wanting explicit coverage) funnel through."""
    if not _active:
        return
    held = _held(create=False)
    if held:
        _record_violation(
            "blocking", call=what,
            locks=", ".join(sorted({h[0] for h in held})))


def _sleep_patched(secs):
    check_blocking("time.sleep")
    return _real_sleep(secs)


def install() -> None:
    """Patch the factories and blocking calls (idempotent). Instances
    created BEFORE install stay raw — install early (the conftest hook
    runs before any fixture constructs a queue/worker; the dispatcher
    and worker mains install before building anything)."""
    global _active, _installed
    if _installed:
        _active = True
        return
    _installed = True
    _active = True
    _saved["Lock"] = threading.Lock
    _saved["RLock"] = threading.RLock
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    _saved["sleep"] = time.sleep
    time.sleep = _sleep_patched
    try:
        import concurrent.futures as cf

        real_result = cf.Future.result
        _saved["future_result"] = real_result

        def result_patched(self, timeout=None):
            check_blocking("Future.result")
            return real_result(self, timeout)

        cf.Future.result = result_patched
    except Exception:
        pass
    jax = sys.modules.get("jax")
    if jax is not None and hasattr(jax, "block_until_ready"):
        real_bur = jax.block_until_ready
        _saved["block_until_ready"] = real_bur

        def bur_patched(x):
            check_blocking("jax.block_until_ready")
            return real_bur(x)

        jax.block_until_ready = bur_patched
    grpc_channel = sys.modules.get("grpc._channel")
    if grpc_channel is not None:
        try:
            multi = grpc_channel._UnaryUnaryMultiCallable
            real_call = multi.__call__
            _saved["grpc_call"] = (multi, real_call)

            def grpc_patched(self, *a, **k):
                check_blocking("grpc.unary_unary")
                return real_call(self, *a, **k)

            multi.__call__ = grpc_patched
        except AttributeError:
            pass   # private API moved: gRPC coverage is best-effort
    _register_metrics()


def _register_metrics() -> None:
    try:
        from .. import obs
    except Exception:
        return
    reg = obs.get_registry()
    reg.gauge_fn(
        "dbx_lockdep_edges",
        lambda: len(_state.edges),
        help="distinct lock-acquisition-order edges recorded by the "
             "runtime lockdep shim")
    for kind in ("order-cycle", "blocking", "self-deadlock"):
        _counters[kind] = reg.counter(
            "dbx_lockdep_violations_total",
            help="lockdep violations by kind (order-cycle = ABBA risk, "
                 "blocking = blocking call under a lock, self-deadlock "
                 "= plain Lock re-acquired by its holder)",
            kind=kind)


def maybe_install() -> None:
    """The env hook: install iff ``DBX_LOCKDEP=1`` (zero work otherwise)."""
    if enabled():
        install()


def uninstall() -> None:
    """Restore every patched symbol and stop recording. Shims created
    while installed keep functioning as plain locks."""
    global _active, _installed
    _active = False
    if not _installed:
        return
    _installed = False
    threading.Lock = _saved.pop("Lock", _RealLock)
    threading.RLock = _saved.pop("RLock", _RealRLock)
    time.sleep = _saved.pop("sleep", _real_sleep)
    real_result = _saved.pop("future_result", None)
    if real_result is not None:
        import concurrent.futures as cf

        cf.Future.result = real_result
    real_bur = _saved.pop("block_until_ready", None)
    if real_bur is not None:
        jax = sys.modules.get("jax")
        if jax is not None:
            jax.block_until_ready = real_bur
    grpc_saved = _saved.pop("grpc_call", None)
    if grpc_saved is not None:
        multi, real_call = grpc_saved
        multi.__call__ = real_call


def reset() -> None:
    """Clear the tables (patches stay); the test-harness seam."""
    with _state.lock:
        _state.edges.clear()
        _state.adj.clear()
        _state.edge_sites.clear()
        _state.violations.clear()
        _state.violation_keys.clear()
        _state.held_stats.clear()
        _state.dropped_edges = 0


def active() -> bool:
    return _active


def report() -> dict:
    """Snapshot: edge count, per-edge acquire counts, violations, and
    per-class held-duration stats."""
    with _state.lock:
        return {
            "edges": len(_state.edges),
            "edge_counts": {f"{a} -> {b}": n
                            for (a, b), n in sorted(_state.edges.items())},
            "dropped_edges": _state.dropped_edges,
            "violations": list(_state.violations),
            "held": {cls: {"acquires": s[0],
                           "held_total_s": round(s[1], 6),
                           "held_max_s": round(s[2], 6)}
                     for cls, s in sorted(_state.held_stats.items())},
        }
