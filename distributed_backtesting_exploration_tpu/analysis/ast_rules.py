"""dbxlint AST-layer rules.

Four single-module rules over parsed source, all sharing one scope model
(:class:`_Scope`): a tree of function-like nodes (def / async def /
lambda) with bare-name resolution walking lexically outward. Class bodies
are transparent for scoping (names defined in a class body are NOT
visible inside its methods, matching Python), but methods are still
scanned as potential roots/targets. The concurrency rules
(``lock-discipline``, ``lock-order``, ``atomicity``, ``lock-blocking``)
need a whole-package view and live in :mod:`.locks`, built on the same
scope model.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from .core import Finding, LintContext, PyFile

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal_name(node: ast.AST) -> str | None:
    """Last component of a callee expression (``jax.jit`` -> ``jit``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


@dataclasses.dataclass
class _Scope:
    """One function-like scope (or the module itself)."""

    node: ast.AST                       # Module / FunctionDef / Lambda
    parent: "_Scope | None"
    qualname: str
    defs: dict = dataclasses.field(default_factory=dict)  # name -> _Scope

    def resolve(self, name: str) -> "_Scope | None":
        scope = self
        while scope is not None:
            hit = scope.defs.get(name)
            if hit is not None:
                return hit
            scope = scope.parent
        return None

    def own_nodes(self):
        """AST nodes belonging directly to this scope — descent stops at
        nested function-like nodes (their bodies are their own scopes)."""
        stack = (list(ast.iter_child_nodes(self.node))
                 if isinstance(self.node, _FUNC_NODES + (ast.Module,))
                 else [self.node])
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, _FUNC_NODES):
                # Still yield decorators/defaults — they evaluate in THIS
                # scope — but not the nested body.
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    stack.extend(node.decorator_list)
                    stack.extend(node.args.defaults)
                    stack.extend(d for d in node.args.kw_defaults if d)
                continue
            stack.extend(ast.iter_child_nodes(node))


def _build_scopes(tree: ast.Module) -> tuple[_Scope, list[_Scope]]:
    """Scope tree + flat list of every function-like scope in the module."""
    module = _Scope(tree, None, "<module>")
    all_scopes: list[_Scope] = []

    def visit(node: ast.AST, scope: _Scope, in_class: str | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = (f"{in_class}.{child.name}" if in_class
                        else child.name)
                sub = _Scope(child, scope, qual)
                if in_class is None:
                    # Methods are not bare-name-resolvable from peers.
                    scope.defs[child.name] = sub
                all_scopes.append(sub)
                visit(child, sub, None)
            elif isinstance(child, ast.Lambda):
                sub = _Scope(child, scope, f"{scope.qualname}.<lambda>")
                all_scopes.append(sub)
                visit(child, sub, None)
            elif isinstance(child, ast.ClassDef):
                visit(child, scope, child.name)
            else:
                visit(child, scope, in_class)

    visit(tree, module, None)
    return module, all_scopes


# ---------------------------------------------------------------------------
# Rule: trace-time-env
# ---------------------------------------------------------------------------

# Callables whose function arguments are traced (executed at trace time,
# baked into the jit cache without being part of its key).
_TRACE_ENTRY_CALLS = {
    "jit", "pallas_call", "pmap", "vmap", "grad", "value_and_grad",
    "shard_map", "make_jaxpr", "eval_shape", "checkpoint", "remat", "scan",
    "while_loop", "cond",
}
_TRACE_DECORATORS = {"jit", "pmap", "pallas_call", "shard_map", "vmap"}


def _is_env_read(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return True
    if isinstance(node, ast.Call) and _terminal_name(node.func) == "getenv":
        return True
    return False


class TraceTimeEnvRule:
    """``os.environ`` reads reachable from jit/pallas-traced functions.

    An env read inside traced code executes once at trace time and is
    invisible to the jit cache key — later in-process changes silently
    reuse the stale compile (the ``DBX_LANES_CAP`` bug class, ADVICE.md
    round 5). Reachability is same-module and over-approximate: a traced
    root reaches every module/nested function it references by name.
    The fix is to read the variable host-side and thread it in as a
    static argument (``ops.fused.resolve_lanes_cap`` is the template).
    """

    name = "trace-time-env"
    doc = "os.environ read reachable from jit/pallas-traced code"

    def check(self, ctx: LintContext) -> list[Finding]:
        out: list[Finding] = []
        for pf in ctx.files:
            out.extend(self._check_file(pf))
        return out

    def _roots(self, module: _Scope, scopes: list[_Scope]) -> list[_Scope]:
        roots: list[_Scope] = []
        # (a) decorated defs: @jax.jit / @functools.partial(jax.jit, ...).
        for scope in scopes:
            deco = getattr(scope.node, "decorator_list", [])
            for d in deco:
                names = {n for sub in ast.walk(d)
                         for n in [_terminal_name(sub)] if n}
                if names & _TRACE_DECORATORS:
                    roots.append(scope)
                    break
        # (b) call-form: jax.jit(fn) / pl.pallas_call(kernel, ...) — every
        # function reference inside the call's arguments is a traced root.
        for scope in [module] + scopes:
            for node in scope.own_nodes():
                if not (isinstance(node, ast.Call)
                        and _terminal_name(node.func)
                        in _TRACE_ENTRY_CALLS):
                    continue
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Lambda):
                            hit = next((s for s in scopes
                                        if s.node is sub), None)
                            if hit:
                                roots.append(hit)
                        elif isinstance(sub, ast.Name):
                            hit = scope.resolve(sub.id)
                            if hit:
                                roots.append(hit)
        return roots

    def _check_file(self, pf: PyFile) -> list[Finding]:
        module, scopes = _build_scopes(pf.tree)
        reachable: dict[int, tuple[_Scope, str]] = {}   # id -> (scope, root)
        work = [(s, s.qualname) for s in self._roots(module, scopes)]
        while work:
            scope, root = work.pop()
            if id(scope) in reachable:
                continue
            reachable[id(scope)] = (scope, root)
            # Nested defs of a traced function execute at trace time when
            # called; include them outright (over-approximation is safe
            # here — anything inside a traced region IS trace-time code).
            for sub in scope.defs.values():
                work.append((sub, root))
            for node in scope.own_nodes():
                if isinstance(node, ast.Name):
                    hit = scope.resolve(node.id)
                    if hit is not None:
                        work.append((hit, root))
        findings: dict[tuple, Finding] = {}
        for scope, root in reachable.values():
            for node in ast.walk(scope.node):
                if _is_env_read(node):
                    key = (pf.rel, node.lineno)
                    findings.setdefault(key, Finding(
                        self.name, pf.rel, node.lineno,
                        f"os.environ read at trace time (reachable from "
                        f"traced function `{root}`); it is invisible to "
                        f"the jit cache key — read it host-side and "
                        f"thread it in as a static argument"))
        return list(findings.values())


# ---------------------------------------------------------------------------
# Shared concurrency vocabulary (the lock rules in .locks build on these)
# ---------------------------------------------------------------------------

# Method names that mutate their receiver (dict/list/set/deque surface,
# plus `put` — the ByteLRU/store API every cache level here speaks).
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "discard", "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse", "push", "push_front", "put",
}

# Device-synchronizing calls: each blocks the calling host thread until
# the accelerator drains — milliseconds to seconds on a loaded chip, an
# eternity in a gRPC handler or under a lock (the PR-9 PagePool
# scrape-stall class).
_DEVICE_SYNC = {"block_until_ready", "device_get"}

# Bounded queue/thread waits (round 14): `.get`/`.put`/`.join` with an
# explicit ``timeout=`` keyword. The keyword is the detector — it is what
# separates a queue/thread WAIT from the untimeouted `dict.get(k, d)` and
# `str.join(xs)` vocabulary that saturates ordinary code. A bounded wait
# is still a wait: in a servicer handler or under a lock it parks the
# caller exactly like a sleep of the timeout's length.
_WAIT_TERMINALS = {"get", "put", "join"}


def _is_timeout_wait(node: ast.Call, terminal: str | None) -> bool:
    """True for ``x.get(timeout=...)`` / ``x.put(..., timeout=...)`` /
    ``x.join(timeout=...)`` — the pipeline-queue wait vocabulary."""
    return (terminal in _WAIT_TERMINALS
            and any(kw.arg == "timeout" for kw in node.keywords))


def _self_attr(node: ast.AST) -> str | None:
    """``self.X`` -> ``X``."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


# ---------------------------------------------------------------------------
# Rule: import-time-config
# ---------------------------------------------------------------------------

class ImportTimeConfigRule:
    """Module-level env reads / file IO (configuration captured at import).

    Import-time capture freezes the value for the process regardless of
    later in-process changes, runs in an order the importer cannot see,
    and makes a module un-reimportable with different config (the
    ``DBX_OBS_JSONL`` import-time read this rule was cut from). Read
    config lazily at first use instead. ``if __name__ == "__main__"``
    blocks are runtime, not import time, and are exempt.
    """

    name = "import-time-config"
    doc = "module-level os.environ read or file IO"

    _IO_CALLS = {"open", "urlopen", "create_connection", "socket"}

    def check(self, ctx: LintContext) -> list[Finding]:
        out: list[Finding] = []
        for pf in ctx.files:
            for node in self._import_time_nodes(pf.tree.body):
                if _is_env_read(node):
                    out.append(Finding(
                        self.name, pf.rel, node.lineno,
                        "module-level environment read: captured once at "
                        "import, frozen for the process — read it lazily "
                        "at first use"))
                elif (isinstance(node, ast.Call)
                      and _terminal_name(node.func) in self._IO_CALLS):
                    # Terminal-name match covers the attribute spellings
                    # these calls actually use (`socket.create_connection`,
                    # `urllib.request.urlopen`), not just bare `open(...)`.
                    out.append(Finding(
                        self.name, pf.rel, node.lineno,
                        f"module-level `{_terminal_name(node.func)}(...)`: "
                        "IO at import time runs before any caller can "
                        "configure or handle it"))
        return out

    @classmethod
    def _import_time_nodes(cls, body):
        """Walk statements executed at import: module body + class bodies,
        descending through If/Try/With/loops, pruning function bodies,
        lambdas, and `if __name__ == \"__main__\"` guards."""
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.ClassDef):
                yield from cls._import_time_nodes(stmt.body)
                continue
            if isinstance(stmt, ast.If) and cls._is_main_guard(stmt.test):
                continue
            stack = [stmt]
            while stack:
                node = stack.pop()
                if isinstance(node, _FUNC_NODES):
                    continue
                if isinstance(node, ast.ClassDef):
                    yield from cls._import_time_nodes(node.body)
                    continue
                yield node
                stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _is_main_guard(test: ast.AST) -> bool:
        return (isinstance(test, ast.Compare)
                and isinstance(test.left, ast.Name)
                and test.left.id == "__name__")


# ---------------------------------------------------------------------------
# Rule: blocking-call
# ---------------------------------------------------------------------------

class BlockingCallRule:
    """Sleeps / subprocesses / device syncs inside gRPC servicer handlers
    and the worker control loop.

    A dispatcher RPC handler runs on the shared gRPC thread pool — one
    sleeping handler steals a pool slot from every worker; the worker's
    control loop owns the liveness heartbeat — a sleep there starves
    SendStatus past the dispatcher's prune window and gets a healthy
    worker pruned mid-drain (the deferred-completion redesign exists
    because exactly that happened). Device syncs
    (``jax.block_until_ready``, ``jax.device_get``) and future waits
    (``.result()``) block the same way for as long as the accelerator
    (or the producing thread) takes — compute belongs on the compute
    thread, never in a handler or the heartbeat loop. File IO is
    deliberately allowed (journal/results persistence is the handlers'
    job). The poll-tick and bounded-drain sleeps are allowlisted by
    qualname below. The "while holding a lock" variant of this class is
    its own rule (``lock-blocking``, :mod:`.locks`) fed by the
    interprocedural held-lock sets.
    """

    name = "blocking-call"
    doc = "sleep/subprocess/device-sync in a servicer or the worker loop"

    # Control-plane classes scanned in addition to *Servicer subclasses.
    _CONTROL_PLANE_CLASSES = {"Worker", "SliceWorker"}

    # qualname -> why a SLEEP there is the design, not a bug. Only `sleep`
    # is exempted in these methods; any other blocking call (subprocess,
    # input, ...) added to them is still flagged.
    _ALLOW_SLEEP = {
        "Worker.run": "the poll tick itself (bounded by poll_interval_s)",
        "Worker._shutdown": "bounded exit-budget drain wait",
        "SliceWorker.run": "follower idle tick between broadcast rounds",
        "SliceWorker._leader_loop": "leader idle tick between empty polls",
    }

    # qualname -> why a bounded QUEUE/THREAD WAIT (`.get(timeout=...)`,
    # `.put(timeout=...)`, `.join(timeout=...)`) is the design there.
    # The round-14 pipeline threads exist to wait — their handoff gets
    # are the mechanism, not a stall — and the shutdown path's bounded
    # joins are the drain budget. Anywhere else in a servicer or the
    # control loop, a timeout'd wait parks the shared thread pool or the
    # heartbeat exactly like a sleep of the same length.
    _ALLOW_QUEUE_WAIT = {
        "Worker._collect_loop":
            "the pipeline handoff wait (collector thread, not the "
            "control loop)",
        "Worker._shutdown":
            "bounded joins of the prefetch + compute pipeline at exit",
    }

    _BLOCKING_TERMINAL = {"sleep", "input", "result"} | _DEVICE_SYNC
    _BLOCKING_MODULES = {"subprocess"}

    def check(self, ctx: LintContext) -> list[Finding]:
        out: list[Finding] = []
        for pf in ctx.files:
            for node in ast.walk(pf.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                servicer = any(
                    (_dotted(b) or "").split(".")[-1].endswith("Servicer")
                    for b in node.bases)
                if not servicer and (node.name
                                     not in self._CONTROL_PLANE_CLASSES):
                    continue
                for m in node.body:
                    if not isinstance(m, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        continue
                    out.extend(self._check_method(pf, node.name, m))
        return out

    def _check_method(self, pf: PyFile, cls: str, m) -> list[Finding]:
        out = []
        qual = f"{cls}.{m.name}"
        sleep_allowed = qual in self._ALLOW_SLEEP
        wait_allowed = qual in self._ALLOW_QUEUE_WAIT
        for node in ast.walk(m):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func) or ""
            terminal = _terminal_name(node.func)
            if terminal == "sleep" and sleep_allowed:
                continue
            is_wait = _is_timeout_wait(node, terminal)
            if is_wait and wait_allowed:
                continue
            blocking = (terminal in self._BLOCKING_TERMINAL
                        or is_wait
                        or dotted.split(".")[0] in self._BLOCKING_MODULES)
            if blocking:
                out.append(Finding(
                    self.name, pf.rel, node.lineno,
                    f"blocking call `{dotted or terminal}` inside "
                    f"`{cls}.{m.name}` (gRPC handler / worker control "
                    "loop): it stalls the shared thread pool or starves "
                    "the liveness heartbeat"))
        return out


# ---------------------------------------------------------------------------
# Rule: obs-cardinality
# ---------------------------------------------------------------------------

class ObsCardinalityRule:
    """Metric label values derived from unbounded runtime data.

    Every distinct label value is a NEW time series held forever by the
    registry, carried in every ``/metrics`` scrape, every ``/stats.json``
    snapshot, every GetStats ``obs_json`` payload and every BENCH obs
    blob. A label fed from job ids, file paths, peer addresses, trace ids
    or similar unbounded runtime data therefore grows the metric surface
    without bound over a fleet run — exactly the data that belongs in
    span/event ATTRS (the JSONL log and the span ring are per-event, not
    per-series) or in a bounded label like ``method``/``pool``/``kernel``.

    Detection is lexical + one assignment hop: a label value that is (or
    is built from — f-strings, concatenation, ``str(...)``/``format``
    wrappers) an identifier matching the unbounded-data vocabulary
    (``*_id``, ``jid``, ``path``, ``addr``, ``peer``, ``trace``,
    ``tenant`` ...), or a local name assigned from one
    (``wid = self.worker_id``). Values routed through a SANCTIONED
    bounded-map constructor (``tenant_bucket(...)`` — sched.tenancy's
    first-N-then-"other" label map) are bounded by construction and not
    flagged. Bounded exceptions that are real design decisions (e.g.
    per-worker gauges whose children are removed on worker exit) carry
    an inline suppression with the justification.
    """

    name = "obs-cardinality"
    doc = "metric label value derived from unbounded runtime data"

    _METRIC_CALLS = {"counter", "gauge", "histogram", "gauge_fn"}
    # Non-label kwargs of the registry constructors.
    _SKIP_KWARGS = {"help", "buckets", "fn"}
    # Calls whose RESULT is a bounded label by construction: the tenant
    # bucket map caps distinct values at DBX_TENANT_LABEL_MAX + "other",
    # so feeding it unbounded tenant ids is the sanctioned pattern (the
    # reason per-tenant obs can exist under this rule at all); the
    # autotuner's shape_bucket clamps (T, P) onto finite power-of-two
    # rails, so per-shape-bucket obs is bounded the same way (raw dims
    # would mint one series per shape); stream_bucket is the tenant map's
    # twin for the live fan-out tier's param-block digests
    # (DBX_STREAM_LABEL_MAX sticky prefixes + "other"); worker_bucket is
    # the fleet telemetry plane's twin for worker ids — worker-chosen
    # wire strings that churn per restart (DBX_WORKER_LABEL_MAX sticky
    # names + "other"); trigger_bucket folds flight-recorder trigger
    # kinds onto the closed _KINDS vocabulary + "other" (a total map,
    # not sticky-first-N — the catalogue is a code constant).
    _SANCTIONED_CALLS = {"tenant_bucket", "shape_bucket", "stream_bucket",
                         "worker_bucket", "trigger_bucket"}
    _UNBOUNDED = re.compile(
        r"(?:^|_)(?:id|ids|jid|uid|uuid|guid|key|token|path|paths|file|"
        r"filename|dir|addr|address|peer|host|hostname|port|url|uri|"
        r"target|trace|span|digest|digests|blake2b|checksum|hash|"
        r"tenant|tenants|stream|streams|sub|subs|subscriber|subscribers|"
        r"subscription|subscriptions|"
        # Flight-recorder incident identifiers (round 17): bundle names
        # embed content digests, triggers/incidents carry job/worker
        # subjects — all unbounded; metric labels must go through
        # trigger_bucket (or stay label-free).
        r"bundle|bundles|trigger|triggers|incident|incidents|subject|"
        r"subjects|"
        # Decision-plane record fields (round 19): candidate/actual
        # worker ids and per-decision regret are unbounded runtime data
        # (worker-chosen wire strings; a float per decision) — metric
        # labels must ride the bounded route/outcome vocabularies or
        # worker_bucket, with the raw ids in the decision record itself.
        r"candidate|candidates|worker|workers|regret)(?:$|_)")

    def check(self, ctx: LintContext) -> list[Finding]:
        out: list[Finding] = []
        for pf in ctx.files:
            module, scopes = _build_scopes(pf.tree)
            for scope in [module] + scopes:
                assigns = self._scope_assigns(scope)
                for node in scope.own_nodes():
                    if not (isinstance(node, ast.Call)
                            and _terminal_name(node.func)
                            in self._METRIC_CALLS):
                        continue
                    for kw in node.keywords:
                        if kw.arg is None or kw.arg in self._SKIP_KWARGS:
                            # **splats are opaque here; the registry's own
                            # pass-through (`self.gauge(name, **labels)`)
                            # and dict-built label sets are judged at
                            # their construction site, not the splat.
                            continue
                        src = self._suspicious(kw.value, assigns)
                        if src is not None:
                            out.append(Finding(
                                self.name, pf.rel, node.lineno,
                                f"label `{kw.arg}` is fed from unbounded "
                                f"runtime data (`{src}`): every distinct "
                                "value becomes a permanent time series — "
                                "use a bounded label set, or carry the id "
                                "in span/event attrs instead"))
        return out

    @staticmethod
    def _scope_assigns(scope: _Scope) -> dict:
        """Simple ``name = expr`` bindings of this scope (last wins) —
        the one-hop alias map (`wid = self.worker_id`). ``own_nodes``
        yields in stack (reverse-source) order, so keep the binding with
        the greatest line number, not the last one yielded."""
        out: dict[str, ast.AST] = {}
        lines: dict[str, int] = {}
        for node in scope.own_nodes():
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) \
                            and node.lineno >= lines.get(t.id, -1):
                        lines[t.id] = node.lineno
                        out[t.id] = node.value
        return out

    @classmethod
    def _suspicious(cls, expr: ast.AST, assigns: dict,
                    depth: int = 0) -> str | None:
        """The offending identifier when ``expr`` derives from unbounded
        runtime data, else None. Constants are always clean; containers
        (f-strings, concatenation, str()/format calls) are scanned
        recursively; a bare local name follows ONE assignment hop."""
        if isinstance(expr, ast.Constant):
            return None
        if isinstance(expr, ast.Name):
            if cls._UNBOUNDED.search(expr.id):
                return expr.id
            if depth == 0 and expr.id in assigns:
                hit = cls._suspicious(assigns[expr.id], assigns, 1)
                if hit is not None:
                    return f"{expr.id} = {hit}"
            return None
        if isinstance(expr, ast.Attribute):
            if cls._UNBOUNDED.search(expr.attr):
                return _dotted(expr) or expr.attr
            return None
        if isinstance(expr, ast.JoinedStr):
            for v in expr.values:
                if isinstance(v, ast.FormattedValue):
                    hit = cls._suspicious(v.value, assigns, depth)
                    if hit is not None:
                        return hit
            return None
        if isinstance(expr, ast.BinOp):
            return (cls._suspicious(expr.left, assigns, depth)
                    or cls._suspicious(expr.right, assigns, depth))
        if isinstance(expr, ast.Call):
            # A sanctioned bounded-map constructor launders unbounded
            # input into a bounded label set — clean regardless of args.
            if _terminal_name(expr.func) in cls._SANCTIONED_CALLS:
                return None
            # str(x), "{}".format(x), "|".join(xs): judge the arguments.
            for a in list(expr.args) + [k.value for k in expr.keywords]:
                hit = cls._suspicious(a, assigns, depth)
                if hit is not None:
                    return hit
            return None
        return None


class JournalDisciplineRule:
    """Journaled-state mutation not preceded by its journal append.

    The dispatcher's recoverability contract is an ORDER: the publish
    side (enqueue records, `delta` chain links) journals FIRST, then
    mutates live state. A crash between the two merely re-enqueues a
    journaled-but-unpublished job; the reversed order opens a window
    where live state holds jobs (or chain links) no restart can restore
    — the exact loss dbxmc's `journal-append-first` invariant catches
    dynamically (analysis/modelcheck). This rule is the static half of
    that contract.

    Detection: within one function that BOTH appends a publish-side
    journal record (``*journal.append("enqueue" | "delta", ...)``) AND
    mutates journal-covered dispatcher state (``self._records[...]=``,
    ``self._delta_chain[...]=``, ``*._state.enqueue_n/register/``
    ``push_pending(...)``, ``*._sched.push(...)``), every such mutation
    must sit on a LATER line than the first append. Functions with no
    publish-side append (the replay/restore path, completion paths —
    where state legally leads the journal) are out of scope; reorderings
    that split across functions are dbxmc's job, not a lexical rule's.
    """

    name = "journal-discipline"
    doc = "journaled-state mutation precedes its journal append"

    _PUBLISH_EVENTS = {"enqueue", "delta"}
    _STATE_CALLS = {"enqueue_n", "register", "push_pending"}
    _MUTATED_MAPS = ("._records", "._delta_chain")

    def check(self, ctx: LintContext) -> list[Finding]:
        out: list[Finding] = []
        for pf in ctx.files:
            for fn in ast.walk(pf.tree):
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                append_line = self._first_publish_append(fn)
                if append_line is None:
                    continue
                for lineno, what in self._mutations(fn):
                    if lineno < append_line:
                        out.append(Finding(
                            self.name, pf.rel, lineno,
                            f"`{what}` mutates journal-covered state "
                            "BEFORE the publish-side journal append "
                            f"(line {append_line}): a crash in between "
                            "holds live jobs no restart can restore — "
                            "journal first, then publish"))
        return out

    @classmethod
    def _first_publish_append(cls, fn: ast.AST) -> int | None:
        first: int | None = None
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            dotted = _dotted(node.func) or ""
            if not dotted.endswith("journal.append"):
                continue
            ev = node.args[0]
            if (isinstance(ev, ast.Constant)
                    and ev.value in cls._PUBLISH_EVENTS
                    and (first is None or node.lineno < first)):
                first = node.lineno
        return first

    @classmethod
    def _mutations(cls, fn: ast.AST):
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func) or ""
                parts = dotted.split(".")
                if (len(parts) >= 3 and parts[-2] == "_state"
                        and parts[-1] in cls._STATE_CALLS):
                    yield node.lineno, dotted
                elif dotted.endswith("._sched.push"):
                    yield node.lineno, dotted
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        base = _dotted(t.value) or ""
                        if base.endswith(cls._MUTATED_MAPS):
                            yield node.lineno, f"{base}[...] ="
