"""dbxlint engine: findings, the rule registry, suppressions, file loading.

A *rule* is a plain object with ``name``, ``doc`` and
``check(ctx) -> list[Finding]``. Rules are registered in ``all_rules()``
(import-cycle-free: the rule modules import this one, not vice versa at
import time). The engine is deliberately dependency-free — stdlib ``ast``
plus, for the jaxpr layer only, a lazy jax import inside the rule.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import tokenize

PACKAGE_NAME = "distributed_backtesting_exploration_tpu"

# Inline suppression directive: `# dbxlint: disable=<rule>[,<rule>...]`,
# placed on the finding's line or on a comment line directly above it.
# Policy (enforced by review, not the engine): always follow the directive
# with `-- <justification>`.
_DIRECTIVE = "dbxlint: disable="


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a file:line."""

    rule: str
    path: str       # relative to the linted root
    line: int       # 1-indexed
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass
class PyFile:
    """A parsed Python source file (shared by every AST rule)."""

    path: str       # absolute
    rel: str        # relative to the linted root
    source: str
    tree: ast.Module

    @property
    def lines(self) -> list[str]:
        return self.source.splitlines()


@dataclasses.dataclass
class LintContext:
    """Everything a rule may look at for one lint invocation."""

    root: str                 # absolute root (dir or single file)
    files: list[PyFile]
    package: bool = False     # True when root IS the dbx package itself
    skipped: list[tuple[str, str]] = dataclasses.field(default_factory=list)
    # Filled by lint_path: rule names that ran vs. were not applicable to
    # this root (e.g. kernel-hygiene outside the package) — "skipped" must
    # never masquerade as "clean".
    rules_ran: list[str] = dataclasses.field(default_factory=list)
    rules_skipped: list[str] = dataclasses.field(default_factory=list)


def _iter_py_files(root: str):
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__" and not d.startswith("."))
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def load_context(root: str) -> LintContext:
    """Parse every ``.py`` under ``root`` (unparseable files are recorded
    in ``ctx.skipped``, never silently dropped — a syntax error in a lint
    target is itself a finding-worthy event the CLI surfaces)."""
    root = os.path.abspath(root)
    base = os.path.dirname(root) if os.path.isfile(root) else root
    ctx = LintContext(root=root, files=[],
                      package=os.path.basename(root) == PACKAGE_NAME)
    for path in _iter_py_files(root):
        try:
            with tokenize.open(path) as fh:   # honors coding cookies
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            ctx.skipped.append((os.path.relpath(path, base), str(e)))
            continue
        ctx.files.append(PyFile(path=path, rel=os.path.relpath(path, base),
                                source=source, tree=tree))
    return ctx


def _suppressed_rules(comment_text: str) -> set[str]:
    """Rule names named by a directive in ``comment_text`` (empty = none).

    Grammar: ``disable=<rule>[, <rule>...] [-- justification]`` — spaces
    after commas are fine; the ``--`` (or the first non-rule word) ends
    the list, so prose never suppresses by accident."""
    pos = comment_text.find(_DIRECTIVE)
    if pos < 0:
        return set()
    spec = comment_text[pos + len(_DIRECTIVE):].split("--", 1)[0]
    rules: set[str] = set()
    for part in spec.split(","):
        tokens = part.strip().split()
        if not tokens:
            break
        rules.add(tokens[0])
        if len(tokens) > 1:      # prose after a rule name: list is over
            break
    return rules


def _py_comments(source: str) -> dict[int, str] | None:
    """1-indexed line -> COMMENT token text, via the real tokenizer — a
    directive inside a string literal must never count (None = untokenizable,
    caller falls back to the line-tail heuristic)."""
    import io

    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return None
    return out


def _line_tail_comment(line: str) -> str:
    """Comment tail of a non-Python line (``# ...`` or proto ``// ...``);
    best-effort — non-Python sources have no tokenizer here."""
    for marker in ("#", "//"):
        pos = line.find(marker)
        if pos >= 0:
            return line[pos:]
    return ""


def apply_suppressions(findings: list[Finding], root: str,
                       ctx: "LintContext | None" = None
                       ) -> tuple[list[Finding], int]:
    """Drop findings suppressed by an inline directive in a COMMENT on the
    finding's line or on a comment-only line directly above. Returns
    ``(kept, n_suppressed)``. Python sources come from ``ctx`` (already in
    memory, decoded once by the tokenizer-aware loader) and are scanned at
    the token level; other files (``.proto``) fall back to a line-tail
    scan."""
    root = os.path.abspath(root)
    base = os.path.dirname(root) if os.path.isfile(root) else root
    by_rel = {pf.rel: pf for pf in (ctx.files if ctx is not None else [])}
    line_cache: dict[str, list[str]] = {}
    comment_cache: dict[str, dict[int, str] | None] = {}
    kept: list[Finding] = []
    suppressed = 0

    def load_lines(path: str, rel: str) -> list[str]:
        lines = line_cache.get(path)
        if lines is None:
            pf = by_rel.get(rel)
            if pf is not None:
                lines = pf.lines
            else:
                try:
                    with open(path, encoding="utf-8",
                              errors="replace") as fh:
                        lines = fh.read().splitlines()
                except OSError:
                    lines = []
            line_cache[path] = lines
        return lines

    def comment_at(path: str, rel: str, lines: list[str], lineno: int) -> str:
        if not (0 < lineno <= len(lines)):
            return ""
        if rel.endswith(".py"):
            comments = comment_cache.get(path, False)
            if comments is False:
                pf = by_rel.get(rel)
                source = pf.source if pf is not None else "\n".join(lines)
                comments = _py_comments(source)
                comment_cache[path] = comments
            if comments is not None:
                return comments.get(lineno, "")
            # untokenizable: fall through to the heuristic
        return _line_tail_comment(lines[lineno - 1])

    for f in findings:
        path = os.path.join(base, f.path)
        lines = load_lines(path, f.path)
        rules = set(_suppressed_rules(comment_at(path, f.path, lines,
                                                 f.line)))
        above = lines[f.line - 2] if 2 <= f.line <= len(lines) + 1 else ""
        if above.lstrip().startswith(("#", "//")):
            rules |= _suppressed_rules(comment_at(path, f.path, lines,
                                                  f.line - 1))
        if f.rule in rules or "all" in rules:
            suppressed += 1
        else:
            kept.append(f)
    return kept, suppressed


def all_rules() -> list:
    """The registered rule set, in catalogue order."""
    from . import ast_rules, certify, jaxpr_rules, locks, proto_rules

    return [
        ast_rules.TraceTimeEnvRule(),
        locks.LockDisciplineRule(),
        locks.LockOrderRule(),
        locks.AtomicityRule(),
        locks.LockBlockingRule(),
        ast_rules.ImportTimeConfigRule(),
        ast_rules.BlockingCallRule(),
        ast_rules.ObsCardinalityRule(),
        ast_rules.JournalDisciplineRule(),
        jaxpr_rules.KernelHygieneRule(),
        certify.SubstrateContractRule(),
        certify.WeakTypeProvenanceRule(),
        certify.DigestDeterminismRule(),
        proto_rules.ProtoDriftRule(),
    ]


def lint_path(root: str, rules=None) -> tuple[list[Finding], int, LintContext]:
    """Run ``rules`` (default: all) over ``root``. Returns
    ``(findings, n_suppressed, ctx)`` with findings sorted by location;
    ``ctx.rules_ran``/``ctx.rules_skipped`` record applicability (a rule
    whose ``applicable(ctx)`` is False is skipped and reported as such)."""
    ctx = load_context(root)
    findings: list[Finding] = []
    for rule in (rules if rules is not None else all_rules()):
        if not getattr(rule, "applicable", lambda _ctx: True)(ctx):
            ctx.rules_skipped.append(rule.name)
            continue
        ctx.rules_ran.append(rule.name)
        findings.extend(rule.check(ctx))
    findings, suppressed = apply_suppressions(findings, root, ctx)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, suppressed, ctx
