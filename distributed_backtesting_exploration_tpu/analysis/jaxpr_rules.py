"""dbxlint jaxpr/IR-layer rule: kernel hygiene for the fused sweeps.

The AST layer sees source; this layer sees what jax will actually compile.
Every strategy registered in ``rpc.compute.JaxSweepBackend._FUSED_STRATEGIES``
is traced with ``jax.make_jaxpr`` over tiny synthetic inputs and the full
(nested) jaxpr is walked for:

- **host callbacks** (``pure_callback`` / ``io_callback`` /
  ``debug_callback``): a host round-trip inside a fused kernel defeats the
  whole VMEM-resident design and deadlocks under some collectives;
- **float64 leaks**: every kernel is float32 by contract (f64 either
  crashes Mosaic or silently doubles VMEM pressure); any f64/c128 aval in
  any equation is flagged;
- **weak-type escapes**: a weakly-typed *output* means a Python-scalar
  promotion reached the public Metrics contract — downstream dtype now
  depends on a constant's Python type, the classic silent-promotion trap.

Tracing is shape-polymorphic work only (no compile, no device); the whole
registry traces in a few seconds on CPU.

The jaxpr walk itself lives in :mod:`.dataflow` (one traversal, N rules):
``check_traced`` consumes the :class:`.dataflow.Analysis` the abstract
interpreter produces — callbacks/f64 ride the same walk dbxcert uses for
provenance classes, and weak-type findings now carry the introducing
equation chain instead of a bare flag.
"""

from __future__ import annotations

import inspect
import os

import numpy as np

from . import dataflow
from .core import Finding, LintContext

# One representative value per grid-axis name used across the fused
# registry (windows/periods must be small integral bar counts; MACD/TRIX
# need fast < slow).
_AXIS_VALUES = {
    "fast": [2.0], "slow": [5.0], "window": [3.0], "k": [1.0],
    "lookback": [2.0], "period": [3.0], "band": [20.0], "signal": [2.0],
    "span": [2.0],
}
_T_BARS = 32


def _tiny_inputs(fields: tuple) -> list[np.ndarray]:
    """One-ticker OHLCV-ish panel, ``(1, _T_BARS)`` float32 per field."""
    t = np.arange(1, _T_BARS + 1, dtype=np.float32)
    close = 100.0 + np.sin(t) + 0.01 * t
    by_name = {
        "close": close,
        "high": close * 1.01,
        "low": close * 0.99,
        "open": close,
        "volume": np.full(_T_BARS, 1e4, np.float32),
    }
    return [by_name[f][None, :].astype(np.float32) for f in fields]


def check_traced(name: str, fn, args, *, path: str = "?",
                 line: int = 0) -> list[Finding]:
    """Trace ``fn(*args)`` and lint the jaxpr. ``name`` labels findings;
    ``path``/``line`` anchor them (the kernel's def site). The walk is
    :func:`dataflow.analyze` — the same single traversal dbxcert rides —
    so callbacks, f64 leaks and weak-type provenance all come from one
    pass over the nested program."""
    import jax

    rule = KernelHygieneRule.name
    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # a kernel that fails to even trace is finding #0
        return [Finding(rule, path, line,
                        f"kernel `{name}` failed to trace: {e!r}")]
    an = dataflow.analyze(closed)
    findings: list[Finding] = []
    for prim, _frame in an.callbacks:
        findings.append(Finding(
            rule, path, line,
            f"kernel `{name}`: host callback `{prim}` in the "
            "traced program — a host round-trip inside a fused "
            "kernel defeats the VMEM-resident design"))
    for dt, prim, _frame in an.f64[:1]:
        findings.append(Finding(
            rule, path, line,
            f"kernel `{name}`: {dt} value produced by "
            f"`{prim}` — the fused kernels are float32 "
            "by contract (f64 blows VMEM budgets and "
            "Mosaic lowering)"))
    for i, aval in enumerate(closed.out_avals):
        dt = str(getattr(aval, "dtype", ""))
        if dt and dt != "float32":
            findings.append(Finding(
                rule, path, line,
                f"kernel `{name}`: output {i} is {dt}, not float32 — "
                "the Metrics wire contract is float32"))
        elif getattr(aval, "weak_type", False):
            chain = an.out_vals[i].weak_chain if i < len(an.out_vals) \
                else ()
            via = (f" (provenance: {' -> '.join(chain)})" if chain
                   else "")
            findings.append(Finding(
                rule, path, line,
                f"kernel `{name}`: output {i} is weakly typed — a "
                "Python-scalar promotion escaped the kernel; anchor the "
                f"dtype with an explicit jnp.float32 cast{via}"))
    return findings


class KernelHygieneRule:
    """Trace every registered fused kernel; flag callbacks/f64/weak types."""

    name = "kernel-hygiene"
    doc = "host callbacks, float64 leaks, weak-type escapes in fused kernels"

    def applicable(self, ctx: LintContext) -> bool:
        # The kernel registry belongs to the installed package; linting an
        # arbitrary directory (fixtures) has no registry to trace — the
        # engine reports the rule as skipped rather than silently "clean".
        return ctx.package

    def check(self, ctx: LintContext) -> list[Finding]:
        if not self.applicable(ctx):
            return []
        findings: list[Finding] = []
        # BOTH epilogue substrates trace: the default single-pass carry
        # scan (T-block loop with carry state — new scratch/carry code
        # must not leak f64 or weak types) AND the ladder fallback, which
        # otherwise only runs when an operator flips DBX_EPILOGUE and
        # would rot unlinted. The env var is the same host-side knob the
        # public wrappers resolve per call, so setting it between traces
        # selects the substrate.
        prior = os.environ.get("DBX_EPILOGUE")
        try:
            # "scan:8" pins the production T-block size: a bare "scan"
            # re-blocks to one block in interpret mode (CPU lint boxes),
            # which would not trace the multi-block carry chain.
            for epilogue in ("scan:8", "ladder"):
                os.environ["DBX_EPILOGUE"] = epilogue
                findings.extend(self._check_registry(ctx, epilogue))
        finally:
            if prior is None:
                os.environ.pop("DBX_EPILOGUE", None)
            else:
                os.environ["DBX_EPILOGUE"] = prior
        return findings

    def _check_registry(self, ctx: LintContext,
                        epilogue: str) -> list[Finding]:
        from ..rpc.compute import JaxSweepBackend

        findings: list[Finding] = []
        suffix = "" if epilogue.startswith("scan") else f"@{epilogue}"
        for strategy, spec in sorted(
                JaxSweepBackend._FUSED_STRATEGIES.items()):
            strategy = strategy + suffix
            run = spec.run
            target = inspect.unwrap(getattr(run, "__func__", run))
            try:
                src, line = (inspect.getsourcefile(target),
                             inspect.getsourcelines(target)[1])
            except (OSError, TypeError):
                src, line = None, 0
            rel = (os.path.relpath(src, ctx.root) if src
                   else "rpc/compute.py")
            try:
                grid = {axis: np.asarray(_AXIS_VALUES[axis], np.float32)
                        for axis in sorted(spec.axes)}
                arrays = _tiny_inputs(spec.fields)
            except KeyError as e:
                # A newly registered kernel with an axis/field this rule
                # has no tiny-input template for must surface as a loud
                # finding, not crash the whole lint run. Template gaps are
                # substrate-independent — report once, on the scan pass.
                if epilogue.startswith("scan"):
                    findings.append(Finding(
                        self.name, rel, line,
                        f"kernel `{strategy}`: no tiny-input template for "
                        f"grid axis/field {e.args[0]!r} — extend "
                        f"_AXIS_VALUES/_tiny_inputs in "
                        f"analysis/jaxpr_rules.py so this kernel stays "
                        f"under kernel-hygiene coverage"))
                continue
            findings.extend(check_traced(
                strategy,
                lambda *arrs, _run=run, _g=grid: _run(*arrs, _g, 0.0, 252,
                                                      None),
                arrays, path=rel, line=line))
        findings.extend(self._check_paged(ctx, suffix))
        findings.extend(self._check_scenario(ctx, suffix))
        findings.extend(self._check_append_steps(ctx, suffix))
        return findings

    def _check_paged(self, ctx: LintContext, suffix: str) -> list[Finding]:
        """The paged execution variants (round 10) are registered kernels
        too: every ``_FUSED_STRATEGIES`` entry traces its page-table path
        (gather + repeat-last fix + the family kernel on the assembled
        block) under the active epilogue substrate, via
        ``ops.fused.paged_hygiene_probe`` — a tiny pool + ragged
        two-ticker page table. A registry entry with no paged row or
        probe template surfaces as a loud finding, so a newly added
        family can't silently serve dense-only."""
        from ..ops import fused
        from ..rpc.compute import JaxSweepBackend

        findings: list[Finding] = []
        try:
            src, line = (inspect.getsourcefile(fused.fused_paged_sweep),
                         inspect.getsourcelines(fused.fused_paged_sweep)[1])
            rel = os.path.relpath(src, ctx.root)
        except (OSError, TypeError):
            rel, line = "ops/fused.py", 0
        for strategy in sorted(JaxSweepBackend._FUSED_STRATEGIES):
            label = f"{strategy}.paged{suffix}"
            try:
                fn, args = fused.paged_hygiene_probe(strategy)
            except Exception as e:   # a probe that cannot build is a
                # finding, never a crashed run. Probe-template gaps are
                # substrate-independent — report once, on the scan pass
                # (the _check_registry template-gap discipline).
                if not suffix:
                    findings.append(Finding(
                        self.name, rel, line,
                        f"kernel `{label}`: paged hygiene probe failed "
                        f"to build tiny pool/page-table inputs: {e!r} — "
                        f"extend ops/fused.py _PAGED_FAMILIES/"
                        f"_PAGED_PROBE_AXES so this kernel's paged path "
                        f"stays under coverage"))
                continue
            findings.extend(check_traced(label, fn, args, path=rel,
                                         line=line))
        return findings

    def _check_scenario(self, ctx: LintContext,
                        suffix: str) -> list[Finding]:
        """The fused scenario generator x sweep megakernel (round 18) is
        a registered kernel too: every family the spec-batch route can
        serve traces its in-trace block-regeneration path (per-spec
        threefry keying + ``_gen_impl`` block scan + the family sweep on
        the regenerated panel) under the active epilogue substrate, via
        ``ops.fused.scenario_hygiene_probe`` — a tiny base panel and two
        scenario specs. A family ``scenario_supported`` claims with no
        probe template surfaces as a loud finding, so the megakernel
        route can't silently serve untraced."""
        from ..ops import fused
        from ..rpc.compute import JaxSweepBackend

        findings: list[Finding] = []
        try:
            src, line = (
                inspect.getsourcefile(fused.fused_scenario_sweep),
                inspect.getsourcelines(fused.fused_scenario_sweep)[1])
            rel = os.path.relpath(src, ctx.root)
        except (OSError, TypeError):
            rel, line = "ops/fused.py", 0
        for strategy in sorted(JaxSweepBackend._FUSED_STRATEGIES):
            if not fused.scenario_supported(strategy):
                continue
            label = f"{strategy}.scenario{suffix}"
            try:
                fn, args = fused.scenario_hygiene_probe(strategy)
            except Exception as e:   # a probe that cannot build is a
                # finding, never a crashed run. Probe-template gaps are
                # substrate-independent — report once, on the scan pass
                # (the _check_registry template-gap discipline).
                if not suffix:
                    findings.append(Finding(
                        self.name, rel, line,
                        f"kernel `{label}`: scenario hygiene probe "
                        f"failed to build tiny base/spec inputs: {e!r} — "
                        f"extend ops/fused.py scenario_hygiene_probe so "
                        f"this family's megakernel route stays under "
                        f"kernel-hygiene coverage"))
                continue
            findings.extend(check_traced(label, fn, args, path=rel,
                                         line=line))
        return findings

    def _check_append_steps(self, ctx: LintContext,
                            suffix: str) -> list[Finding]:
        """The streaming ``_append_step`` recurrent kernels are
        registered kernels too — every fused strategy with a streaming
        family (plus pairs, which routes outside ``_FUSED_STRATEGIES``)
        traces its append step under the active epilogue substrate, so
        no fused code path serves untraced. Probe inputs come from
        ``streaming.recurrent.hygiene_probe`` (tiny carry + ΔT slice)."""
        from ..rpc.compute import JaxSweepBackend
        from ..streaming import recurrent

        findings: list[Finding] = []
        names = sorted(set(JaxSweepBackend._FUSED_STRATEGIES) | {"pairs"})
        try:
            src, line = (inspect.getsourcefile(recurrent.append_step),
                         inspect.getsourcelines(recurrent.append_step)[1])
            rel = os.path.relpath(src, ctx.root)
        except (OSError, TypeError):
            rel, line = "streaming/recurrent.py", 0
        for strategy in names:
            if not recurrent.supports_strategy(strategy):
                continue
            label = f"{strategy}._append_step{suffix}"
            try:
                fn, args = recurrent.hygiene_probe(strategy)
            except Exception as e:   # a probe that cannot build is a
                findings.append(Finding(  # finding, never a crashed run
                    self.name, rel, line,
                    f"kernel `{label}`: hygiene probe failed to build "
                    f"tiny inputs: {e!r}"))
                continue
            findings.extend(check_traced(label, fn, args, path=rel,
                                         line=line))
        return findings
