"""Per-job lifecycle timelines from merged JSONL span logs.

``python -m ...obs.timeline --jsonl dispatcher.jsonl worker1.jsonl ...``

The trace layer (:mod:`.trace`) gives every span a
``(trace_id, span_id, parent_id)`` triple and the dispatcher mints one
trace per job, so the JSONL event logs of any number of processes —
dispatcher, workers, slice leaders — merge into one timeline per job:

    queue-wait -> dispatch -> [transport] -> decode -> compile/execute
    -> d2h -> [transport] -> report

This module reconstructs those timelines, computes **critical-path stage
attribution** (every instant of the job's end-to-end wall is charged to
exactly one stage, so the stages sum to the measured e2e by
construction), aggregates per-stage and per-worker totals, and flags
**stragglers** — jobs whose time in some stage exceeds the fleet's p95
for that stage.

Attribution model: each span name maps to a stage with a priority;
walking the job's e2e window, each instant is charged to the
highest-priority span covering it (ties to the later-starting, i.e.
innermost, span), and instants no span covers are charged to
``transport`` — the wire/queue gaps between processes that no process
can time directly. Generic envelope spans (``worker.submit``,
``worker.collect``) act as low-priority fallbacks for their halves of
the pipeline, so time inside submit but outside the decode span still
lands in ``execute`` rather than vanishing into transport.

Wall-clock timestamps (``t0``) anchor the merge: logs from one host
share a clock; cross-host merging inherits NTP-grade skew, which shifts
the transport buckets but never the in-process stage durations.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

# The canonical stage order of the job lifecycle (report tables and the
# acceptance contract both use it). `panel_cache_hit` is the
# dispatch-by-digest pseudo-stage: a worker serving a panel from its
# digest cache emits its decode span with a truthy `cache_hit` attr, and
# that window is charged here — without it the (near-zero) hit window
# would read as decode work that never happened, and timelines from
# workers that skip the span entirely would silently mis-charge the gap
# to transport.
# `push` is the live fan-out stage (serve/): the dispatcher-side window
# from a completion landing to the result fanned out onto every
# subscriber queue — emitted BEFORE the job's e2e span closes, so it
# lands inside the attribution window (delivery to the client socket is
# the subscriber generator's own wall, visible on the tick-to-push
# histogram instead).
STAGES = ("queue_wait", "dispatch", "transport", "panel_cache_hit",
          "carry_hit", "decode", "compile", "execute", "d2h", "report",
          "push")

# span name -> (stage, priority). Priority 2 = stage-specific span wins
# its interval outright; priority 1 = envelope fallback (charged only
# where no specific span covers). The "job" span is the e2e window, not
# a stage.
SPAN_STAGE = {
    "job.queue_wait": ("queue_wait", 2),
    "job.dispatch": ("dispatch", 2),
    "worker.decode": ("decode", 2),
    "worker.compile": ("compile", 2),
    "worker.execute": ("execute", 2),
    "worker.d2h": ("d2h", 2),
    "worker.report": ("report", 2),
    # The digest-miss recovery RPC (can fire inside the decode window on
    # the compute-thread race leg): network wall, charged to transport —
    # same priority as the specific spans, so innermost-wins beats the
    # enclosing decode span over the fetch's own interval.
    "worker.payload_fetch": ("transport", 2),
    # Streaming appends: the whole carry advance/rebuild window. With a
    # truthy `carry_hit` attr it charges to the `carry_hit` pseudo-stage
    # (the streaming twin of panel_cache_hit — an O(ΔT) advance is not
    # execute work at full-reprice scale); a checkpoint-miss full reprice
    # stays execute.
    "worker.append": ("execute", 2),
    # Live fan-out (serve/): the completion->fanned-out window on the
    # dispatcher. Priority 2: it overlaps only envelope spans (the
    # worker's report fallback), and those instants ARE push work.
    "job.push": ("push", 2),
    # Pipelined executor (round 14): the submit-return -> collect-start
    # window — the batch is in flight on the device while the submit
    # thread stages the NEXT batch. Envelope priority: any specific span
    # inside it wins, but an otherwise-uncovered in-flight window is
    # device execute, NOT the transport the uncovered-gap rule would
    # charge it to.
    "worker.inflight": ("execute", 1),
    # Control-loop payload warm-up (DBX_PREFETCH): decode work done
    # early, so the compute-side decode span can report a cache hit
    # without the real decode wall vanishing from the decode stage.
    "worker.prefetch": ("decode", 2),
    "worker.submit": ("execute", 1),
    "worker.collect": ("d2h", 1),
    "worker.process": ("execute", 1),
    "slice.run_group": ("execute", 1),
    "slice.run_ts_group": ("execute", 1),
}

# Pipeline lanes of the overlap-aware mode: the submit half (host decode
# / page-table build / compile / launch) vs the collect half (device
# drain + d2h). A serial worker alternates lanes, so their coverages
# tile the busy wall (overlap factor ~1); the pipelined executor runs
# them concurrently on two threads, so one wall second carries up to two
# lane seconds (factor -> 2 at perfect double-buffered overlap).
# `worker.inflight` joins neither lane: its window is queue/device wait,
# and counting it would inflate the factor without any host work
# actually overlapping.
_LANE_SPANS = {
    "worker.prefetch": "submit", "worker.decode": "submit",
    "worker.compile": "submit", "worker.execute": "submit",
    "worker.append": "submit", "worker.submit": "submit",
    "worker.d2h": "collect", "worker.collect": "collect",
}
# worker.process (the serial loop's whole-batch envelope) joins NEITHER
# lane: it covers both halves of its batch, so counting it as submit
# would read every serial d2h as overlapped.

E2E_SPAN = "job"


@dataclasses.dataclass
class JobTimeline:
    """All spans of one trace (one job), plus its identity anchors."""

    trace_id: str
    job_id: str = ""
    worker: str = ""
    e2e_t0: float = 0.0
    e2e_dur: float = 0.0
    spans: list = dataclasses.field(default_factory=list)

    @property
    def window(self) -> tuple[float, float]:
        """The attribution window: the dispatcher's measured end-to-end
        span when present, else the span cover (partial logs)."""
        if self.e2e_dur > 0:
            return (self.e2e_t0, self.e2e_t0 + self.e2e_dur)
        if not self.spans:
            return (0.0, 0.0)
        return (min(s["t0"] for s in self.spans),
                max(s["t0"] + s["dur_s"] for s in self.spans))


def parse_events(paths) -> tuple[list[dict], int]:
    """Merge JSONL files into one event list; malformed lines (torn tails,
    truncated writes, non-JSON noise) are skipped AND counted — a
    diagnostic log must never crash its own analyzer, but silent drops
    would misread a corrupt log as a quiet fleet. An unreadable FILE is an
    error (raises OSError): naming a wrong path is operator error, not log
    corruption."""
    events: list[dict] = []
    malformed = 0
    for path in paths:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    malformed += 1
                    continue
                if not isinstance(rec, dict) or "ev" not in rec:
                    malformed += 1
                    continue
                events.append(rec)
    return events, malformed


def stats_url(url: str, doc: str = "stats.json") -> str:
    """Normalize an endpoint to its ``doc`` document URL (a full
    ``.../<doc>`` passes through) — shared by the ``--url`` CLIs here
    and in obs.dump plus obs.fleet's ``/fleet.json`` fetch."""
    if url.rstrip("/").endswith("/" + doc):
        return url
    return url.rstrip("/") + "/" + doc


def fetch_events(urls) -> tuple[list[dict], int]:
    """Scrape live ``/stats.json`` snapshots and return their
    ``dbx_spans_recent`` ring records as span events — the no-log-
    shipping twin of :func:`parse_events`, with the same skip-and-count
    contract for malformed entries. An unreachable URL raises (operator
    error, like an unreadable file)."""
    import urllib.request

    events: list[dict] = []
    malformed = 0
    for url in urls:
        with urllib.request.urlopen(stats_url(url), timeout=10) as resp:
            try:
                snap = json.loads(resp.read())
            except json.JSONDecodeError:
                malformed += 1
                continue
        fam = snap.get("dbx_spans_recent")
        vals = fam.get("values", []) if isinstance(fam, dict) else []
        for rec in vals:
            if not isinstance(rec, dict) or "ev" not in rec:
                malformed += 1
                continue
            events.append(rec)
    return events, malformed


def _span_t0(rec: dict) -> float:
    # t0 is stamped by the trace layer; older logs carry only the write
    # timestamp — the span ENDED at ts, so start = ts - dur.
    if "t0" in rec:
        return float(rec["t0"])
    return float(rec.get("ts", 0.0)) - float(rec.get("dur_s", 0.0))


def reconstruct(events) -> dict[str, JobTimeline]:
    """Group span events into one :class:`JobTimeline` per trace id.

    A span carrying a ``traces`` list (one compute batch serving several
    jobs) is fanned out to every listed trace — the batch's wall is part
    of EACH job's timeline (the jobs shared the device; attribution is
    wall-clock, not device-second, by design)."""
    out: dict[str, JobTimeline] = {}
    for rec in events:
        if rec.get("ev") != "span":
            continue
        dur = float(rec.get("dur_s", 0.0))
        t0 = _span_t0(rec)
        # (trace_id, parent_id) per destination trace: a multi-job batch
        # span stores its local stack parent in ``parent_id`` ("" when it
        # is the context's outermost span) and each trace's REMOTE parent
        # in its ``traces`` pair — losing the pair's half would leave the
        # fanned-out copies parentless.
        tids = []
        if rec.get("trace_id"):
            tids.append((rec["trace_id"], rec.get("parent_id", "")))
        tids.extend((t, rec.get("parent_id") or p)
                    for t, p in rec.get("traces", []) if t)
        for tid, parent_id in tids:
            tl = out.get(tid)
            if tl is None:
                tl = out[tid] = JobTimeline(trace_id=tid)
            name = rec.get("name", "?")
            tl.spans.append({
                "name": name, "t0": t0, "dur_s": dur,
                "span_id": rec.get("span_id", ""),
                "parent_id": parent_id,
                "pid": rec.get("pid"), "ok": rec.get("ok", True),
                "worker": rec.get("worker", ""),
                "cache_hit": bool(rec.get("cache_hit", False)),
                "carry_hit": bool(rec.get("carry_hit", False))})
            if name == E2E_SPAN:
                tl.e2e_t0, tl.e2e_dur = t0, dur
            if rec.get("job") and not tl.job_id:
                tl.job_id = str(rec["job"])
            if rec.get("worker") and name in (E2E_SPAN, "job.dispatch"):
                tl.worker = str(rec["worker"])
    for tl in out.values():
        tl.spans.sort(key=lambda s: (s["t0"], -s["dur_s"]))
    return out


def critical_path(tl: JobTimeline) -> dict[str, float]:
    """Charge every instant of the job's window to exactly one stage.

    Boundary sweep over the clipped span intervals: per segment, the
    highest-priority covering span's stage wins (ties to the later start
    — the innermost span); uncovered segments are ``transport``. The
    returned stage seconds therefore sum EXACTLY to the window length —
    the property the acceptance check ("stages within 10% of measured
    e2e") rides on; the 10% slack only absorbs clock jitter between the
    dispatcher's two window timestamps and span timestamps taken on
    other threads."""
    lo, hi = tl.window
    out = {s: 0.0 for s in STAGES}
    if hi <= lo:
        return out
    ivals = []
    for s in tl.spans:
        staged = SPAN_STAGE.get(s["name"])
        if staged is None:
            continue
        stage, prio = staged[0], staged[1]
        if s["name"] == "worker.decode" and s.get("cache_hit"):
            # Dispatch by digest: every panel of this group came from the
            # worker's digest cache — the window is a cache HIT, not
            # decode work. (d2h spans also carry cache_hit — the group's
            # panel upload was device-cached — but the result drain they
            # time is real work and stays attributed to d2h.)
            stage = "panel_cache_hit"
        if s["name"] == "worker.append" and s.get("carry_hit"):
            # Streaming append served from the carry checkpoint: the
            # O(ΔT) advance window, not full-reprice execute work.
            stage = "carry_hit"
        a = max(s["t0"], lo)
        b = min(s["t0"] + s["dur_s"], hi)
        if b > a:
            ivals.append((a, b, prio, s["t0"], stage))
    points = sorted({lo, hi, *(a for a, *_ in ivals),
                     *(b for _, b, *_ in ivals)})
    for a, b in zip(points, points[1:]):
        mid = (a + b) / 2
        best = None
        for ia, ib, prio, t0, stage in ivals:
            if ia <= mid < ib:
                key = (prio, t0)
                if best is None or key > best[0]:
                    best = (key, stage)
        out[best[1] if best else "transport"] += b - a
    return out


def _merge_ivals(ivals) -> list:
    """Union of ``(a, b)`` intervals: sorted, coalesced, as tuples."""
    out: list = []
    for a, b in sorted(ivals):
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def _coverage(merged, lo: float, hi: float) -> float:
    """Seconds of a merged interval union inside ``[lo, hi]``."""
    return sum(max(0.0, min(b, hi) - max(a, lo)) for a, b in merged)


def overlap_lanes(timelines) -> dict:
    """Per-worker pipeline-lane interval unions for the overlap-aware
    mode: ``worker -> {"submit": [...], "collect": [...], "both": [...]}``
    (merged, non-overlapping intervals each).

    Spans are deduped by span id across timelines first — a multi-job
    batch's span is fanned out to every member's timeline, and counting
    the one decode wall once per job would read co-batching as
    pipelining. The per-JOB wall-clock attribution (:func:`critical_path`)
    deliberately keeps the fan-out; the lanes measure the WORKER's
    thread-level concurrency instead."""
    per: dict = {}
    for tl in timelines.values():
        lanes = per.setdefault(tl.worker or "?",
                               {"submit": {}, "collect": {}})
        for s in tl.spans:
            lane = _LANE_SPANS.get(s["name"])
            if lane is None or s["dur_s"] <= 0:
                continue
            key = s["span_id"] or (s["name"], s["t0"], s["dur_s"])
            lanes[lane][key] = (s["t0"], s["t0"] + s["dur_s"])
    out = {}
    for w, lanes in per.items():
        submit = _merge_ivals(list(lanes["submit"].values()))
        collect = _merge_ivals(list(lanes["collect"].values()))
        out[w] = {"submit": submit, "collect": collect,
                  "both": _merge_ivals(submit + collect)}
    return out


def overlap_factor(lanes: dict, lo: float, hi: float) -> float:
    """Pipelining factor of one worker's lanes inside a window: lane
    seconds per covered wall second. 1.0 = fully serial (lanes tile the
    busy wall); 2.0 = the submit and collect halves fully overlapped
    (perfect double buffering). Windows with no covered wall (a worker
    that never ran compute spans) report 1.0 — no evidence of overlap is
    not evidence of idleness."""
    union = _coverage(lanes["both"], lo, hi)
    if union <= 0:
        return 1.0
    return (_coverage(lanes["submit"], lo, hi)
            + _coverage(lanes["collect"], lo, hi)) / union


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    pos = q * (len(sorted_vals) - 1)
    i = int(pos)
    frac = pos - i
    if i + 1 >= len(sorted_vals):
        return sorted_vals[-1]
    return sorted_vals[i] + (sorted_vals[i + 1] - sorted_vals[i]) * frac


# Straggler flagging needs a population: with fewer jobs than this, p95
# is within noise of the max and every run would "find" one straggler.
MIN_STRAGGLER_JOBS = 8


def summarize(timelines: dict[str, JobTimeline], *,
              min_straggler_jobs: int = MIN_STRAGGLER_JOBS,
              overlap: bool = False) -> dict:
    """Fleet digest: per-stage totals/quantiles, per-worker attribution,
    per-job stage seconds, and stragglers (jobs > p95 in a stage).

    ``overlap=True`` adds the overlap-aware mode (round 14): a per-job
    ``overlap_factor`` — the worker's submit+collect lane seconds per
    covered wall second inside the job's window — and a summary
    ``overlap`` block with per-worker and fleet factors. The per-instant
    stage attribution is unchanged (it charges wall clock and must keep
    summing to e2e); the factor is the separate answer to "how much
    pipeline concurrency did this wall second carry"."""
    lanes = overlap_lanes(timelines) if overlap else {}
    jobs = []
    per_stage: dict[str, list] = {s: [] for s in STAGES}
    per_worker: dict[str, dict] = {}
    for tid, tl in sorted(timelines.items()):
        stages = critical_path(tl)
        lo, hi = tl.window
        row = {"trace_id": tid, "job": tl.job_id,
               "worker": tl.worker, "t0": lo,
               "e2e_s": round(hi - lo, 9),
               "measured_e2e_s": round(tl.e2e_dur, 9),
               "stages": {k: round(v, 9) for k, v in stages.items()},
               "spans": len(tl.spans)}
        if overlap:
            row["overlap_factor"] = round(overlap_factor(
                lanes[tl.worker or "?"], lo, hi), 4)
        jobs.append(row)
        for k, v in stages.items():
            per_stage[k].append(v)
        w = per_worker.setdefault(tl.worker or "?",
                                  {"jobs": 0, "e2e_s": 0.0,
                                   **{s: 0.0 for s in STAGES}})
        w["jobs"] += 1
        w["e2e_s"] += hi - lo
        for k, v in stages.items():
            w[k] += v

    stage_stats = {}
    for k, vals in per_stage.items():
        sv = sorted(vals)
        stage_stats[k] = {
            "total_s": round(sum(sv), 9),
            "mean_s": round(sum(sv) / len(sv), 9) if sv else 0.0,
            "p95_s": round(_quantile(sv, 0.95), 9),
            "max_s": round(sv[-1], 9) if sv else 0.0}

    stragglers = []
    if len(jobs) >= min_straggler_jobs:
        for stage in STAGES:
            p95 = stage_stats[stage]["p95_s"]
            if p95 <= 0:
                continue
            for j in jobs:
                if j["stages"][stage] > p95:
                    stragglers.append({
                        "job": j["job"], "trace_id": j["trace_id"],
                        "worker": j["worker"], "stage": stage,
                        "seconds": j["stages"][stage], "p95_s": p95})
    stragglers.sort(key=lambda s: -(s["seconds"] - s["p95_s"]))

    out = {"jobs": len(jobs),
           "e2e_total_s": round(sum(j["e2e_s"] for j in jobs), 9),
           "stages": stage_stats,
           "workers": {k: {kk: (vv if kk == "jobs" else round(vv, 9))
                           for kk, vv in v.items()}
                       for k, v in sorted(per_worker.items())},
           "stragglers": stragglers,
           "per_job": jobs}
    if overlap:
        lane_s = {ln: 0.0 for ln in ("submit", "collect")}
        union_s = 0.0
        workers = {}
        for w, wl in sorted(lanes.items()):
            cov = {ln: _coverage(wl[ln], float("-inf"), float("inf"))
                   for ln in ("submit", "collect")}
            union = _coverage(wl["both"], float("-inf"), float("inf"))
            for ln in lane_s:
                lane_s[ln] += cov[ln]
            union_s += union
            workers[w] = round((cov["submit"] + cov["collect"])
                               / union if union > 0 else 1.0, 4)
        out["overlap"] = {
            "overlap_factor": round((lane_s["submit"] + lane_s["collect"])
                                    / union_s if union_s > 0 else 1.0, 4),
            "lane_seconds": {ln: round(v, 9) for ln, v in lane_s.items()},
            "covered_wall_s": round(union_s, 9),
            "workers": workers}
    return out


def summarize_spans(spans, **kw) -> dict:
    """Summarize in-memory span records (the obs ring) — bench.py's hook:
    the e2e configs run dispatcher+worker in-process, so the completed
    spans land in the ring without any JSONL file.

    The ring is bounded, and eviction tears the OLDEST jobs first: a
    job's earliest record (``job.queue_wait``, written at take time)
    falls off while its later worker spans and e2e ``job`` span survive,
    so the missing stages would be silently charged to transport. A
    job's ring records are appended in completion order, so the presence
    of its first-written span implies the rest survived too — timelines
    missing ``job.queue_wait`` are dropped from the digest and counted
    as ``torn_jobs`` instead of skewing the stage shares."""
    timelines = reconstruct(spans)
    torn = [t for t, tl in timelines.items()
            if not any(s["name"] == "job.queue_wait" for s in tl.spans)]
    for t in torn:
        del timelines[t]
    if not timelines:
        return {}
    out = summarize(timelines, **kw)
    out.pop("per_job", None)   # BENCH JSON carries the digest, not N rows
    n_strag = len(out["stragglers"])
    if n_strag > 50:
        # Same digest-not-rows discipline: stragglers are sorted worst
        # first, so the tail past 50 is noise a 400 KB BENCH blob would
        # otherwise carry; the total survives as a count.
        out["stragglers"] = out["stragglers"][:50]
        out["stragglers_total"] = n_strag
    if torn:
        out["torn_jobs"] = len(torn)
    return out


# ---------------------------------------------------------------------------
# Rendering + CLI
# ---------------------------------------------------------------------------

def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.2f}ms"
    return f"{v * 1e6:.0f}us"


def _table(rows: list[tuple], header: tuple) -> str:
    rows = [tuple(str(c) for c in r) for r in rows]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(header)]

    def line(cells):
        return "  ".join(c.ljust(w) for c, w in zip(cells, widths)).rstrip()

    out = [line(header), line(tuple("-" * w for w in widths))]
    out.extend(line(r) for r in rows)
    return "\n".join(out)


def render_text(summary: dict) -> str:
    out = [f"{summary['jobs']} job(s), "
           f"{_fmt_s(summary['e2e_total_s'])} end-to-end wall"]
    if "overlap" in summary:
        ov = summary["overlap"]
        out.append(
            f"pipeline overlap {ov['overlap_factor']:.2f}x "
            f"(submit {_fmt_s(ov['lane_seconds']['submit'])} + collect "
            f"{_fmt_s(ov['lane_seconds']['collect'])} over "
            f"{_fmt_s(ov['covered_wall_s'])} covered wall)")
    rows = []
    total = summary["e2e_total_s"] or 1.0
    for stage in STAGES:
        st = summary["stages"][stage]
        if not st["total_s"]:
            continue
        rows.append((stage, _fmt_s(st["total_s"]), _fmt_s(st["mean_s"]),
                     _fmt_s(st["p95_s"]), _fmt_s(st["max_s"]),
                     f"{100.0 * st['total_s'] / total:.1f}%"))
    out.append("")
    out.append("== critical-path stage attribution ==")
    out.append(_table(rows, ("stage", "total", "mean/job", "p95", "max",
                             "share")))
    if len(summary["workers"]) > 1 or "?" not in summary["workers"]:
        out.append("")
        out.append("== per worker ==")
        wrows = [(w, v["jobs"], _fmt_s(v["e2e_s"]),
                  _fmt_s(v["execute"] + v["compile"]),
                  _fmt_s(v["transport"]), _fmt_s(v["report"]))
                 for w, v in summary["workers"].items()]
        out.append(_table(wrows, ("worker", "jobs", "e2e", "compute",
                                  "transport", "report")))
    if summary["stragglers"]:
        out.append("")
        out.append("== stragglers (stage time > fleet p95) ==")
        srows = [(s["job"] or s["trace_id"][:12], s["stage"],
                  _fmt_s(s["seconds"]), _fmt_s(s["p95_s"]), s["worker"])
                 for s in summary["stragglers"][:20]]
        out.append(_table(srows, ("job", "stage", "seconds", "p95",
                                  "worker")))
    for j in summary.get("per_job", []):
        out.append("")
        top = sorted(j["stages"].items(), key=lambda kv: -kv[1])
        out.append(f"-- job {j['job'] or j['trace_id'][:12]} "
                   f"(worker {j['worker'] or '?'}): "
                   f"e2e {_fmt_s(j['e2e_s'])}, "
                   + ", ".join(f"{k} {_fmt_s(v)}"
                               for k, v in top if v > 0))
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="obs.timeline",
        description="merge JSONL span logs from any number of processes "
                    "into per-job lifecycle timelines with critical-path "
                    "stage attribution and straggler flags")
    ap.add_argument("--jsonl", nargs="+", action="extend", default=[],
                    metavar="PATH",
                    help="JSONL event log(s) (DBX_OBS_JSONL output); "
                         "repeatable, merged on trace ids")
    ap.add_argument("--url", nargs="+", action="extend", default=[],
                    metavar="URL",
                    help="live /stats.json endpoint(s) "
                         "(http://host:port or the full .../stats.json): "
                         "the snapshot's recent-span ring is merged in "
                         "beside --jsonl, so an operator can point at a "
                         "running fleet without shipping logs")
    ap.add_argument("--job", default=None,
                    help="restrict to one job id (or trace-id prefix)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--min-straggler-jobs", type=int,
                    default=MIN_STRAGGLER_JOBS,
                    help="minimum fleet size before stragglers are "
                         "flagged (p95 of a tiny sample is noise)")
    ap.add_argument("--overlap", action="store_true",
                    help="overlap-aware mode: per-job and per-worker "
                         "pipeline overlap factors (submit+collect lane "
                         "seconds per covered wall second)")
    args = ap.parse_args(argv)
    if not args.jsonl and not args.url:
        ap.error("no inputs: pass --jsonl path(s) and/or --url "
                 "endpoint(s)")

    events, malformed = parse_events(args.jsonl)
    if args.url:
        url_events, url_malformed = fetch_events(args.url)
        events.extend(url_events)
        malformed += url_malformed
    if malformed:
        print(f"obs.timeline: skipped {malformed} malformed "
              "line(s)/record(s)", file=sys.stderr)
    if not events:
        print("obs.timeline: no parseable events in "
              + ", ".join(args.jsonl + args.url), file=sys.stderr)
        return 2
    timelines = reconstruct(events)
    if args.job:
        timelines = {t: tl for t, tl in timelines.items()
                     if tl.job_id == args.job or t.startswith(args.job)}
        if not timelines:
            print(f"obs.timeline: no trace matches --job {args.job}",
                  file=sys.stderr)
            return 2
    if not timelines:
        print("obs.timeline: events parsed but none carry trace ids "
              "(pre-tracing logs?)", file=sys.stderr)
        return 2
    summary = summarize(timelines,
                        min_straggler_jobs=args.min_straggler_jobs,
                        overlap=args.overlap)
    if args.format == "json":
        print(json.dumps(summary, indent=2))
    else:
        sys.stdout.write(render_text(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
