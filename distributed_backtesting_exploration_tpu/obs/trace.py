"""Span/trace API + timing utilities (supersedes ``utils.trace``).

A :func:`span` is the unit of phase attribution: it times a named phase,
records the duration into the shared registry's ``dbx_span_seconds``
histogram (labeled by span name), tracks nesting per thread, and — when the
JSONL event log is configured — emits one event per span with its parent,
so a post-mortem reader can rebuild the per-batch chain
(``decode -> submit -> collect -> report``) from the log alone.

Distributed traces (round 7): every span now carries a
``(trace_id, span_id, parent_id)`` triple. The ambient trace is a
``contextvars.ContextVar`` holding the remote parent(s) a span chain
should join — the dispatcher mints one trace_id per job at enqueue time,
ships it over the wire (``JobSpec.trace_id`` / ``parent_span_id``), and
the worker adopts it with :func:`trace_context` so its local span chain
becomes children of the dispatcher's dispatch span. A compute batch can
serve SEVERAL jobs (several traces) at once; a multi-trace context makes
spans carry a ``traces`` list of ``[trace_id, parent_span_id]`` pairs
instead of one ``trace_id`` — the timeline analyzer (:mod:`.timeline`)
fans such spans out to every listed trace.

Completed spans land in three places: the ``dbx_span_seconds`` histogram
(aggregate), the JSONL event log when configured (durable), and a bounded
in-memory ring (:func:`recent_spans`) exported via ``/stats.json`` and
GetStats ``obs_json`` so a live process can be asked "what just ran"
without any log file.

``timed`` (log-only), ``StepTimer`` (running throughput meter) and
``device_profile`` (jax.profiler wrapper) move here from ``utils.trace``,
which remains as a deprecation shim for one release.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import itertools
import logging
import os
import random
import threading
import time

from . import events
from .registry import get_registry

log = logging.getLogger("dbx.trace")

_tls = threading.local()

# Ambient remote-trace context: a tuple of (trace_id, parent_span_id)
# pairs the NEXT outermost span on this thread should join. Contextvars
# are per-thread for plain threads, so the worker's control and compute
# threads each set their own.
_trace_ctx: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "dbx_trace_ctx", default=())

# ID minting: 128-bit trace ids / 64-bit span ids as lowercase hex.
# random.getrandbits is ~3x cheaper than uuid4 and these ids only need
# collision resistance within one fleet run, not global uniqueness.
_rand = random.Random()


def new_trace_id() -> str:
    return f"{_rand.getrandbits(128):032x}"


def new_span_id() -> str:
    return f"{_rand.getrandbits(64):016x}"


def current_span() -> str | None:
    """Name of the innermost active span on this thread, or None."""
    stack = getattr(_tls, "stack", None)
    return stack[-1][0] if stack else None


def current_trace() -> str | None:
    """The ambient trace id when exactly one trace is adopted, else None."""
    pairs = _trace_ctx.get()
    return pairs[0][0] if len(pairs) == 1 else None


@contextlib.contextmanager
def trace_context(trace_id, parent_span_id: str = ""):
    """Adopt a remote trace for the duration of the block.

    ``trace_id`` is either one id string (with its ``parent_span_id``) or
    a list of ``(trace_id, parent_span_id)`` pairs — the multi-job batch
    case. Pairs with empty trace ids are dropped (jobs enqueued by a
    pre-tracing dispatcher); an all-empty context leaves spans untraced,
    exactly the old behavior.
    """
    if isinstance(trace_id, str):
        pairs = ((trace_id, parent_span_id or ""),) if trace_id else ()
    else:
        pairs = tuple((t, p or "") for t, p in trace_id if t)
    token = _trace_ctx.set(pairs)
    try:
        yield
    finally:
        _trace_ctx.reset(token)


def job_trace_pairs(jobs) -> list:
    """``(trace_id, parent_span_id)`` pairs of a job batch (JobSpec or any
    object exposing ``trace_id`` / ``parent_span_id``), traceless jobs
    skipped — the argument :func:`trace_context` takes for a batch."""
    out = []
    for j in jobs:
        tid = getattr(j, "trace_id", "")
        if tid:
            out.append((tid, getattr(j, "parent_span_id", "")))
    return out


# ---------------------------------------------------------------------------
# Bounded in-memory span ring
# ---------------------------------------------------------------------------

SPAN_RING_CAPACITY = 512

_ring_lock = threading.Lock()
# Created lazily at first use so the capacity knob (DBX_SPAN_RING) is
# read when the ring is first needed, not at import time — the
# DBX_OBS_JSONL discipline: tests and operators can set it after import.
_ring: collections.deque | None = None


def _ring_capacity() -> int:
    """``DBX_SPAN_RING`` (default 512): completed spans retained for
    /stats.json, GetStats ``obs_json`` and bench's end-of-run timeline
    digest. 0 disables the ring entirely."""
    try:
        return max(int(os.environ.get("DBX_SPAN_RING",
                                      SPAN_RING_CAPACITY)), 0)
    except ValueError as e:
        raise ValueError(
            f"DBX_SPAN_RING={os.environ['DBX_SPAN_RING']!r} is not an "
            "integer") from e


def _get_ring() -> collections.deque:
    """The ring, created at first use (caller holds ``_ring_lock``)."""
    global _ring
    if _ring is None:
        _ring = collections.deque(maxlen=_ring_capacity())
    return _ring


def configure_ring(capacity: int | None = None) -> None:
    """Resize (and clear) the completed-span ring. 0 disables it; None
    re-reads ``DBX_SPAN_RING`` — the reset path for tests/benches that
    flip the env knob after the ring already materialized."""
    global _ring
    with _ring_lock:
        _ring = collections.deque(
            maxlen=_ring_capacity() if capacity is None
            else max(int(capacity), 0))


def recent_spans(n: int | None = None) -> list[dict]:
    """The last ``n`` (default: all retained) completed span records,
    oldest first — the same dicts the JSONL event log would carry.

    Copies only the requested tail under the ring lock: every span
    completion appends under the same lock, so a stats scrape of a large
    ring (bench sizes it to 32k via DBX_SPAN_RING) must not stall the
    hot path for a full-ring copy."""
    with _ring_lock:
        ring = _get_ring()
        if n is None:
            return list(ring)
        if n <= 0:
            return []
        return list(itertools.islice(ring, max(len(ring) - n, 0), None))


# In-process completed-span taps: keyed callables invoked (outside every
# lock) with each completed span record. The empty-tuple steady state
# keeps the hot path at one truthiness check; the tuple is rebuilt under
# the lock on add/remove so iteration never races a mutation.
_listeners: tuple = ()
_listeners_by_key: dict = {}
_listeners_lock = threading.Lock()


def add_span_listener(key: str, fn) -> None:
    """Register ``fn(record)`` to observe every completed span (the
    fleet-telemetry stage collector's feed). Keyed so a re-registered
    component replaces its predecessor instead of stacking."""
    global _listeners
    with _listeners_lock:
        _listeners_by_key[key] = fn
        _listeners = tuple(_listeners_by_key.values())


def remove_span_listener(key: str) -> None:
    global _listeners
    with _listeners_lock:
        _listeners_by_key.pop(key, None)
        _listeners = tuple(_listeners_by_key.values())


# Span histograms are get-or-create per distinct name; cache the children so
# repeated spans cost a dict lookup, not a registry resolution.
_span_hists: dict = {}
_span_hists_lock = threading.Lock()


def _span_hist(name: str):
    h = _span_hists.get(name)
    if h is None:
        with _span_hists_lock:
            h = _span_hists.get(name)
            if h is None:
                h = get_registry().histogram(
                    "dbx_span_seconds",
                    help="wall-clock duration of named phases (span API)",
                    span=name)
                _span_hists[name] = h
    return h


def _record_span(name: str, t0_wall: float, dur: float, *, span_id: str,
                 stack_parent, pairs: tuple, ok: bool = True,
                 **attrs) -> dict:
    """The one completed-span sink: histogram + ring + JSONL event.

    ``stack_parent`` is the enclosing local span as ``(name, span_id)`` or
    None; ``pairs`` the ambient (or explicit) remote-trace pairs. A nested
    span parents onto its local enclosing span; only the OUTERMOST span of
    a context parents onto the remote ``parent_span_id``.
    """
    _span_hist(name).observe(dur)
    rec = {"ev": "span", "name": name, "t0": round(t0_wall, 6),
           "dur_s": round(dur, 9), "span_id": span_id,
           "parent": stack_parent[0] if stack_parent else None,
           "thread": threading.current_thread().name, "ok": ok}
    if len(pairs) == 1:
        rec["trace_id"] = pairs[0][0]
        rec["parent_id"] = (stack_parent[1] if stack_parent
                            else pairs[0][1])
    elif pairs:
        rec["traces"] = [[t, p] for t, p in pairs]
        rec["parent_id"] = stack_parent[1] if stack_parent else ""
    elif stack_parent:
        rec["parent_id"] = stack_parent[1]
    rec.update(attrs)
    with _ring_lock:
        ring = _get_ring()
        if ring.maxlen:
            ring.append(rec)
    if events.enabled():
        events.emit_record(rec)
    for fn in _listeners:
        # In-process span taps (the fleet telemetry collector): called
        # OUTSIDE every lock with the already-built record; a listener
        # failure must never break the instrumented code path.
        try:
            fn(rec)
        except Exception:
            log.exception("span listener failed")
    return rec


def emit_span(name: str, t0_wall: float, dur_s: float, *,
              trace_id: str = "", parent_id: str = "", pairs=None,
              span_id: str | None = None, ok: bool = True,
              **attrs) -> str:
    """Record an already-measured span (histogram + ring + event log) and
    return its span id.

    The synthesized-span entry point for phases that are not ``with``
    blocks — the dispatcher's queue-wait (enqueue ts -> dispatch ts) and
    the job's end-to-end wall (enqueue ts -> completion recorded) exist
    only as two timestamps, never as one open stack frame. ``pairs``
    (a list of ``(trace_id, parent_span_id)``) overrides ``trace_id`` for
    the multi-job-batch case. An enclosing local span on this thread, if
    any, becomes the local parent — the compute backend emits its
    compile/execute spans from inside the worker's submit span.
    """
    sid = span_id or new_span_id()
    if pairs is None:
        pairs = ((trace_id, parent_id),) if trace_id else ()
    else:
        pairs = tuple((t, p or "") for t, p in pairs if t)
    stack = getattr(_tls, "stack", None)
    _record_span(name, t0_wall, max(float(dur_s), 0.0), span_id=sid,
                 stack_parent=stack[-1] if stack else None, pairs=pairs,
                 ok=ok, **attrs)
    return sid


@contextlib.contextmanager
def span(name: str, **attrs):
    """Time a named phase: ``with span("decode", jobs=32): ...``.

    Durations land in ``dbx_span_seconds{span=name}``; the completed span
    (with its ``trace_id``/``span_id``/``parent_id`` triple, ``t0`` wall
    start, and ``dur_s``) goes to the in-memory ring and — when the JSONL
    event log is configured — to the log. Exceptions propagate; the span
    records either way (``ok`` marks it).
    """
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    parent = stack[-1] if stack else None
    sid = new_span_id()
    stack.append((name, sid))
    pairs = _trace_ctx.get()
    t0_wall = time.time()
    t0 = time.perf_counter()
    ok = True
    try:
        yield
    except BaseException:
        ok = False
        raise
    finally:
        dur = time.perf_counter() - t0
        stack.pop()
        _record_span(name, t0_wall, dur, span_id=sid, stack_parent=parent,
                     pairs=pairs, ok=ok, **attrs)


@contextlib.contextmanager
def timer(hist):
    """Observe the block's wall into a pre-resolved histogram — in a
    ``finally``, so failures and timeouts are measured too (an RPC
    latency histogram that excludes the 30 s deadline-exceeded calls
    reads healthy while throughput is zero)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        hist.observe(time.perf_counter() - t0)


@contextlib.contextmanager
def timed(name: str, *, logger: logging.Logger = log, level=logging.INFO):
    """Log the wall-clock duration of a phase: ``with timed("decode"): ...``"""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        logger.log(level, "%s took %.1fms", name,
                   1e3 * (time.perf_counter() - t0))


@contextlib.contextmanager
def device_profile(logdir: str):
    """Capture a jax.profiler trace (XLA kernel timeline) under ``logdir``.

    View with TensorBoard's profile plugin. On the remote-proxy TPU backend
    host-side events still capture; device traces need a directly-attached
    chip.
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Running throughput meter: the ``backtests/sec`` counter surfaced by
    the dispatcher's GetStats — usable worker-side for per-batch logs.

    Pass ``gauge`` (an :class:`~.registry.Gauge`) to publish the running
    rate on every :meth:`add`."""

    def __init__(self, gauge=None):
        self.t0 = time.monotonic()
        self.units = 0.0
        self._gauge = gauge

    def bind_gauge(self, gauge) -> None:
        """Attach (or detach, with None) the published-rate gauge after
        construction — for owners whose metric lifecycle starts later
        than their own (e.g. a Worker binds in run(), not __init__)."""
        self._gauge = gauge

    def add(self, n: float) -> None:
        self.units += n
        if self._gauge is not None:
            self._gauge.set(self.rate)

    @property
    def rate(self) -> float:
        return self.units / max(time.monotonic() - self.t0, 1e-9)
