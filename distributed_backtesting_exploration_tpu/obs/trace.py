"""Span/trace API + timing utilities (supersedes ``utils.trace``).

A :func:`span` is the unit of phase attribution: it times a named phase,
records the duration into the shared registry's ``dbx_span_seconds``
histogram (labeled by span name), tracks nesting per thread, and — when the
JSONL event log is configured — emits one event per span with its parent,
so a post-mortem reader can rebuild the per-batch chain
(``decode -> submit -> collect -> report``) from the log alone.

``timed`` (log-only), ``StepTimer`` (running throughput meter) and
``device_profile`` (jax.profiler wrapper) move here from ``utils.trace``,
which remains as a deprecation shim for one release.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time

from . import events
from .registry import get_registry

log = logging.getLogger("dbx.trace")

_tls = threading.local()


def current_span() -> str | None:
    """Name of the innermost active span on this thread, or None."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


# Span histograms are get-or-create per distinct name; cache the children so
# repeated spans cost a dict lookup, not a registry resolution.
_span_hists: dict = {}
_span_hists_lock = threading.Lock()


def _span_hist(name: str):
    h = _span_hists.get(name)
    if h is None:
        with _span_hists_lock:
            h = _span_hists.get(name)
            if h is None:
                h = get_registry().histogram(
                    "dbx_span_seconds",
                    help="wall-clock duration of named phases (span API)",
                    span=name)
                _span_hists[name] = h
    return h


@contextlib.contextmanager
def span(name: str, **attrs):
    """Time a named phase: ``with span("decode", jobs=32): ...``.

    Durations land in ``dbx_span_seconds{span=name}``; when the JSONL
    event log is configured each span also emits
    ``{"ev": "span", "name", "dur_s", "parent", "thread", ...attrs}``.
    Exceptions propagate; the span records either way (``ok`` marks it).
    """
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    parent = stack[-1] if stack else None
    stack.append(name)
    t0 = time.perf_counter()
    ok = True
    try:
        yield
    except BaseException:
        ok = False
        raise
    finally:
        dur = time.perf_counter() - t0
        stack.pop()
        _span_hist(name).observe(dur)
        if events.enabled():
            events.emit("span", name=name, dur_s=round(dur, 9),
                        parent=parent, thread=threading.current_thread().name,
                        ok=ok, **attrs)


@contextlib.contextmanager
def timer(hist):
    """Observe the block's wall into a pre-resolved histogram — in a
    ``finally``, so failures and timeouts are measured too (an RPC
    latency histogram that excludes the 30 s deadline-exceeded calls
    reads healthy while throughput is zero)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        hist.observe(time.perf_counter() - t0)


@contextlib.contextmanager
def timed(name: str, *, logger: logging.Logger = log, level=logging.INFO):
    """Log the wall-clock duration of a phase: ``with timed("decode"): ...``"""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        logger.log(level, "%s took %.1fms", name,
                   1e3 * (time.perf_counter() - t0))


@contextlib.contextmanager
def device_profile(logdir: str):
    """Capture a jax.profiler trace (XLA kernel timeline) under ``logdir``.

    View with TensorBoard's profile plugin. On the remote-proxy TPU backend
    host-side events still capture; device traces need a directly-attached
    chip.
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Running throughput meter: the ``backtests/sec`` counter surfaced by
    the dispatcher's GetStats — usable worker-side for per-batch logs.

    Pass ``gauge`` (an :class:`~.registry.Gauge`) to publish the running
    rate on every :meth:`add`."""

    def __init__(self, gauge=None):
        self.t0 = time.monotonic()
        self.units = 0.0
        self._gauge = gauge

    def bind_gauge(self, gauge) -> None:
        """Attach (or detach, with None) the published-rate gauge after
        construction — for owners whose metric lifecycle starts later
        than their own (e.g. a Worker binds in run(), not __init__)."""
        self._gauge = gauge

    def add(self, n: float) -> None:
        self.units += n
        if self._gauge is not None:
            self._gauge.set(self.rate)

    @property
    def rate(self) -> float:
        return self.units / max(time.monotonic() - self.t0, 1e-9)
