"""Tiny stdlib HTTP exposure: ``/metrics`` (Prometheus text) + ``/stats.json``.

One daemon thread per server; ``port=0`` binds an ephemeral port (the bound
port is on ``MetricsServer.port``). No external deps — the scrape surface
must exist on any box the dispatcher or a worker lands on.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import trace
from .registry import Registry, get_registry

log = logging.getLogger("dbx.obs.http")

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Recent-span window shipped by /stats.json and GetStats obs_json: enough
# to cover a poll-cycle of batches without bloating every scrape (the full
# ring stays readable in-process via obs.recent_spans()).
STATS_SPAN_WINDOW = 128


def stats_payload(registry: Registry) -> dict:
    """The ``/stats.json`` document: the registry snapshot plus the tail of
    the process-wide completed-span ring under ``dbx_spans_recent`` —
    shaped like a metric family (``{"type": "spans", "values": [...]}``) so
    snapshot consumers that dispatch on ``type`` skip it untouched."""
    snap = registry.snapshot()
    snap["dbx_spans_recent"] = {"type": "spans",
                                "values": trace.recent_spans(
                                    STATS_SPAN_WINDOW)}
    return snap


class MetricsServer:
    """Serves a registry over HTTP; ``start()``/``stop()`` lifecycle."""

    def __init__(self, port: int = 0, *, registry: Registry | None = None,
                 bind: str = "0.0.0.0", routes: dict | None = None):
        self.registry = registry or get_registry()
        self._bind = bind
        self._requested_port = port
        # Extra JSON document routes: path -> zero-arg callable returning
        # a JSON-able dict, evaluated per request (the dispatcher mounts
        # its FleetView snapshot as /fleet.json here).
        self._routes = dict(routes or {})
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.port: int | None = None

    def start(self) -> "MetricsServer":
        reg = self.registry
        routes = self._routes

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):                      # noqa: N802 (stdlib API)
                path = self.path.split("?", 1)[0]
                if path in ("/metrics", "/"):
                    body = reg.render_prometheus().encode()
                    ctype = PROMETHEUS_CONTENT_TYPE
                elif path == "/stats.json":
                    # default=str: ring span records carry arbitrary
                    # span attrs, same guard as the JSONL event writer.
                    body = json.dumps(stats_payload(reg),
                                      default=str).encode()
                    ctype = "application/json"
                elif path in routes:
                    try:
                        doc = routes[path]()
                    except Exception:
                        log.exception("route %s provider failed", path)
                        self.send_error(500)
                        return
                    body = json.dumps(doc, default=str).encode()
                    ctype = "application/json"
                elif path == "/healthz":
                    body, ctype = b"ok\n", "text/plain"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):     # scrapes are not news
                pass

        self._httpd = ThreadingHTTPServer((self._bind, self._requested_port),
                                          Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dbx-metrics-http",
            daemon=True)
        self._thread.start()
        log.info("metrics endpoint on http://%s:%d/metrics", self._bind,
                 self.port)
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def start_metrics_server(port: int, *,
                         registry: Registry | None = None) -> MetricsServer:
    """Start a /metrics endpoint on ``port`` (0 = ephemeral)."""
    return MetricsServer(port, registry=registry).start()
