"""``dbxwhy``: why did job J land on worker W, and what did it cost?

The decision plane (obs/decisions.py) records one explain document per
dispatched job — the WFQ pick context (sched/explain.py), the payload
route, the polling worker's fleet-view age, the LIVE placement stage's
rank (round 20: chosen vs best-placed worker, score gap, deferrals
spent against ``DBX_PLACEMENT_DEFER_CAP``), and the shadow placement
scorer's per-candidate cost ranking with its measured regret. The
PR-4 timeline (obs/timeline.py) records what then actually happened —
queue-wait, dispatch, transport, compile/execute, d2h, report. This CLI
stitches the two into one report per job:

    dbxwhy <job-id> --jsonl dispatcher.jsonl [worker.jsonl ...]
    dbxwhy <job-id> --url http://dispatcher:9100

Both streams ride the same JSONL event log (``DBX_OBS_JSONL`` — spans
as ``ev="span"``, decisions as ``ev="decision"`` lines), so the merge
contract is obs.timeline's verbatim: any number of ``--jsonl`` files,
malformed lines skipped and counted, an unreadable FILE an error.
``--url`` scrapes a live dispatcher instead: ``/decisions.json`` for
the record tail and ``/stats.json`` for the span ring — no log
shipping. A job dispatched more than once (requeue, journal-replay
restart) has one record per dispatch; all are shown, oldest first —
the decision CHAIN, not just the last word.

Exit codes: 0 with a report, 2 when no decision record matches the job
(or no inputs parse) — the obs.timeline contract.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import timeline


def split_events(events) -> tuple[list[dict], list[dict]]:
    """One merged JSONL stream -> (decision records, span events)."""
    decisions = [e for e in events if e.get("ev") == "decision"]
    spans = [e for e in events if e.get("ev") == "span"]
    return decisions, spans


def fetch_decisions(urls) -> tuple[list[dict], int]:
    """Scrape live ``/decisions.json`` tails. Mirrors
    ``timeline.fetch_events``: malformed entries skip-and-count, an
    unreachable URL raises (operator error, not log corruption)."""
    import urllib.request

    out: list[dict] = []
    malformed = 0
    for url in urls:
        doc_url = timeline.stats_url(url, "decisions.json")
        with urllib.request.urlopen(doc_url, timeout=10) as resp:
            try:
                doc = json.loads(resp.read())
            except json.JSONDecodeError:
                malformed += 1
                continue
        recent = doc.get("recent") if isinstance(doc, dict) else None
        for rec in recent or ():
            if isinstance(rec, dict):
                out.append(rec)
            else:
                malformed += 1
    return out, malformed


def match_job(decisions, spans, job: str):
    """Filter both streams to one job id (or trace-id prefix)."""
    hits = [d for d in decisions
            if d.get("jid") == job
            or str(d.get("trace_id", "")).startswith(job)]
    timelines = {
        t: tl for t, tl in timeline.reconstruct(spans).items()
        if tl.job_id == job or t.startswith(job)}
    return hits, timelines


def _fmt_cost(c: dict) -> str:
    parts = [f"exec {timeline._fmt_s(c.get('exec_s', 0.0))}"]
    if c.get("transfer_s"):
        parts.append(f"h2d {timeline._fmt_s(c['transfer_s'])}")
    if c.get("compile_s"):
        parts.append(f"compile {timeline._fmt_s(c['compile_s'])}")
    flags = [f for f in ("carry_hit", "resident") if c.get(f)]
    if flags:
        parts.append("+".join(flags))
    return ", ".join(parts)


def render_decision(d: dict, idx: int, total: int) -> str:
    out = []
    head = f"== decision {idx + 1}/{total}: job {d.get('jid', '?')} -> " \
           f"worker {d.get('worker', '?')} =="
    out.append(head)
    out.append(f"route={d.get('route', '?')}  "
               f"tenant={d.get('tenant', '?')}  "
               f"strategy={d.get('strategy', '?')}  "
               f"combos={d.get('combos', 0)}  "
               f"affinity_skips={d.get('affinity_skips', 0)}")
    age = d.get("fleet_age_s")
    out.append("fleet-view age at decision: "
               + (f"{age:.3f}s" if isinstance(age, (int, float))
                  else "(no telemetry)"))
    wfq = d.get("wfq")
    if isinstance(wfq, dict) and wfq.get("affinity_held"):
        out.append("wfq: served from the placement-held list (locality "
                   "deferral; no pick-time scheduler state)")
    elif isinstance(wfq, dict):
        out.append(
            f"wfq: tag={wfq.get('tag')} vtime={wfq.get('vtime')} "
            f"vfinish={wfq.get('vfinish')} cost={wfq.get('cost')} "
            f"weight={wfq.get('weight')}"
            + (" OVER-QUOTA" if wfq.get("over_quota") else ""))
        heads = wfq.get("heads") or {}
        if heads:
            out.append("  competing heads: " + ", ".join(
                f"{t}={v}" for t, v in sorted(heads.items())))
        if wfq.get("demoted"):
            out.append("  quota-demoted this pick: "
                       + ", ".join(wfq["demoted"]))
    placement = d.get("placement")
    if isinstance(placement, dict):
        best = str(placement.get("best", "?"))
        actual = str(d.get("worker", ""))
        verdict = ("best-placed worker" if best == actual else
                   f"best-placed was {best}, "
                   f"gap {timeline._fmt_s(placement.get('gap_s', 0.0))}")
        out.append(
            f"placement: outcome={placement.get('outcome', '?')}  "
            f"cost={timeline._fmt_s(placement.get('cost_s', 0.0))} "
            f"vs best={timeline._fmt_s(placement.get('best_cost_s', 0.0))}  "
            f"defers={placement.get('defers', 0)}/{placement.get('cap', 0)}  "
            f"({verdict}; table: "
            f"{placement.get('table_workers', 0)} worker(s))")
    shadow = d.get("shadow") or {}
    costs = shadow.get("costs") or {}
    if costs:
        actual = str(d.get("worker", ""))
        rows = []
        for wid, c in sorted(costs.items(),
                             key=lambda kv: kv[1].get("cost_s", 0.0)):
            marks = ("<- actual" if wid == actual else "") + \
                (" (shadow pick)" if wid == shadow.get("best")
                 and wid != actual else "")
            rows.append((wid, timeline._fmt_s(c.get("cost_s", 0.0)),
                         _fmt_cost(c), marks.strip()))
        out.append("shadow ranking "
                   f"({shadow.get('candidates', 0)} candidate(s)):")
        out.append(timeline._table(
            rows, ("worker", "cost", "breakdown", "")))
    if "regret_s" in shadow:
        verdict = ("shadow agrees with the placement"
                   if shadow.get("agree") else
                   f"shadow preferred {shadow.get('best', '?')}")
        out.append(f"regret: {timeline._fmt_s(shadow['regret_s'])} "
                   f"({verdict})")
    elif not costs:
        out.append("shadow: no live candidates at scoring time")
    return "\n".join(out)


def render(job: str, decisions: list, timelines: dict) -> str:
    out = []
    for i, d in enumerate(decisions):
        if i:
            out.append("")
        out.append(render_decision(d, i, len(decisions)))
    if timelines:
        out.append("")
        out.append("== what actually happened ==")
        summary = timeline.summarize(timelines)
        out.append(timeline.render_text(summary).rstrip("\n"))
    else:
        out.append("")
        out.append("(no span timeline for this job in the inputs)")
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dbxwhy",
        description="stitch a job's dispatch decision records (WFQ pick "
                    "context, payload route, live placement rank, shadow "
                    "placement ranking, regret) with its span timeline")
    ap.add_argument("job", help="job id (or trace-id prefix)")
    ap.add_argument("--jsonl", nargs="+", action="extend", default=[],
                    metavar="PATH",
                    help="JSONL event log(s) (DBX_OBS_JSONL output) "
                         "carrying ev=decision and ev=span lines; "
                         "repeatable, merged")
    ap.add_argument("--url", nargs="+", action="extend", default=[],
                    metavar="URL",
                    help="live dispatcher metrics endpoint(s): "
                         "/decisions.json is scraped for the record "
                         "tail and /stats.json for the span ring")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)
    if not args.jsonl and not args.url:
        ap.error("no inputs: pass --jsonl path(s) and/or --url "
                 "endpoint(s)")

    events, malformed = timeline.parse_events(args.jsonl)
    decisions, spans = split_events(events)
    if args.url:
        url_decisions, url_malformed = fetch_decisions(args.url)
        decisions.extend(url_decisions)
        malformed += url_malformed
        try:
            url_spans, span_malformed = timeline.fetch_events(args.url)
        except OSError:
            url_spans, span_malformed = [], 0   # decisions-only endpoint
        spans.extend(url_spans)
        malformed += span_malformed
    if malformed:
        print(f"dbxwhy: skipped {malformed} malformed "
              "line(s)/record(s)", file=sys.stderr)
    if not decisions and not spans:
        print("dbxwhy: no parseable events in "
              + ", ".join(args.jsonl + args.url), file=sys.stderr)
        return 2
    hits, timelines = match_job(decisions, spans, args.job)
    if not hits:
        print(f"dbxwhy: no decision record matches {args.job!r} "
              "(is DBX_DECISIONS on, and the dispatcher's DBX_OBS_JSONL "
              "among the inputs?)", file=sys.stderr)
        return 2
    hits.sort(key=lambda d: d.get("t_take", 0.0))
    if args.format == "json":
        doc = {"job": args.job, "decisions": hits}
        if timelines:
            doc["timeline"] = timeline.summarize(timelines)
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        sys.stdout.write(render(args.job, hits, timelines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
