"""Process-local metrics registry: counters, gauges, fixed-bucket histograms.

The observability substrate every layer records into (dispatcher RPC
latencies, worker batch spans, kernel wall-times). Design constraints, in
order:

- **Lock-cheap hot path.** A counter increment is one ``threading.Lock``
  acquire + a float add; a histogram observation adds one bisect. Callers
  on hot paths (per-RPC, per-batch) pre-resolve their metric objects once
  and hold direct references — name/label resolution never happens per
  event. Measured <2 µs per observation, which keeps the dispatcher's
  direct-dispatch ceiling (~16 ms per batch-32 RPC) well under the 2%
  instrumentation budget.
- **No external deps.** Renders the Prometheus text exposition format
  (v0.0.4) itself; no client library.
- **Pull-friendly.** Gauges that mirror existing state (queue depth, channel
  occupancy) register as callbacks/collectors evaluated at scrape time, so
  steady-state cost is zero when nobody is looking.
"""

from __future__ import annotations

import bisect
import math
import re
import threading

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Default histogram buckets: wall-clock seconds from 50 µs (a queue state
# transition) to 30 s (a cold jit compile), roughly x2.5 per step.
LATENCY_BUCKETS_S = (
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_help(text: str) -> str:
    """HELP-line escaping (exposition format v0.0.4): backslash and
    newline only — a raw newline would terminate the HELP line mid-text
    and feed the remainder to the scraper as a garbage sample line."""
    return text.replace("\\", r"\\").replace("\n", r"\n")


def _render_labels(key: tuple, extra: tuple = ()) -> str:
    pairs = list(key) + list(extra)
    if not pairs:
        return ""
    body = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", r"\\").replace('"', r"\"")
                     .replace("\n", r"\n"))
        for k, v in pairs)
    return "{" + body + "}"


class Counter:
    """Monotonic float counter."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Instantaneous value; ``set`` or a scrape-time callback."""

    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self, fn=None):
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn = fn

    def set(self, v: float) -> None:
        # Under the lock like inc/dec (dbxlint lock-discipline): a set
        # racing an inc on another thread must not lose the increment to
        # a stale read-modify-write interleaving.
        with self._lock:
            self._value = float(v)

    def set_fn(self, fn) -> None:
        """Evaluate ``fn()`` at scrape time instead of a stored value."""
        self._fn = fn

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return math.nan
        return self._value


def histogram_quantile(counts, bounds, q: float,
                       upper: float | None = None) -> float:
    """Rank-interpolated quantile over per-bucket counts — the ONE
    scrape-side estimate, shared by :class:`Histogram` and the fleet
    telemetry fold (obs.fleet), whose wire-form frames carry the same
    per-bucket counts over the same bounds. ``upper`` bounds the
    overflow (+inf) bucket: a tracked max when the caller has one,
    ``None`` caps at the last finite bound (a merged wire histogram has
    no max to offer)."""
    count = sum(counts)
    if not count:
        return 0.0
    rank = q * count
    acc = 0
    lo = 0.0
    for i, c in enumerate(counts):
        if acc + c >= rank:
            if i < len(bounds):
                hi = bounds[i]
            else:
                hi = upper if upper is not None else lo
            if c == 0:
                return hi
            return lo + (hi - lo) * (rank - acc) / c
        acc += c
        if i < len(bounds):
            lo = bounds[i]
    return upper if upper is not None else lo


class Histogram:
    """Fixed-bucket histogram (cumulative ``le`` buckets, Prometheus-style).

    Tracks count, sum, max, and per-bucket counts. Quantiles in
    :meth:`summary` are estimated by linear interpolation inside the
    bucket that crosses the rank — the standard scrape-side estimate,
    computed here so ``stats()``/JSON consumers need no PromQL.
    """

    __slots__ = ("_lock", "buckets", "_counts", "count", "sum", "max")

    def __init__(self, buckets=LATENCY_BUCKETS_S):
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = b
        self._lock = threading.Lock()
        self._counts = [0] * (len(b) + 1)   # +inf bucket last
        self.count = 0
        self.sum = 0.0
        self.max = 0.0

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += v
            if v > self.max:
                self.max = v

    def _quantile(self, counts, q: float, count: int, mx: float) -> float:
        # counts must be a locked snapshot (count/mx ride along for the
        # callers' convenience; the shared estimator re-derives the
        # total from the same snapshot). `mx or None`: a zero max means
        # nothing real landed in the overflow bucket — cap at the last
        # finite bound like the wire-form fold does.
        return histogram_quantile(counts, self.buckets, q,
                                  upper=mx or None)

    def summary(self) -> dict:
        """JSON-able digest: count/sum/avg/max + estimated p50/p90/p99."""
        with self._lock:
            counts = list(self._counts)
            count, total, mx = self.count, self.sum, self.max
        if not count:
            return {"count": 0, "sum": 0.0}
        out = {"count": count, "sum": round(total, 9),
               "avg": round(total / count, 9), "max": round(mx, 9)}
        for q, name in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
            out[name] = round(self._quantile(counts, q, count, mx), 9)
        return out

    def cumulative(self) -> list[tuple[float, int]]:
        """[(le_bound, cumulative_count), ...] ending with (+inf, count)."""
        with self._lock:
            counts = list(self._counts)
        out = []
        acc = 0
        for bound, c in zip(self.buckets, counts):
            acc += c
            out.append((bound, acc))
        out.append((math.inf, acc + counts[-1]))
        return out


class _Family:
    """One metric name: kind, help text, and children keyed by label set."""

    __slots__ = ("kind", "help", "buckets", "children")

    def __init__(self, kind: str, help: str, buckets=None):
        self.kind = kind
        self.help = help
        self.buckets = buckets
        self.children: dict[tuple, object] = {}


class Registry:
    """Named metric families with label-keyed children.

    ``counter``/``gauge``/``histogram`` are get-or-create and return the
    child object directly — hold the reference on hot paths. Collectors
    (``add_collector``) run once per render/snapshot to refresh gauges
    that mirror external state.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}
        self._collectors: dict[str, object] = {}

    # -- construction ------------------------------------------------------

    def _child(self, kind: str, name: str, help: str, labels: dict,
               factory, buckets=None):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for k in labels:
            if not _LABEL_RE.match(str(k)):
                raise ValueError(f"invalid label name {k!r}")
        key = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(kind, help, buckets)
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"not {kind}")
            child = fam.children.get(key)
            if child is None:
                child = fam.children[key] = factory()
            return child

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._child("counter", name, help, labels, Counter)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._child("gauge", name, help, labels, Gauge)

    def gauge_fn(self, name: str, fn, help: str = "", **labels) -> Gauge:
        """Gauge whose value is ``fn()`` at scrape time (replaces any
        previous callback on the same name+labels — re-registration is how
        a restarted component takes over its gauge)."""
        g = self.gauge(name, help, **labels)
        g.set_fn(fn)
        return g

    def histogram(self, name: str, help: str = "",
                  buckets=LATENCY_BUCKETS_S, **labels) -> Histogram:
        return self._child("histogram", name, help, labels,
                           lambda: Histogram(buckets), buckets)

    def peek(self, name: str, **labels):
        """Read one labeled counter/gauge value WITHOUT creating it
        (None when the family or child does not exist, AND for
        histogram children — a histogram has no single value; use
        its ``summary()`` via the family accessor instead) — the
        read-only probe for consumers (the fleet telemetry frame) that
        must not mint zero-valued series on processes that never
        recorded them."""
        key = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            child = fam.children.get(key) if fam is not None else None
        if child is None:
            return None
        return child.value if not isinstance(child, Histogram) else None

    def remove_child(self, name: str, **labels) -> None:
        """Drop one labeled child (and its family once empty) — lifecycle
        hygiene for per-instance label sets (e.g. per-worker gauges) in
        long-lived processes that construct many instances."""
        key = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return
            fam.children.pop(key, None)
            if not fam.children:
                del self._families[name]

    def add_collector(self, key: str, fn) -> None:
        """Run ``fn(registry)`` once per render/snapshot, BEFORE reading
        metrics — the hook for refreshing gauges that mirror external
        state (queue depth, channel occupancy). Keyed so a restarted
        component replaces its predecessor instead of stacking stale
        closures."""
        with self._lock:
            self._collectors[key] = fn

    def remove_collector(self, key: str) -> None:
        with self._lock:
            self._collectors.pop(key, None)

    # -- reading -----------------------------------------------------------

    def _run_collectors(self) -> None:
        with self._lock:
            items = list(self._collectors.items())
        for key, fn in items:
            with self._lock:
                # Skip collectors removed since the snapshot: a component
                # tearing down mid-scrape must not have its collector run
                # after its cleanup.
                if self._collectors.get(key) is not fn:
                    continue
            try:
                fn(self)
            except Exception:
                pass   # a dead component's collector must not kill scrapes

    def _families_snapshot(self) -> list:
        """(name, kind, help, children-items) copied under the lock: a
        worker thread first-observing a new label set mid-scrape must not
        blow up the iteration (dict-changed-size)."""
        with self._lock:
            return [(name, fam.kind, fam.help,
                     sorted(fam.children.items()))
                    for name, fam in sorted(self._families.items())]

    def render_prometheus(self) -> str:
        """Prometheus text exposition format v0.0.4."""
        self._run_collectors()
        lines: list[str] = []
        for name, kind, help_, children in self._families_snapshot():
            if help_:
                lines.append(f"# HELP {name} {_escape_help(help_)}")
            lines.append(f"# TYPE {name} {kind}")
            for key, child in children:
                if kind == "histogram":
                    for bound, acc in child.cumulative():
                        le = "+Inf" if math.isinf(bound) else repr(bound)
                        lines.append(
                            f"{name}_bucket"
                            f"{_render_labels(key, (('le', le),))} {acc}")
                    lines.append(
                        f"{name}_sum{_render_labels(key)} {child.sum}")
                    lines.append(
                        f"{name}_count{_render_labels(key)} {child.count}")
                else:
                    v = child.value
                    lines.append(f"{name}{_render_labels(key)} {v}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Full JSON-able state: every family, every labeled child.

        Counters/gauges map to values; histograms to :meth:`summary`
        digests. Child keys render as ``name`` or ``name{k=v,...}``.
        """
        self._run_collectors()
        out: dict = {}
        for name, kind, _help, children in self._families_snapshot():
            entry: dict = {}
            for key, child in children:
                label = ",".join(f"{k}={v}" for k, v in key)
                if kind == "histogram":
                    entry[label] = child.summary()
                else:
                    entry[label] = child.value
            out[name] = {"type": kind, "values": entry}
        return out

    def summaries(self, prefix: str = "") -> dict:
        """Compact digest for the wire (GetStats ``obs_json``): flat
        ``name{labels}`` keys, values for counters/gauges, summary dicts
        for histograms. ``prefix`` filters by metric-name prefix."""
        snap = self.snapshot()
        out: dict = {}
        for name, fam in snap.items():
            if prefix and not name.startswith(prefix):
                continue
            for label, v in fam["values"].items():
                key = f"{name}{{{label}}}" if label else name
                out[key] = v
        return out


_REGISTRY = Registry()


def get_registry() -> Registry:
    """The process-global default registry."""
    return _REGISTRY
