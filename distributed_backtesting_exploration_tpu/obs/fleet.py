"""Fleet telemetry plane: staleness-bounded worker-state gossip + `dbxtop`.

Every obs surface before this round is per-process — the dispatcher can
describe its queue and a worker its caches, but nobody answers "what is
the fleet doing right now, and which worker is the problem?". This
module closes that gap with the PR-10 gossip discipline (piggyback
compact deltas on the polls that already flow, merge deterministically
on the dispatcher, no extra coordinator):

- **worker side** (:class:`WorkerTelemetry`): each poll attaches a
  compact telemetry frame to ``JobsRequest.telemetry_json`` — monotone
  counters, per-stage cost EWMAs + fixed-bucket histograms (fed by a
  span listener over the existing ``worker.decode`` /
  ``worker.compile`` / ``worker.execute`` / ``worker.d2h``
  instrumentation), cache residency summaries (counts + byte totals + a
  small top-K digest sketch, never full key lists), pipeline depth and
  backend capability flags. A frame rides only when something changed
  or the heartbeat interval elapsed (``DBX_FLEET_HEARTBEAT_S``) — the
  schedule-gossip dirty-bit style, so a clean poll costs zero wire
  bytes.

- **dispatcher side** (:class:`FleetView`): merges frames under a
  staleness bound (``DBX_FLEET_STALE_S``; stale workers are flagged,
  then evicted by the maintenance loop's prune path), folds per-worker
  stage histograms into fleet-wide fixed-bucket histograms (the bucket
  bounds are shared — the merge is EXACT, tested against a
  single-process registry), computes fleet rollups (jobs/s, stage
  p50/p95, cache hit ratios) and straggler flags (per-stage EWMA above
  the fleet p95 — the PR-4 timeline rule applied live), and serves
  everything on ``/fleet.json``, GetStats ``obs_json`` and the
  :func:`main` CLI (``dbxtop``: one-shot table or ``--watch`` refresh).

**Merge determinism contract**: a :meth:`FleetView.snapshot` is a pure
function of (latest frame per worker, now) — frames carry their own
worker-computed rates and a total order (``gen``/``seq``/``t``), so the
same frame set arriving in ANY order yields byte-identical snapshots.
This is what lets ROADMAP item 3's placement scorer (and any future
shard-to-shard gossip) trust the view.

**Cardinality bounds**: worker identity on metric labels goes through
``sched.tenancy.worker_bucket`` (first ``DBX_WORKER_LABEL_MAX`` workers
keep their name, the rest share ``other``) — the dbxlint
obs-cardinality sanctioned source; the JSON surfaces (frames,
``/fleet.json``) carry full ids, which are per-document, not
per-series.
"""

from __future__ import annotations

import argparse
import collections
import json
import math
import os
import sys
import threading
import time
import uuid

from .registry import LATENCY_BUCKETS_S, get_registry, histogram_quantile
from . import costmodel as costmodel_mod
from . import trace

# ---------------------------------------------------------------------------
# Knobs (all read lazily — never at import)
# ---------------------------------------------------------------------------


def telemetry_enabled() -> bool:
    """``DBX_FLEET_TELEMETRY`` (default on): workers attach telemetry
    frames to their polls. ``0`` is the kill switch (the bench A/B's
    off arm)."""
    return os.environ.get("DBX_FLEET_TELEMETRY", "1").lower() not in (
        "0", "off", "false")


def heartbeat_s() -> float:
    """``DBX_FLEET_HEARTBEAT_S`` (default 2.0): the longest a worker
    stays frame-silent while nothing changes. Bounds frame age on an
    idle fleet, so dispatcher-side staleness is always a liveness
    signal, never just quiet."""
    return float(os.environ.get("DBX_FLEET_HEARTBEAT_S", 2.0))


def frame_min_s() -> float:
    """``DBX_FLEET_FRAME_MIN_S`` (default 0.2): minimum seconds between
    frames from one worker. A SATURATED worker is dirty on every poll
    (its job counter moved), and rebuilding cache residency summaries
    per 4 ms poll would burn the control plane for telemetry nobody can
    read that fast — this floor caps gossip at ~5 frames/s/worker while
    keeping frame age far inside the staleness bound. 0 restores
    frame-per-dirty-poll."""
    return float(os.environ.get("DBX_FLEET_FRAME_MIN_S", 0.2))


def stale_s() -> float:
    """``DBX_FLEET_STALE_S`` (default 10.0): frame age past which a
    worker's fleet-view entry is flagged stale (rollups exclude it);
    past 3x the bound the prune path evicts the entry entirely. The
    default matches the peer registry's prune window — a worker whose
    frames stopped is a worker whose polls stopped."""
    return float(os.environ.get("DBX_FLEET_STALE_S", 10.0))


def slo_burn_threshold() -> float:
    """``DBX_FLEET_SLO_BURN`` (default 0.1): queue-wait SLO breach
    fraction over a burn window above which that window's
    ``dbx_fleet_slo_burn_total`` counter ticks."""
    return float(os.environ.get("DBX_FLEET_SLO_BURN", 0.1))


#: The stages a telemetry frame costs out — exactly the span names the
#: PR-4 worker instrumentation already emits, folded onto the timeline
#: analyzer's stage vocabulary.
TELEMETRY_STAGES = ("decode", "compile", "execute", "d2h")

_SPAN_TO_STAGE = {
    "worker.decode": "decode",
    "worker.prefetch": "decode",
    "worker.compile": "compile",
    "worker.execute": "execute",
    "worker.append": "execute",
    "worker.d2h": "d2h",
}

#: Shared fixed bucket bounds: worker-side accumulation and the
#: dispatcher-side fold use the SAME bounds, which is what makes the
#: fleet histogram merge exact (summing per-bucket counts commutes).
STAGE_BUCKETS_S = LATENCY_BUCKETS_S

# Straggler rule (the PR-4 timeline rule applied live): a worker whose
# per-stage EWMA exceeds the fleet p95 for that stage, once the merged
# stage has a real population. The margin absorbs the fixed-bucket
# quantile's interpolation granularity — a worker sitting exactly AT
# the fleet p95 (the bulk of a healthy uniform fleet) must not flap in
# and out of the flag on bucket-boundary noise.
MIN_STRAGGLER_OBS = 8
MIN_STRAGGLER_WORKERS = 2
STRAGGLER_MARGIN = 1.25

_EWMA_ALPHA = 0.25

# Multi-window SLO burn (the SRE fast/slow-burn pair) over the PR-8
# queue-wait SLO: breach fraction per window vs DBX_FLEET_SLO_BURN.
SLO_WINDOWS = {"5m": 300.0, "1h": 3600.0}
_SLO_BUCKET_S = 10.0


# ---------------------------------------------------------------------------
# Worker side: process stage stats + per-worker frames
# ---------------------------------------------------------------------------


class _StageStats:
    """Per-stage cost accumulators fed by the completed-span stream.

    PROCESS-scoped (one span listener, however many Workers the process
    hosts — the registry-histogram precedent): frames from co-hosted
    workers carry identical stage stats plus their process identity
    (``pid`` + the host-unique ``proc_id`` token), and the fleet fold
    dedupes per process so co-hosting never double-counts.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._stats = {
            s: {"n": 0, "sum_s": 0.0, "ewma_s": 0.0,
                "buckets": [0] * (len(STAGE_BUCKETS_S) + 1)}
            for s in TELEMETRY_STAGES}
        self.version = 0      # bumps per observation — the dirty signal

    def observe(self, rec: dict) -> None:
        stage = _SPAN_TO_STAGE.get(rec.get("name", ""))
        if stage is None:
            return
        dur = float(rec.get("dur_s", 0.0))
        i = 0
        while i < len(STAGE_BUCKETS_S) and dur > STAGE_BUCKETS_S[i]:
            i += 1
        with self._lock:
            st = self._stats[stage]
            st["n"] += 1
            st["sum_s"] += dur
            st["ewma_s"] = (dur if st["n"] == 1 else
                            _EWMA_ALPHA * dur
                            + (1.0 - _EWMA_ALPHA) * st["ewma_s"])
            st["buckets"][i] += 1
            self.version += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {s: {"n": st["n"], "sum_s": round(st["sum_s"], 9),
                        "ewma_s": round(st["ewma_s"], 9),
                        "buckets": list(st["buckets"])}
                    for s, st in self._stats.items()}


_stage_stats: _StageStats | None = None
_stage_stats_lock = threading.Lock()

#: Host/boot-unique process token carried in every frame beside ``pid``:
#: the dispatcher's per-process dedupe of process-scope data (stage
#: streams, cache hit counters) keys on THIS, because bare OS pids
#: collide across hosts — in a containerized fleet every worker process
#: is pid 1, and pid-keyed dedupe would silently collapse the whole
#: fleet's stats into one worker's stream.
_PROC_TOKEN = uuid.uuid4().hex[:16]


def stage_stats() -> _StageStats:
    """The process-wide stage collector, listener installed on first use
    (bounded state: 4 stages x one bucket list — kept for the process
    lifetime, like the registry's span histograms)."""
    global _stage_stats
    with _stage_stats_lock:
        if _stage_stats is None:
            _stage_stats = _StageStats()
            trace.add_span_listener("fleet-stages", _stage_stats.observe)
        return _stage_stats


# Process-scope cache hit/miss counter families sampled into frames
# (read-only peeks — a worker that never created a family reports
# nothing, and no zero-valued series is minted).
_PROC_HIT_COUNTERS = {
    "panel_host": (("dbx_panel_cache_hits_total", {"level": "host"}),
                   ("dbx_panel_cache_misses_total", {"level": "host"})),
    "panel_device": (("dbx_panel_cache_hits_total", {"level": "device"}),
                     ("dbx_panel_cache_misses_total", {"level": "device"})),
    "carry_device": (("dbx_carry_cache_hits_total", {"level": "device"}),
                     ("dbx_carry_cache_misses_total", {"level": "device"})),
    "carry_host": (("dbx_carry_cache_hits_total", {"level": "host"}),
                   ("dbx_carry_cache_misses_total", {"level": "host"})),
}
_PAGE_FIELDS = ("open", "high", "low", "close", "volume")


class WorkerTelemetry:
    """Builds one worker's telemetry frames (the ``telemetry_json`` leg).

    ``stats_fn`` is the owning worker's counter snapshot hook (a dict of
    ``jobs_completed`` / ``completions_dropped`` / ``polls`` / ``busy``
    / ``inflight`` / ``pipeline_on`` / ``pipeline_depth``); ``backend``
    supplies capability flags + cache residency via its optional
    ``telemetry()``. Frames are canonical (sorted keys, rounded floats)
    so the dispatcher's merge can be byte-deterministic.
    """

    # Windowed rate: frames carry a worker-computed jobs/s over roughly
    # this many seconds, so the fleet view needs no cross-frame state
    # (the merge-determinism contract).
    RATE_WINDOW_S = 10.0

    def __init__(self, worker_id: str, *, stats_fn=None, backend=None,
                 registry=None, stages=None, costmodel=None):
        self.worker_id = worker_id
        self.gen = uuid.uuid4().hex[:16]
        self._stats_fn = stats_fn
        self._backend = backend
        self._reg = registry or get_registry()
        # `stages` overrides the process-wide span-fed collector — for
        # probes/tests that carry their own stage stream (a bench's
        # artificially slowed worker). The frame marks which scope its
        # stage stats describe, so the dispatcher's per-pid fold knows
        # whether co-hosted frames share one stream.
        self._stages_scope = "proc" if stages is None else "worker"
        self._stages = stages if stages is not None else stage_stats()
        # The cost-model drift accumulator rides the same frames
        # (process-scoped by default, like the stage stats; an injected
        # tracker follows the `stages` probe discipline). A probe-scoped
        # frame (scope="worker") bypasses the dispatcher's per-process
        # dedupe, so it may only carry costmodel data it owns — the
        # shared process tracker would double-count in the fleet fold.
        self._costmodel_own = costmodel is not None
        self._costmodel = (costmodel if costmodel is not None
                           else costmodel_mod.tracker())
        self._lock = threading.Lock()
        self._seq = 0
        self._t0 = time.time()
        self._last_sent = 0.0
        self._last_fingerprint = None
        self._rate_ring: collections.deque = collections.deque(maxlen=64)
        self._c_frames = self._reg.counter(
            "dbx_worker_telemetry_frames_total",
            help="telemetry frames attached to polls")
        self._c_bytes = self._reg.counter(
            "dbx_worker_telemetry_bytes_total",
            help="serialized telemetry frame bytes attached to polls")

    def _worker_stats(self) -> dict:
        base = {"jobs_completed": 0, "completions_dropped": 0, "polls": 0,
                "busy": 0, "inflight": 0, "pipeline_on": False,
                "pipeline_depth": 0}
        if self._stats_fn is not None:
            base.update(self._stats_fn())
        return base

    def _backend_telemetry(self) -> dict:
        b = self._backend
        if b is None:
            return {"caps": {}, "caches": {}}
        tel = getattr(b, "telemetry", None)
        if callable(tel):
            try:
                out = tel()
                return {"caps": dict(out.get("caps", {})),
                        "caches": dict(out.get("caches", {}))}
            except Exception:
                pass   # a backend's telemetry must never fail a poll
        return {"caps": {"backend": type(b).__name__,
                         "chips": int(getattr(b, "chips", 0) or 0)},
                "caches": {}}

    def _proc_counters(self) -> dict:
        out = {}
        for key, ((hname, hlabels),
                  (mname, mlabels)) in _PROC_HIT_COUNTERS.items():
            h = self._reg.peek(hname, **hlabels)
            m = self._reg.peek(mname, **mlabels)
            if h is None and m is None:
                continue
            out[key] = [int(h or 0), int(m or 0)]
        ph = pm = None
        for f in _PAGE_FIELDS:
            h = self._reg.peek("dbx_page_pool_hits_total", field=f)
            m = self._reg.peek("dbx_page_pool_misses_total", field=f)
            if h is not None or m is not None:
                ph = (ph or 0) + int(h or 0)
                pm = (pm or 0) + int(m or 0)
        if ph is not None:
            out["page_pool"] = [ph, pm or 0]
        return out

    def _jobs_per_s(self, now: float, jobs: int) -> float:
        """Windowed completion rate, computed worker-side so the frame
        is self-contained (see RATE_WINDOW_S)."""
        ring = self._rate_ring
        ring.append((now, jobs))
        t_lo, j_lo = ring[0]
        for t, j in ring:
            if now - t <= self.RATE_WINDOW_S:
                t_lo, j_lo = t, j
                break
        if now - t_lo <= 0:
            return 0.0
        return max(jobs - j_lo, 0) / (now - t_lo)

    def frame(self, now: float | None = None) -> dict:
        """One full telemetry frame (the ``telemetry_json`` payload)."""
        now = time.time() if now is None else now
        return self._build_frame(now, self._worker_stats(),
                                 self._backend_telemetry())

    def _build_frame(self, now: float, ws: dict, bt: dict) -> dict:
        with self._lock:
            self._seq += 1
            seq = self._seq
        cm = (self._costmodel.frame()
              if self._stages_scope == "proc" or self._costmodel_own
              else {})
        frame = {
            "v": 1,
            "gen": self.gen,
            "pid": os.getpid(),
            "proc_id": _PROC_TOKEN,
            "scope": self._stages_scope,
            "seq": seq,
            "t": round(now, 3),
            "uptime_s": round(now - self._t0, 3),
            "busy": int(ws["busy"]),
            "inflight": int(ws["inflight"]),
            "pipeline": {"on": bool(ws["pipeline_on"]),
                         "depth": int(ws["pipeline_depth"])},
            "jobs_completed": int(ws["jobs_completed"]),
            "completions_dropped": int(ws["completions_dropped"]),
            "polls": int(ws["polls"]),
            "jobs_per_s": round(self._jobs_per_s(
                now, int(ws["jobs_completed"])), 4),
            "caps": bt["caps"],
            "caches": bt["caches"],
            "proc": self._proc_counters(),
            "stages": self._stages.snapshot(),
        }
        if cm:
            # Only when residuals exist — a drift-silent worker's frame
            # carries zero extra wire bytes (the dirty-bit budget).
            frame["costmodel"] = cm
        return frame

    @staticmethod
    def _fingerprint(ws: dict, bt: dict, stage_version: int,
                     cm_version: int = 0) -> tuple:
        """The change detector behind the dirty bit: worker counters +
        stage-stat version + cost-model residual version + cache
        residency. Deliberately EXCLUDES the poll count (every poll
        polls — counting it as change would defeat the dirty bit) and
        wall-clock-derived fields."""
        return (ws["jobs_completed"], ws["completions_dropped"],
                ws["busy"], ws["inflight"], stage_version, cm_version,
                json.dumps(bt["caches"], sort_keys=True, default=str))

    def take_frame_json(self, now: float | None = None) -> str:
        """The poll hook: a canonical-JSON frame when dirty or the
        heartbeat elapsed — rate-floored at ``DBX_FLEET_FRAME_MIN_S`` —
        else ``""`` (zero wire cost). The worker and backend stats are
        sampled ONCE and shared by the fingerprint and the frame — this
        runs on the poll path, inside the <=5% telemetry-overhead
        budget. The caller re-marks with :meth:`remark_dirty` when the
        poll RPC fails."""
        now = time.time() if now is None else now
        with self._lock:
            # Rate floor FIRST, before any stats are sampled: on a
            # saturated fleet every poll is dirty, and this early exit
            # is what keeps the suppressed-poll path at ~a lock acquire
            # (the <=5% overhead budget's real guardian).
            if now - self._last_sent < frame_min_s():
                return ""
        ws = self._worker_stats()
        bt = self._backend_telemetry()
        fp = self._fingerprint(ws, bt, self._stages.version,
                               self._costmodel.version)
        hb = heartbeat_s()
        with self._lock:
            if (fp == self._last_fingerprint
                    and now - self._last_sent < hb):
                return ""
        payload = json.dumps(self._build_frame(now, ws, bt),
                             sort_keys=True,
                             separators=(",", ":"), default=str)
        with self._lock:
            # Double-checked under the second acquisition: a racing
            # caller (only the control thread calls this in the worker,
            # but the class makes no such assumption) that committed the
            # same fingerprint meanwhile wins; this frame stays unsent.
            if (fp == self._last_fingerprint
                    and now - self._last_sent < hb):
                return ""
            self._last_fingerprint = fp
            self._last_sent = now
        self._c_frames.inc()
        self._c_bytes.inc(len(payload))
        return payload

    def remark_dirty(self) -> None:
        """The drained frame never reached the dispatcher (RPC failure):
        resend on the next successful poll — the schedule registry's
        ``remark_dirty`` twin."""
        with self._lock:
            self._last_fingerprint = None


# ---------------------------------------------------------------------------
# Dispatcher side: the fleet view
# ---------------------------------------------------------------------------


class _Entry:
    __slots__ = ("frame", "last_seen", "flagged")

    def __init__(self, frame: dict, last_seen: float):
        self.frame = frame
        self.last_seen = last_seen
        self.flagged: set = set()    # stages already counted as straggler


def _frame_order(frame: dict) -> tuple:
    """Cross-generation precedence: wall stamp then generation id (a
    total order — merge outcome independent of arrival order)."""
    return (float(frame.get("t", 0.0)), str(frame.get("gen", "")))


def _finite(x) -> float:
    """``float(x)``, rejecting NaN/Infinity — Python's json.loads parses
    bare NaN tokens, a NaN would defeat ``_frame_order`` (every
    comparison False) and re-serialize as invalid JSON on /fleet.json."""
    v = float(x)
    if not math.isfinite(v):
        raise ValueError(f"non-finite frame value {x!r}")
    return v


#: Every frame key this build knows how to read. Anything else is a
#: FUTURE field from a newer worker (the mixed-fleet rollout case):
#: skipped and counted, never a malformed frame — forward compat is
#: what let this build's own ``costmodel`` key roll out.
_KNOWN_FRAME_KEYS = frozenset({
    "v", "gen", "pid", "proc_id", "scope", "seq", "t", "uptime_s",
    "busy", "inflight", "pipeline", "jobs_completed",
    "completions_dropped", "polls", "jobs_per_s", "caps", "caches",
    "proc", "stages", "costmodel"})


def _sanitize_frame(frame: dict) -> dict:
    """Coerce a decoded frame's typed fields AT INGEST, so one
    JSON-valid frame with an ill-typed or non-finite field (a hostile
    or buggy worker's ``"busy": "yes"`` or ``"jobs_per_s": NaN``) lands
    in the malformed path instead of being adopted and poisoning every
    later :meth:`FleetView.snapshot` — the "malformed frames teach
    nothing, never an RPC error" contract applies to types, not just
    JSON syntax. Raises (caught by the caller) on anything
    uncoercible. Keys outside ``_KNOWN_FRAME_KEYS`` (a NEWER worker's
    fields) are skipped-and-counted, not errors."""
    out = dict(frame)
    unknown = sorted(str(k) for k in frame if k not in _KNOWN_FRAME_KEYS)
    if unknown:
        out["unknown_fields"] = unknown
    out["gen"] = str(frame["gen"])
    out["pid"] = int(frame.get("pid", 0))
    out["proc_id"] = str(frame.get("proc_id", ""))
    out["scope"] = str(frame.get("scope", "proc"))
    out["seq"] = int(frame.get("seq", 0))
    out["t"] = _finite(frame.get("t", 0.0))
    out["uptime_s"] = _finite(frame.get("uptime_s", 0.0))
    for k in ("busy", "inflight", "jobs_completed",
              "completions_dropped", "polls"):
        out[k] = int(frame.get(k, 0))
    out["jobs_per_s"] = _finite(frame.get("jobs_per_s", 0.0))
    for k in ("pipeline", "caps", "caches", "proc"):
        out[k] = dict(frame.get(k) or {})
    stages = {}
    for s, st in dict(frame.get("stages") or {}).items():
        st = dict(st)
        stages[str(s)] = {
            "n": int(st.get("n", 0)),
            "sum_s": _finite(st.get("sum_s", 0.0)),
            "ewma_s": _finite(st.get("ewma_s", 0.0)),
            "buckets": [int(c) for c in st.get("buckets", [])],
        }
    out["stages"] = stages
    cm = frame.get("costmodel")
    if cm:
        cm = dict(cm)
        out["costmodel"] = {
            "n": int(cm.get("n", 0)),
            "ewma": _finite(cm.get("ewma", 0.0)),
            "buckets": [int(c) for c in cm.get("buckets", [])],
            "blowouts": int(cm.get("blowouts", 0)),
        }
    else:
        out.pop("costmodel", None)
    return out


def _hist_quantile(buckets: list, q: float) -> float:
    """Quantile estimate over per-bucket counts with the shared
    STAGE_BUCKETS_S bounds — the registry Histogram's ONE interpolation
    (`registry.histogram_quantile`), on the wire form (no tracked max,
    so the overflow bucket caps at the last finite bound)."""
    return histogram_quantile(buckets, STAGE_BUCKETS_S, q)


class FleetView:
    """The dispatcher's staleness-bounded merged view of worker state.

    Entries are keyed by worker id and superseded by frame precedence
    (same generation: higher ``seq``; across generations: higher wall
    stamp, ties to generation id) — a deterministic total order, so the
    merged view is independent of frame arrival order. ``snapshot`` is
    a pure function of (retained frames, now): it mutates nothing.

    Staleness: a worker whose newest frame is older than the bound
    (``DBX_FLEET_STALE_S``; dispatcher clock) is flagged ``stale`` and
    excluded from fleet rollups; :meth:`prune` (called from the
    dispatcher's maintenance loop beside the peer prune) evicts entries
    older than 3x the bound, and :meth:`forget` drops a pruned peer's
    entry immediately.
    """

    EVICT_MULTIPLE = 3.0

    def __init__(self, *, registry=None, stale_s_override: float | None = None,
                 clock=time.monotonic):
        self._reg = registry or get_registry()
        self._clock = clock
        self._stale_override = stale_s_override
        self._lock = threading.Lock()
        self._entries: dict[str, _Entry] = {}
        # Worker-label buckets whose per-worker gauges were set by the
        # last collect() — the removal set for evicted/forgotten workers
        # (a dead series must not serve its last value forever).
        self._gauge_buckets: set = set()
        # (clock stamp, snapshot) from the last collect(): GetStats
        # reuses it instead of building the full merged view twice per
        # call (summaries() already ran the collector).
        self._last_collect: tuple | None = None
        self._frame_sizes: collections.deque = collections.deque(
            maxlen=4096)
        # SLO burn ring: fixed-width time buckets of (ok, breach) counts
        # covering the largest burn window.
        self._slo_buckets: collections.deque = collections.deque(
            maxlen=int(max(SLO_WINDOWS.values()) / _SLO_BUCKET_S) + 1)
        self._c_frames = {
            o: self._reg.counter("dbx_fleet_frames_total",
                                 help="telemetry frames received, by "
                                      "outcome",
                                 outcome=o)
            for o in ("ok", "superseded", "malformed")}
        self._c_evicted = self._reg.counter(
            "dbx_fleet_workers_evicted_total",
            help="fleet-view entries evicted for staleness")
        self._c_unknown = self._reg.counter(
            "dbx_fleet_frame_unknown_fields_total",
            help="frame fields this build did not recognize (newer "
                 "workers in a mixed fleet) — skipped, not malformed")
        self._c_straggler = {
            s: self._reg.counter("dbx_fleet_straggler_flags_total",
                                 help="workers newly flagged as stage "
                                      "stragglers (EWMA > fleet p95)",
                                 stage=s)
            for s in TELEMETRY_STAGES}
        self._c_slo_burn = {
            w: self._reg.counter("dbx_fleet_slo_burn_total",
                                 help="scrapes that found the queue-wait "
                                      "SLO breach fraction over this "
                                      "window above DBX_FLEET_SLO_BURN",
                                 window=w)
            for w in SLO_WINDOWS}

    def _stale_bound(self) -> float:
        return (self._stale_override if self._stale_override is not None
                else stale_s())

    # -- ingest ------------------------------------------------------------

    def update(self, worker_id: str, frame_json: str) -> bool:
        """Merge one worker's frame (the RequestJobs gossip leg).
        Malformed payloads teach nothing — counted, never an RPC error.
        Returns True when the frame was adopted."""
        if not frame_json:
            return False
        try:
            frame = json.loads(frame_json)
            if not isinstance(frame, dict) or "gen" not in frame:
                raise ValueError("not a telemetry frame")
            frame = _sanitize_frame(frame)
        except (ValueError, TypeError, AttributeError, KeyError,
                OverflowError):   # int(Infinity) overflows, not ValueErrors
            self._c_frames["malformed"].inc()
            return False
        now = self._clock()
        with self._lock:
            self._frame_sizes.append(len(frame_json))
            cur = self._entries.get(worker_id)
            if cur is not None:
                if frame.get("gen") == cur.frame.get("gen"):
                    newer = (int(frame.get("seq", 0))
                             > int(cur.frame.get("seq", 0)))
                else:
                    # Cross-generation wall-stamp precedence — with one
                    # escape hatch: a live restarted worker whose clock
                    # stepped BACKWARD across the restart must not be
                    # wedged behind its dead generation. Once the
                    # retained entry is itself past the staleness bound,
                    # any differing-generation frame supersedes it (the
                    # old gen stopped gossiping; the new one is talking
                    # right now).
                    newer = (_frame_order(frame) > _frame_order(cur.frame)
                             or now - cur.last_seen > self._stale_bound())
                if not newer:
                    self._c_frames["superseded"].inc()
                    return False
                cur.frame = frame
                cur.last_seen = now
            else:
                self._entries[worker_id] = _Entry(frame, now)
        self._c_frames["ok"].inc()
        unknown = frame.get("unknown_fields")
        if unknown:
            self._c_unknown.inc(len(unknown))
        return True

    def forget(self, worker_id: str) -> None:
        """Drop a pruned peer's entry (the dispatcher's peer-prune path
        — silence already proved the worker gone)."""
        with self._lock:
            self._entries.pop(worker_id, None)

    def prune(self) -> list[str]:
        """Evict entries whose frame age passed ``EVICT_MULTIPLE`` x the
        staleness bound; returns the evicted worker ids. Called from the
        dispatcher's maintenance loop beside the peer prune (a stale
        entry survives flagged until then — visible decay, then gone)."""
        cutoff = self._clock() - self.EVICT_MULTIPLE * self._stale_bound()
        with self._lock:
            dead = [wid for wid, e in self._entries.items()
                    if e.last_seen < cutoff]
            for wid in dead:
                del self._entries[wid]
        if dead:
            self._c_evicted.inc(len(dead))
        return dead

    def observe_slo(self, breach: bool) -> None:
        """One queue-wait SLO observation (the PR-8 per-tenant burn
        pair's fleet-wide feed) into the burn-window ring."""
        now = self._clock()
        bucket = int(now / _SLO_BUCKET_S)
        with self._lock:
            if not self._slo_buckets or self._slo_buckets[-1][0] != bucket:
                self._slo_buckets.append([bucket, 0, 0])
            self._slo_buckets[-1][2 if breach else 1] += 1

    def frame_sizes(self) -> list[int]:
        """Recent received-frame byte sizes (bounded) — the bench's
        ``frame_bytes_p50`` instrument."""
        with self._lock:
            return list(self._frame_sizes)

    # -- the merged view ---------------------------------------------------

    def _copy_entries(self) -> dict[str, tuple[dict, float]]:
        with self._lock:
            return {wid: (e.frame, e.last_seen)
                    for wid, e in self._entries.items()}

    @staticmethod
    def _dedupe_by_pid(frames: list[tuple[str, dict]]) -> list[dict]:
        """One frame per process for process-scope data (stage stats
        and cache hit counters are shared by co-hosted workers): per
        process keep the frame with the largest monotone stage
        population (ties to worker id — deterministic). The process key
        is the frame's host/boot-unique ``proc_id`` token — bare OS
        pids collide across hosts (containers all run pid 1), and a
        pid-keyed dedupe would collapse a multi-host fleet's stats into
        one worker's stream; pid stays the fallback for frames predating
        the token. Frames whose ``scope`` is ``worker`` carry their OWN
        stage stream (probe-injected) and pass through undeduped."""
        own: list[tuple[str, dict]] = []
        best: dict = {}
        for wid, f in frames:
            if f.get("scope") == "worker":
                own.append((wid, f))
                continue
            proc = f.get("proc_id") or f"pid:{f.get('pid', 0)}"
            total = sum(st.get("n", 0)
                        for st in f.get("stages", {}).values())
            key = (total, wid)
            if proc not in best or key > best[proc][0]:
                best[proc] = (key, f)
        return ([f for _, f in sorted(own)]
                + [v[1] for _, v in sorted(
                    best.items(), key=lambda kv: str(kv[0]))])

    def _slo_snapshot(self, now: float) -> dict:
        with self._lock:
            buckets = [list(b) for b in self._slo_buckets]
        nb = int(now / _SLO_BUCKET_S)
        out = {}
        for name, win in sorted(SLO_WINDOWS.items()):
            lo = nb - int(win / _SLO_BUCKET_S)
            ok = sum(b[1] for b in buckets if b[0] > lo)
            breach = sum(b[2] for b in buckets if b[0] > lo)
            total = ok + breach
            out[name] = {"ok": ok, "breach": breach,
                         "burn_rate": round(breach / total, 6)
                         if total else 0.0}
        return out

    def snapshot(self, now: float | None = None) -> dict:
        """The merged fleet document (``/fleet.json``, GetStats
        ``obs_json``'s ``dbx_fleet``, `dbxtop`'s feed). Pure function of
        the retained frames + ``now`` — mutates nothing, so arrival
        order can never leak into the bytes."""
        now = self._clock() if now is None else now
        bound = self._stale_bound()
        entries = self._copy_entries()
        workers: dict = {}
        live: list[tuple[str, dict]] = []
        for wid in sorted(entries):
            frame, last_seen = entries[wid]
            age = max(now - last_seen, 0.0)
            is_stale = age > bound
            if not is_stale:
                live.append((wid, frame))
            workers[wid] = {
                "gen": str(frame.get("gen", "")),
                "pid": int(frame.get("pid", 0)),
                "proc_id": str(frame.get("proc_id", "")),
                "scope": str(frame.get("scope", "proc")),
                "seq": int(frame.get("seq", 0)),
                "age_s": round(age, 3),
                "stale": is_stale,
                "busy": int(frame.get("busy", 0)),
                "inflight": int(frame.get("inflight", 0)),
                "pipeline": frame.get("pipeline", {}),
                "jobs_completed": int(frame.get("jobs_completed", 0)),
                "completions_dropped": int(
                    frame.get("completions_dropped", 0)),
                "jobs_per_s": float(frame.get("jobs_per_s", 0.0)),
                "uptime_s": float(frame.get("uptime_s", 0.0)),
                "caps": frame.get("caps", {}),
                "caches": frame.get("caches", {}),
                "stages": {
                    s: {"n": int(st.get("n", 0)),
                        "sum_s": round(float(st.get("sum_s", 0.0)), 9),
                        "ewma_s": float(st.get("ewma_s", 0.0)),
                        "p50_s": round(_hist_quantile(
                            st.get("buckets", []), 0.5), 9)}
                    for s, st in frame.get("stages", {}).items()},
                "stragglers": [],
            }
            cm = frame.get("costmodel")
            if cm:
                workers[wid]["costmodel"] = {
                    "n": int(cm.get("n", 0)),
                    "ewma": float(cm.get("ewma", 0.0)),
                    "p50": round(costmodel_mod.residual_quantile(
                        cm.get("buckets", []), 0.5), 4),
                    "blowouts": int(cm.get("blowouts", 0)),
                }
            unknown = frame.get("unknown_fields")
            if unknown:
                workers[wid]["unknown_fields"] = len(unknown)
        # Fleet-wide merged stage histograms: process-scope stats fold
        # once per process (co-hosted workers share one span stream;
        # keyed by the host-unique proc_id token, not bare pid).
        merged = {s: {"n": 0, "sum_s": 0.0,
                      "buckets": [0] * (len(STAGE_BUCKETS_S) + 1)}
                  for s in TELEMETRY_STAGES}
        deduped = self._dedupe_by_pid(live)
        for f in deduped:
            for s, st in f.get("stages", {}).items():
                m = merged.get(s)
                if m is None:
                    continue
                m["n"] += int(st.get("n", 0))
                m["sum_s"] += float(st.get("sum_s", 0.0))
                for i, c in enumerate(st.get("buckets", [])):
                    if i < len(m["buckets"]):
                        m["buckets"][i] += int(c)
        fleet_stages = {}
        for s, m in merged.items():
            fleet_stages[s] = {
                "n": m["n"], "sum_s": round(m["sum_s"], 9),
                "p50_s": round(_hist_quantile(m["buckets"], 0.5), 9),
                "p95_s": round(_hist_quantile(m["buckets"], 0.95), 9)}
        # Straggler flags: per-stage EWMA above the fleet p95, with a
        # real population behind the p95 (the PR-4 rule, applied live).
        if len(live) >= MIN_STRAGGLER_WORKERS:
            for wid, frame in live:
                for s in TELEMETRY_STAGES:
                    fs = fleet_stages[s]
                    if fs["n"] < MIN_STRAGGLER_OBS or fs["p95_s"] <= 0:
                        continue
                    ewma = float(frame.get("stages", {})
                                 .get(s, {}).get("ewma_s", 0.0))
                    if ewma > fs["p95_s"] * STRAGGLER_MARGIN:
                        workers[wid]["stragglers"].append(s)
        # Cache hit ratios, over the same per-process dedupe (the hit
        # counters share the stage streams' co-hosting semantics).
        agg: dict = {}
        for f in deduped:
            for key, hm in f.get("proc", {}).items():
                try:
                    h, m = int(hm[0]), int(hm[1])
                except (TypeError, ValueError, IndexError):
                    continue
                a = agg.setdefault(key, [0, 0])
                a[0] += h
                a[1] += m
        hit_ratio = {key: round(h / (h + m), 6)
                     for key, (h, m) in sorted(agg.items()) if h + m}
        # Cost-model residual fold: exact histogram-count sums over the
        # same per-process dedupe (the accumulator is process-scoped,
        # like the stage stats).
        cm_n = cm_blow = 0
        cm_buckets = [0] * (len(costmodel_mod.RESIDUAL_BUCKETS_LOG2) + 1)
        for f in deduped:
            cm = f.get("costmodel")
            if not cm:
                continue
            cm_n += int(cm.get("n", 0))
            cm_blow += int(cm.get("blowouts", 0))
            for i, c in enumerate(cm.get("buckets", [])):
                if i < len(cm_buckets):
                    cm_buckets[i] += int(c)
        fleet_costmodel = {
            "n": cm_n,
            "blowouts": cm_blow,
            "residual_p50": round(costmodel_mod.residual_quantile(
                cm_buckets, 0.5), 4),
            "residual_p95": round(costmodel_mod.residual_quantile(
                cm_buckets, 0.95), 4),
        }
        return {
            "stale_s": bound,
            "workers": workers,
            "fleet": {
                "workers": len(workers),
                "live": len(live),
                "stale": len(workers) - len(live),
                "busy": sum(1 for _, f in live if f.get("busy")),
                "jobs_per_s": round(sum(
                    float(f.get("jobs_per_s", 0.0))
                    for _, f in live), 4),
                "jobs_completed": sum(
                    int(f.get("jobs_completed", 0)) for _, f in live),
                "stages": fleet_stages,
                "costmodel": fleet_costmodel,
                "cache_hit_ratio": hit_ratio,
                "slo": self._slo_snapshot(now),
            },
        }

    def placement_view(self, now: float | None = None) -> dict:
        """Score-table export (round 20): the per-worker scoring inputs
        the decision plane's placement table is built from, and nothing
        else — staleness/straggler verdicts (score-down signals, never
        exclusion), frame age, and the cache-residency digest sketch as
        a flat prefix tuple. Derived from :meth:`snapshot` so the
        straggler rule (fleet p95 with a real population behind it)
        stays single-sourced; runs on the plane's daemon tick, never
        under the take lock."""
        out: dict = {}
        for wid, w in self.snapshot(now)["workers"].items():
            topk = (w.get("caches") or {}).get("panel_topk") or ()
            out[wid] = {
                "stale": bool(w.get("stale")),
                "age_s": float(w.get("age_s", 0.0)),
                "stragglers": tuple(w.get("stragglers") or ()),
                "resident": tuple(
                    str(e.get("d", "")) for e in topk
                    if isinstance(e, dict) and e.get("d")),
            }
        return out

    def collected_snapshot(self, max_age_s: float = 1.0):
        """The snapshot the last :meth:`collect` built, when fresh —
        ``None`` otherwise. GetStats' ``obs_json`` path runs the
        registry collectors (which snapshot) and then needs the merged
        document itself; this hands it the one just built instead of
        folding the whole fleet twice per call."""
        with self._lock:
            if self._last_collect is None:
                return None
            t, snap = self._last_collect
        if self._clock() - t > max_age_s:
            return None
        return snap

    # -- metric surface ----------------------------------------------------

    def collect(self, reg) -> None:
        """Scrape-time gauges + transition counters (called from the
        dispatcher's registry collector). Worker identity on labels
        goes through the bounded ``worker_bucket`` map — the
        obs-cardinality sanctioned source."""
        from ..sched.tenancy import worker_bucket

        snap = self.snapshot()
        fleet = snap["fleet"]
        reg.gauge("dbx_fleet_workers",
                  help="fleet-view entries by staleness state",
                  state="live").set(fleet["live"])
        reg.gauge("dbx_fleet_workers", state="stale").set(fleet["stale"])
        reg.gauge("dbx_fleet_jobs_per_sec",
                  help="sum of live workers' self-reported completion "
                       "rates").set(fleet["jobs_per_s"])
        reg.gauge("dbx_fleet_cost_drift_p95",
                  help="fleet-merged |log2 measured/predicted| stage "
                       "cost residual p95").set(
            snap["fleet"]["costmodel"]["residual_p95"])
        buckets: set = set()
        drift_buckets: set = set()
        for wid, w in snap["workers"].items():
            b = worker_bucket(wid)
            buckets.add(b)
            reg.gauge("dbx_fleet_worker_jobs_per_sec",
                      help="per-worker self-reported completion rate "
                           "(bounded worker-bucket labels)",
                      worker=b).set(w["jobs_per_s"])
            reg.gauge("dbx_fleet_worker_stale",
                      help="1 when the worker bucket's newest frame is "
                           "older than DBX_FLEET_STALE_S",
                      worker=b).set(1 if w["stale"] else 0)
            cm = w.get("costmodel")
            if cm:
                drift_buckets.add(b)
                reg.gauge("dbx_fleet_worker_cost_drift",
                          help="per-worker cost-model residual EWMA "
                               "(log2 measured/predicted; bounded "
                               "worker-bucket labels)",
                          worker=b).set(cm["ewma"])
        with self._lock:
            dead = self._gauge_buckets - buckets
            dead_drift = (self._gauge_buckets | buckets) - drift_buckets
            self._gauge_buckets = buckets
            self._last_collect = (self._clock(), snap)
        for b in dead:
            # Evicted/forgotten workers' series go away with them — the
            # per-worker-gauge lifecycle discipline (worker.py's run()
            # finally is the precedent). A bucket is only removed when
            # NO retained worker maps to it ("other" stays while shared).
            reg.remove_child("dbx_fleet_worker_jobs_per_sec", worker=b)
            reg.remove_child("dbx_fleet_worker_stale", worker=b)
        for b in dead_drift:
            reg.remove_child("dbx_fleet_worker_cost_drift", worker=b)
        # Straggler TRANSITIONS (not levels): count a worker's stage
        # flag once per episode, cleared when it drops below the p95.
        with self._lock:
            for wid, w in snap["workers"].items():
                e = self._entries.get(wid)
                if e is None:
                    continue
                cur = set(w["stragglers"])
                for s in cur - e.flagged:
                    self._c_straggler[s].inc()
                e.flagged = cur
        for win, st in fleet["slo"].items():
            if (st["ok"] + st["breach"]
                    and st["burn_rate"] > slo_burn_threshold()):
                self._c_slo_burn[win].inc()


# ---------------------------------------------------------------------------
# dbxtop: the live fleet table
# ---------------------------------------------------------------------------


def _fetch_fleet(url: str) -> dict:
    import urllib.request

    from .timeline import stats_url

    with urllib.request.urlopen(stats_url(url, doc="fleet.json"),
                                timeout=10) as resp:
        return json.loads(resp.read())


def render_text(snap: dict) -> str:
    """The `dbxtop` table: fleet rollup header + one row per worker."""
    from .timeline import _fmt_s, _table

    fleet = snap.get("fleet", {})
    out = [
        f"fleet: {fleet.get('live', 0)} live / {fleet.get('stale', 0)} "
        f"stale worker(s), {fleet.get('busy', 0)} busy, "
        f"{fleet.get('jobs_per_s', 0.0):.1f} jobs/s, "
        f"{fleet.get('jobs_completed', 0)} completed "
        f"(staleness bound {snap.get('stale_s', 0.0):.1f}s)"]
    stages = fleet.get("stages", {})
    srows = [(s, st["n"], _fmt_s(st["sum_s"]), _fmt_s(st["p50_s"]),
              _fmt_s(st["p95_s"]))
             for s, st in stages.items() if st.get("n")]
    if srows:
        out.append("")
        out.append("== fleet stage costs (merged histograms) ==")
        out.append(_table(srows, ("stage", "n", "total", "p50", "p95")))
    cm = fleet.get("costmodel", {})
    if cm.get("n"):
        out.append(
            f"cost-model drift: {cm['n']} obs, residual p50 "
            f"{cm.get('residual_p50', 0.0):+.2f} / p95 "
            f"{cm.get('residual_p95', 0.0):+.2f} log2, "
            f"{cm.get('blowouts', 0)} blowout(s)")
    ratios = fleet.get("cache_hit_ratio", {})
    if ratios:
        out.append("cache hit ratios: " + ", ".join(
            f"{k} {100 * v:.1f}%" for k, v in ratios.items()))
    slo = fleet.get("slo", {})
    if any(st["ok"] + st["breach"] for st in slo.values()):
        out.append("queue-wait SLO burn: " + ", ".join(
            f"{w} {100 * st['burn_rate']:.1f}% "
            f"({st['breach']}/{st['ok'] + st['breach']})"
            for w, st in sorted(slo.items())))
    rows = []
    for wid, w in snap.get("workers", {}).items():
        flags = []
        if w.get("stale"):
            flags.append("STALE")
        flags += [f"straggler:{s}" for s in w.get("stragglers", [])]
        st = w.get("stages", {})

        def ew(s):
            v = st.get(s, {}).get("ewma_s", 0.0)
            return _fmt_s(v) if v else "-"

        caches = w.get("caches", {})
        cache_mb = sum(
            v for k, v in _iter_bytes(caches)) / (1024 * 1024)
        wcm = w.get("costmodel") or {}
        if w.get("unknown_fields"):
            flags.append(f"+{w['unknown_fields']}fields")
        rows.append((
            wid, w.get("gen", "")[:6],
            "busy" if w.get("busy") else "idle",
            f"{w.get('jobs_per_s', 0.0):.1f}",
            w.get("jobs_completed", 0),
            ew("decode"), ew("compile"), ew("execute"), ew("d2h"),
            f"{wcm['ewma']:+.2f}" if wcm.get("n") else "-",
            str(wcm.get("blowouts", 0)) if wcm.get("n") else "-",
            f"{cache_mb:.1f}", f"{w.get('age_s', 0.0):.1f}s",
            " ".join(flags) or "-"))
    out.append("")
    out.append(_table(rows, ("worker", "gen", "state", "jobs/s", "done",
                             "decode", "compile", "execute", "d2h",
                             "drift", "blow", "cacheMB", "age",
                             "flags")))
    return "\n".join(out) + "\n"


def _iter_bytes(node, prefix=""):
    """Yield every ``*_bytes``/``bytes`` leaf of a residency dict."""
    if isinstance(node, dict):
        for k, v in node.items():
            if isinstance(v, dict):
                yield from _iter_bytes(v, f"{prefix}{k}.")
            elif isinstance(v, (int, float)) and (
                    k == "bytes" or k.endswith("_bytes")):
                yield f"{prefix}{k}", float(v)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="obs.fleet",
        description="dbxtop: live fleet telemetry table from a "
                    "dispatcher's /fleet.json")
    ap.add_argument("--url", required=True,
                    help="dispatcher metrics endpoint "
                         "(http://host:port, or the full /fleet.json)")
    ap.add_argument("--watch", type=float, default=None, metavar="SECS",
                    help="refresh every SECS seconds until interrupted "
                         "(one-shot when omitted)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)
    try:
        while True:
            snap = _fetch_fleet(args.url)
            if args.format == "json":
                body = json.dumps(snap, indent=2, sort_keys=True) + "\n"
            else:
                body = render_text(snap)
            if args.watch is not None:
                # Clear + home, like top: the table repaints in place.
                sys.stdout.write("\x1b[2J\x1b[H")
            sys.stdout.write(body)
            sys.stdout.flush()
            if args.watch is None:
                return 0
            time.sleep(args.watch)
    except KeyboardInterrupt:
        return 0
    except OSError as e:
        print(f"obs.fleet: cannot reach {args.url}: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
