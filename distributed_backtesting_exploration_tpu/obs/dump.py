"""Phase-attribution viewer: ``python -m ...obs.dump <target>...``.

Each ``target`` is either a live endpoint (``http://host:port`` — its
``/stats.json`` is fetched) or a JSONL event-log path (``DBX_OBS_JSONL``
output; also acceptable via ``--jsonl``). All JSONL inputs aggregate
into ONE phase table (a fleet writes one log per process); malformed
lines are skipped and counted, and a run that parses ZERO events exits
non-zero — an empty table from a typo'd path must not read as a healthy
quiet fleet. The output is a phase table: where wall-clock went, by
span/histogram, share-ranked — the live counterpart of bench.py's
roofline stage accounting. For per-JOB lifecycle timelines and
critical-path stage attribution, see :mod:`.timeline`.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request

from .timeline import _fmt_s, _table, parse_events, stats_url


def _phase_rows(digests: dict) -> list[tuple]:
    """(name, count, total_s, avg, p50, p99, max, share%) rows from
    ``{label: histogram-summary}`` digests, share-ranked."""
    total = sum(d.get("sum", 0.0) for d in digests.values()) or 1.0
    rows = []
    for label, d in sorted(digests.items(),
                           key=lambda kv: -kv[1].get("sum", 0.0)):
        if not d.get("count"):
            continue
        rows.append((label, d["count"], _fmt_s(d["sum"]),
                     _fmt_s(d.get("avg", 0.0)),
                     _fmt_s(d.get("p50", 0.0)), _fmt_s(d.get("p99", 0.0)),
                     _fmt_s(d.get("max", 0.0)),
                     f"{100.0 * d['sum'] / total:.1f}%"))
    return rows


_PHASE_HEADER = ("phase", "count", "total", "avg", "p50", "p99", "max",
                 "share")


def render_snapshot(snap: dict) -> str:
    """Registry snapshot (``/stats.json`` shape) -> report text."""
    out: list[str] = []
    hists = {name: fam["values"] for name, fam in snap.items()
             if fam.get("type") == "histogram"}
    for name, values in sorted(hists.items()):
        rows = _phase_rows(values)
        if rows:
            out.append(f"== {name} ==")
            out.append(_table(rows, _PHASE_HEADER))
            out.append("")
    scalars = []
    for name, fam in sorted(snap.items()):
        if fam.get("type") in ("counter", "gauge"):
            for label, v in sorted(fam["values"].items()):
                key = f"{name}{{{label}}}" if label else name
                scalars.append((key, fam["type"],
                                round(v, 6) if isinstance(v, float) else v))
    if scalars:
        out.append("== counters / gauges ==")
        out.append(_table(scalars, ("metric", "type", "value")))
        out.append("")
    return "\n".join(out) if out else "(no metrics recorded)\n"


def render_jsonl(paths) -> tuple[str, int, int]:
    """Aggregate one or more span event logs into the phase table.

    Returns ``(text, n_events, n_malformed)`` — malformed lines (torn
    tails, truncated writes) are skipped and counted, never fatal and
    never silent."""
    if isinstance(paths, str):
        paths = [paths]
    events, malformed = parse_events(paths)
    agg: dict[str, dict] = {}
    for rec in events:
        if rec.get("ev") != "span":
            continue
        name = rec.get("name", "?")
        if rec.get("parent"):
            name = f"{rec['parent']}/{name}"
        dur = float(rec.get("dur_s", 0.0))
        d = agg.setdefault(name, {"count": 0, "sum": 0.0, "max": 0.0,
                                  "durs": []})
        d["count"] += 1
        d["sum"] += dur
        d["max"] = max(d["max"], dur)
        d["durs"].append(dur)
    digests = {}
    for name, d in agg.items():
        durs = sorted(d["durs"])
        digests[name] = {
            "count": d["count"], "sum": d["sum"],
            "avg": d["sum"] / d["count"], "max": d["max"],
            "p50": durs[len(durs) // 2],
            "p99": durs[min(len(durs) - 1, int(len(durs) * 0.99))]}
    rows = _phase_rows(digests)
    head = (f"{len(events)} events, {len(agg)} span phases from "
            + ", ".join(paths))
    if malformed:
        head += f" ({malformed} malformed line(s) skipped)"
    if not rows:
        return head + "\n(no span events)\n", len(events), malformed
    return (head + "\n" + _table(rows, _PHASE_HEADER) + "\n",
            len(events), malformed)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="pretty-print dbx obs endpoints and/or JSONL event "
                    "logs as a phase-attribution table")
    ap.add_argument("targets", nargs="*", default=[],
                    help="http://host:port of a live /metrics server, or "
                         "JSONL event-log path(s)")
    ap.add_argument("--jsonl", nargs="+", action="extend", default=[],
                    metavar="PATH",
                    help="additional JSONL event log(s); all JSONL inputs "
                         "aggregate into one table")
    ap.add_argument("--url", nargs="+", action="extend", default=[],
                    metavar="URL",
                    help="live snapshot endpoint(s) (http://host:port or "
                         "the full .../stats.json) — same as a positional "
                         "http target, spelled like obs.timeline's flag")
    args = ap.parse_args(argv)
    urls = [t for t in args.targets
            if t.startswith(("http://", "https://"))] + args.url
    jsonl = [t for t in args.targets
             if not t.startswith(("http://", "https://"))] + args.jsonl
    if not urls and not jsonl:
        ap.error("no targets: pass an endpoint URL (--url) and/or JSONL "
                 "path(s)")
    for target in urls:
        with urllib.request.urlopen(stats_url(target), timeout=10) as resp:
            snap = json.loads(resp.read())
        sys.stdout.write(render_snapshot(snap))
    if jsonl:
        text, n_events, _malformed = render_jsonl(jsonl)
        sys.stdout.write(text)
        if not n_events:
            # A zero-event run is a broken pipeline (wrong path, log never
            # enabled), not a quiet fleet — fail loudly for CI wrappers.
            print("obs.dump: no parseable events in "
                  + ", ".join(jsonl), file=sys.stderr)
            return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
