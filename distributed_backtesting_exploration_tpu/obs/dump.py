"""Phase-attribution viewer: ``python -m ...obs.dump <target>``.

``target`` is either a live endpoint (``http://host:port`` — its
``/stats.json`` is fetched) or a JSONL event-log path (``DBX_OBS_JSONL``
output). Either way the output is a phase table: where wall-clock went,
by span/histogram, share-ranked — the live counterpart of bench.py's
roofline stage accounting.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.2f}ms"
    return f"{v * 1e6:.0f}us"


def _table(rows: list[tuple], header: tuple) -> str:
    rows = [tuple(str(c) for c in r) for r in rows]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(header)]
    def line(cells):
        return "  ".join(c.ljust(w) for c, w in zip(cells, widths)).rstrip()
    out = [line(header), line(tuple("-" * w for w in widths))]
    out.extend(line(r) for r in rows)
    return "\n".join(out)


def _phase_rows(digests: dict) -> list[tuple]:
    """(name, count, total_s, avg, p50, p99, max, share%) rows from
    ``{label: histogram-summary}`` digests, share-ranked."""
    total = sum(d.get("sum", 0.0) for d in digests.values()) or 1.0
    rows = []
    for label, d in sorted(digests.items(),
                           key=lambda kv: -kv[1].get("sum", 0.0)):
        if not d.get("count"):
            continue
        rows.append((label, d["count"], _fmt_s(d["sum"]),
                     _fmt_s(d.get("avg", 0.0)),
                     _fmt_s(d.get("p50", 0.0)), _fmt_s(d.get("p99", 0.0)),
                     _fmt_s(d.get("max", 0.0)),
                     f"{100.0 * d['sum'] / total:.1f}%"))
    return rows


_PHASE_HEADER = ("phase", "count", "total", "avg", "p50", "p99", "max",
                 "share")


def render_snapshot(snap: dict) -> str:
    """Registry snapshot (``/stats.json`` shape) -> report text."""
    out: list[str] = []
    hists = {name: fam["values"] for name, fam in snap.items()
             if fam.get("type") == "histogram"}
    for name, values in sorted(hists.items()):
        rows = _phase_rows(values)
        if rows:
            out.append(f"== {name} ==")
            out.append(_table(rows, _PHASE_HEADER))
            out.append("")
    scalars = []
    for name, fam in sorted(snap.items()):
        if fam.get("type") in ("counter", "gauge"):
            for label, v in sorted(fam["values"].items()):
                key = f"{name}{{{label}}}" if label else name
                scalars.append((key, fam["type"],
                                round(v, 6) if isinstance(v, float) else v))
    if scalars:
        out.append("== counters / gauges ==")
        out.append(_table(scalars, ("metric", "type", "value")))
        out.append("")
    return "\n".join(out) if out else "(no metrics recorded)\n"


def render_jsonl(path: str) -> str:
    """Aggregate a span event log into the phase table."""
    agg: dict[str, dict] = {}
    n_events = 0
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue   # torn tail is diagnostic-grade, skip quietly
            n_events += 1
            if rec.get("ev") != "span":
                continue
            name = rec.get("name", "?")
            if rec.get("parent"):
                name = f"{rec['parent']}/{name}"
            dur = float(rec.get("dur_s", 0.0))
            d = agg.setdefault(name, {"count": 0, "sum": 0.0, "max": 0.0,
                                      "durs": []})
            d["count"] += 1
            d["sum"] += dur
            d["max"] = max(d["max"], dur)
            d["durs"].append(dur)
    digests = {}
    for name, d in agg.items():
        durs = sorted(d["durs"])
        digests[name] = {
            "count": d["count"], "sum": d["sum"],
            "avg": d["sum"] / d["count"], "max": d["max"],
            "p50": durs[len(durs) // 2],
            "p99": durs[min(len(durs) - 1, int(len(durs) * 0.99))]}
    rows = _phase_rows(digests)
    head = f"{n_events} events, {len(agg)} span phases from {path}"
    if not rows:
        return head + "\n(no span events)\n"
    return head + "\n" + _table(rows, _PHASE_HEADER) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="pretty-print a dbx obs endpoint or JSONL event log "
                    "as a phase-attribution table")
    ap.add_argument("target",
                    help="http://host:port of a live /metrics server, or "
                         "a JSONL event-log path")
    args = ap.parse_args(argv)
    if args.target.startswith(("http://", "https://")):
        url = args.target.rstrip("/") + "/stats.json"
        with urllib.request.urlopen(url, timeout=10) as resp:
            snap = json.loads(resp.read())
        sys.stdout.write(render_snapshot(snap))
    else:
        sys.stdout.write(render_jsonl(args.target))
    return 0


if __name__ == "__main__":
    sys.exit(main())
