"""Unified observability layer: metrics registry, spans, event log, /metrics.

The one place every layer records into (DESIGN.md "Observability"):

- :mod:`.registry` — process-local counters / gauges / fixed-bucket
  histograms, rendered as Prometheus text or JSON summaries;
- :mod:`.trace` — the span API (phase attribution + nesting), ``timed``,
  ``StepTimer``, ``device_profile`` (absorbed from ``utils.trace``, which
  is now a deprecation shim);
- :mod:`.events` — opt-in JSONL event log (``DBX_OBS_JSONL``) for
  post-mortem trace reconstruction;
- :mod:`.http` — the ``/metrics`` + ``/stats.json`` HTTP surface;
- :mod:`.dump` — ``python -m ...obs.dump`` pretty-printer / phase table.
"""

from . import events  # noqa: F401
from .http import MetricsServer, start_metrics_server  # noqa: F401
from .registry import (  # noqa: F401
    LATENCY_BUCKETS_S, Counter, Gauge, Histogram, Registry, get_registry)
from .trace import (  # noqa: F401
    StepTimer, current_span, device_profile, span, timed, timer)
