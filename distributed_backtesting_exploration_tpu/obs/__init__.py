"""Unified observability layer: metrics registry, spans, event log, /metrics.

The one place every layer records into (DESIGN.md "Observability"):

- :mod:`.registry` — process-local counters / gauges / fixed-bucket
  histograms, rendered as Prometheus text or JSON summaries;
- :mod:`.trace` — the span API (phase attribution + nesting + the
  distributed ``trace_id``/``span_id``/``parent_id`` triple), ``timed``,
  ``StepTimer``, ``device_profile`` (absorbed from ``utils.trace``, which
  is now a deprecation shim);
- :mod:`.events` — opt-in JSONL event log (``DBX_OBS_JSONL``) for
  post-mortem trace reconstruction;
- :mod:`.http` — the ``/metrics`` + ``/stats.json`` HTTP surface;
- :mod:`.dump` — ``python -m ...obs.dump`` pretty-printer / phase table;
- :mod:`.timeline` — merge JSONL logs from any number of processes into
  per-job lifecycle timelines with critical-path stage attribution
  (``python -m ...obs.timeline``).
"""

from . import events  # noqa: F401
from .http import MetricsServer, start_metrics_server  # noqa: F401
from .registry import (  # noqa: F401
    LATENCY_BUCKETS_S, Counter, Gauge, Histogram, Registry, get_registry)
from .trace import (  # noqa: F401
    StepTimer, add_span_listener, configure_ring, current_span,
    current_trace, device_profile, emit_span, job_trace_pairs, new_span_id,
    new_trace_id, recent_spans, remove_span_listener, span, timed, timer,
    trace_context)
