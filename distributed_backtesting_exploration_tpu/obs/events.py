"""Opt-in JSONL event log for post-mortem trace reconstruction.

Spans (and any layer that wants durable breadcrumbs) emit one JSON object
per line. Disabled by default — :func:`emit` is a single ``is None`` check
once initialized — and enabled either explicitly (:func:`configure`) or by
exporting ``DBX_OBS_JSONL=/path/to/events.jsonl``.

The environment variable is read LAZILY at first use, not at import
(dbxlint *import-time-config*): an import-time read froze the setting for
the process, so a harness that imported ``obs`` before deciding on a log
path could never enable logging in-process. Now ``os.environ`` is
consulted on the first :func:`emit`/:func:`enabled` call, and an explicit
:func:`configure` always wins over (and stops further consultation of)
the environment.

Unlike the dispatcher's job journal (``rpc.journal``), this log is
diagnostic, not durable state: writes are flushed but not fsync'd, and a
lost tail loses nothing but trace detail.
"""

from __future__ import annotations

import json
import os
import threading
import time

_lock = threading.Lock()
_fh = None
_path: str | None = None
# False until the first configure()/first use: emit/enabled consult
# DBX_OBS_JSONL exactly once, lazily, so in-process toggling before first
# use works and importing this module never does IO.
_env_checked = False


def configure(path: str | None) -> None:
    """Open (or with ``None``, close) the process-wide event log.

    Explicit configuration wins: after any call — even one whose open
    RAISES — the environment variable is never consulted
    (``configure(None)`` therefore disables logging even with
    ``DBX_OBS_JSONL`` set, and a failed configure must not let the env
    fallback sneak logging back on). The open happens OUTSIDE the
    module lock (dbxlint lock-blocking: a slow open — NFS, a fifo —
    must not stall every concurrent ``emit``); an unopenable path
    raises without touching the current log."""
    global _fh, _path, _env_checked
    with _lock:
        _env_checked = True
    new_fh = open(path, "a", encoding="utf-8") if path else None
    with _lock:
        if _fh is not None:
            _fh.close()
        _fh = new_fh
        _path = path if new_fh is not None else None


def _check_env() -> None:
    """First-use environment opt-in: workers/dispatchers started with
    ``DBX_OBS_JSONL`` set begin logging without any code change. A bad
    path must not kill the process — this log is diagnostic, so degrade
    to disabled with a loud warning instead. The open runs OUTSIDE the
    module lock (dbxlint lock-blocking) with a re-check under the
    second acquisition: two first-use racers may both open, the loser
    closes and adopts the winner's state."""
    global _fh, _path, _env_checked
    with _lock:
        if _env_checked:
            return
    env_path = os.environ.get("DBX_OBS_JSONL")
    fh = None
    if env_path:
        try:
            fh = open(env_path, "a", encoding="utf-8")
        except OSError as e:
            import logging

            logging.getLogger("dbx.obs").warning(
                "DBX_OBS_JSONL=%s could not be opened (%s); event logging "
                "disabled", env_path, e)
    with _lock:
        if _env_checked:
            if fh is not None:
                fh.close()
            return
        _env_checked = True
        if fh is not None:
            _fh = fh
            _path = env_path


def configured_path() -> str | None:
    if not _env_checked:
        _check_env()
    return _path


def enabled() -> bool:
    if not _env_checked:
        _check_env()
    return _fh is not None


def emit(event: str, **payload) -> None:
    """Append one event line; no-op (one attribute read) when disabled."""
    emit_record({"ev": event, **payload})


def emit_record(rec: dict) -> None:
    """Append one pre-built record (must carry ``ev``); the writer stamps
    ``ts`` (wall clock at write) and ``pid`` — the timeline analyzer merges
    logs from many processes and needs a per-process identity even when the
    emitting layer (e.g. the compute backend) does not know its worker id.
    """
    if not _env_checked:
        _check_env()
    if _fh is None:
        return
    rec = {"ts": time.time(), "pid": os.getpid(), **rec}
    line = json.dumps(rec, separators=(",", ":"), default=str)
    with _lock:
        if _fh is None:
            return
        _fh.write(line + "\n")
        _fh.flush()
