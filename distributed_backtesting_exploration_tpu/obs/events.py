"""Opt-in JSONL event log for post-mortem trace reconstruction.

Spans (and any layer that wants durable breadcrumbs) emit one JSON object
per line. Disabled by default — :func:`emit` is a single ``is None`` check
— and enabled either explicitly (:func:`configure`) or by exporting
``DBX_OBS_JSONL=/path/to/events.jsonl`` before process start.

Unlike the dispatcher's job journal (``rpc.journal``), this log is
diagnostic, not durable state: writes are flushed but not fsync'd, and a
lost tail loses nothing but trace detail.
"""

from __future__ import annotations

import json
import os
import threading
import time

_lock = threading.Lock()
_fh = None
_path: str | None = None


def configure(path: str | None) -> None:
    """Open (or with ``None``, close) the process-wide event log."""
    global _fh, _path
    with _lock:
        if _fh is not None:
            _fh.close()
            _fh = None
        _path = path
        if path:
            _fh = open(path, "a", encoding="utf-8")


def configured_path() -> str | None:
    return _path


def enabled() -> bool:
    return _fh is not None


def emit(event: str, **payload) -> None:
    """Append one event line; no-op (one attribute read) when disabled."""
    if _fh is None:
        return
    rec = {"ev": event, "ts": time.time(), **payload}
    line = json.dumps(rec, separators=(",", ":"), default=str)
    with _lock:
        if _fh is None:
            return
        _fh.write(line + "\n")
        _fh.flush()


# Environment opt-in at import time: workers/dispatchers started with
# DBX_OBS_JSONL set begin logging without any code change. A bad path must
# not kill the process at import — this log is diagnostic, so degrade to
# disabled with a loud warning instead.
_env_path = os.environ.get("DBX_OBS_JSONL")
if _env_path:
    try:
        configure(_env_path)
    except OSError as e:
        import logging

        logging.getLogger("dbx.obs").warning(
            "DBX_OBS_JSONL=%s could not be opened (%s); event logging "
            "disabled", _env_path, e)
