"""Cost-model drift plane: predicted-vs-measured stage cost residuals.

``tune/`` and ``bench.py`` share one op model (``tune.autotune
.modeled_cost`` — VPU ladder rounds, table streams, lane overhead), and
the autotuner already trusts it as a pruning PRIOR. Nobody checks it
against reality: a worker whose measured execute wall drifts from the
model's prediction (thermal throttling, a pathological shape, a stale
tuned schedule, an outright model bug) is invisible until it surfaces
as a straggler flag with no cause attached. This module closes the loop
(the TVM cost-model discipline from PAPERS.md: learn from measured
schedules, TRACK THE RESIDUALS):

- a span listener over the PR-4 ``worker.execute`` stream (the submit
  spans now carry ``bars``/``combos`` shape attrs beside ``kernel`` and
  ``jobs``) converts each measured group wall into a **residual**
  against the op model's prediction for its (family, route);
- the model is *relative* (VPU-op equivalents per cell-bar), so a
  per-(family, route) **calibration EWMA** of measured
  seconds-per-model-unit anchors it to this process's silicon first
  (``DBX_COSTMODEL_WARMUP`` observations); after warmup the residual is
  ``log2(measured / predicted)`` — 0 = the model nailed it, +1 = twice
  as slow as predicted, symmetric in log space so over- and
  under-prediction fold into one histogram;
- residuals accumulate into a signed EWMA + a fixed log2-bucket
  histogram with a ``version`` dirty bit, riding the PR-14 telemetry
  frames as a ``costmodel`` key (~tens of bytes) into FleetView's
  order-independent merge, ``/fleet.json``, GetStats and `dbxtop`;
- a single observation past ``DBX_COSTMODEL_BLOWOUT`` (log2; default
  3.0 ≈ 8x off) is a **blowout**: counted, and fired into the flight
  recorder (obs/flight.py) as a ``residual`` trigger — a mis-modeled
  stage is an incident worth a black-box bundle, not just a number.

``worker.compile`` spans are deliberately excluded: a cold compile's
wall is XLA's, not the op model's, and one compile residual would
poison the calibration for hundreds of execute observations.

``DBX_COSTMODEL=0`` is the kill switch (observations become no-ops and
frames carry no ``costmodel`` key). Everything degrades to counting:
a model error, a missing attr, a zero-unit shape — skipped, never a
failed job.
"""

from __future__ import annotations

import math
import os
import threading

from . import trace
from .registry import get_registry

#: Residual histogram bounds, in log2(measured/predicted) — shared by
#: the worker-side accumulator and the dispatcher-side fold (same
#: exactness argument as fleet.STAGE_BUCKETS_S: summing per-bucket
#: counts commutes). The last bucket is the +inf overflow.
RESIDUAL_BUCKETS_LOG2 = (-4.0, -2.0, -1.0, -0.5, -0.25,
                         0.25, 0.5, 1.0, 2.0, 4.0)

_EWMA_ALPHA = 0.25          # residual EWMA (matches fleet's stage EWMAs)
_CALIB_ALPHA = 0.1          # seconds-per-unit calibration (slower: the
#                             calibration must not absorb a drift episode
#                             before the residuals can report it)


def enabled() -> bool:
    """``DBX_COSTMODEL`` (default on): track predicted-vs-measured
    residuals worker-side. ``0`` is the kill switch."""
    return os.environ.get("DBX_COSTMODEL", "1").lower() not in (
        "0", "off", "false")


def warmup_n() -> int:
    """``DBX_COSTMODEL_WARMUP`` (default 8): observations per (family,
    route) spent calibrating seconds-per-model-unit before residuals
    are scored — a residual against an uncalibrated constant would just
    measure the platform."""
    try:
        return max(int(os.environ.get("DBX_COSTMODEL_WARMUP", 8)), 1)
    except ValueError:
        return 8


def blowout_log2() -> float:
    """``DBX_COSTMODEL_BLOWOUT`` (default 3.0): |log2 residual| at or
    past which one observation counts as a blowout and fires the flight
    recorder's ``residual`` trigger (3.0 ≈ 8x off the prediction)."""
    try:
        return float(os.environ.get("DBX_COSTMODEL_BLOWOUT", 3.0))
    except ValueError:
        return 3.0


def residual_quantile(counts, q: float) -> float:
    """Rank-interpolated quantile over RESIDUAL_BUCKETS_LOG2 per-bucket
    counts. The registry's ``histogram_quantile`` assumes buckets start
    at 0 (latency); residuals are signed, so the underflow bucket
    collapses to the first bound and interpolation runs between real
    bound pairs."""
    bounds = RESIDUAL_BUCKETS_LOG2
    count = sum(counts)
    if not count:
        return 0.0
    rank = q * count
    acc = 0
    lo = bounds[0]
    for i, c in enumerate(counts):
        hi = bounds[i] if i < len(bounds) else bounds[-1]
        if acc + c >= rank:
            if c == 0 or i == 0:
                return hi if i == 0 else lo
            return lo + (hi - lo) * (rank - acc) / c
        acc += c
        if i < len(bounds):
            lo = bounds[i]
    return bounds[-1]


def _model_units(family: str, bars: int, combos: int) -> float:
    """Total predicted model units for one group: the shared op model's
    per-cell-bar relative cost x the cell-bar count. Lazy import — tune
    imports obs at module level, so the reverse edge must not exist at
    import time."""
    from ..tune.autotune import default_substrates, modeled_cost

    per_cellbar = modeled_cost(family, default_substrates(family),
                               n_bars=bars, n_combos=combos)
    return per_cellbar * float(bars) * float(combos)


class CostModelTracker:
    """Process-scoped residual accumulator fed by the completed-span
    stream (the ``_StageStats`` twin in obs/fleet.py — one listener,
    however many Workers the process hosts; the fleet fold dedupes per
    process)."""

    def __init__(self, *, registry=None, on_blowout=None):
        self._reg = registry or get_registry()
        self._on_blowout = on_blowout
        self._lock = threading.Lock()
        # (family, route) -> [n_obs, ewma seconds-per-model-unit].
        # Bounded in practice by the fused strategy registry x the
        # route vocabulary; the hard cap guards hostile span attrs.
        self._calib: dict[tuple[str, str], list] = {}
        self._n = 0
        self._ewma = 0.0
        self._buckets = [0] * (len(RESIDUAL_BUCKETS_LOG2) + 1)
        self._blowouts = 0
        self.version = 0      # bumps per scored residual — the dirty bit
        self._c_obs = self._reg.counter(
            "dbx_costmodel_observations_total",
            help="execute spans scored against the op model "
                 "(post-warmup)")
        self._c_blowout = self._reg.counter(
            "dbx_costmodel_blowouts_total",
            help="single observations past DBX_COSTMODEL_BLOWOUT "
                 "(|log2 measured/predicted|) — each also fires the "
                 "flight recorder's residual trigger")

    _CALIB_MAX = 256

    def observe(self, rec: dict) -> None:
        """Span listener: score one ``worker.execute`` span against the
        op model. Anything unusable (missing shape attrs, zero units, a
        model error) is skipped — drift tracking must never cost a job."""
        if rec.get("name") != "worker.execute" or not enabled():
            return
        kernel = str(rec.get("kernel", ""))
        if ":" not in kernel:
            return
        route, family = kernel.split(":", 1)
        try:
            dur = float(rec.get("dur_s", 0.0))
            bars = int(rec.get("bars", 0))
            combos = int(rec.get("combos", 0))
            jobs = int(rec.get("jobs", 1)) or 1
        except (TypeError, ValueError):
            return
        if dur <= 0.0 or bars <= 0 or combos <= 0:
            return
        try:
            units = _model_units(family, bars, combos) * jobs
        except Exception:
            return            # an unmodelable family teaches nothing
        if units <= 0.0 or not math.isfinite(units):
            return
        spu = dur / units
        blow = None
        with self._lock:
            cal = self._calib.get((family, route))
            if cal is None:
                if len(self._calib) >= self._CALIB_MAX:
                    return   # hostile attr storm: stop minting keys
                self._calib[(family, route)] = [1, spu]
                return
            n, ewma_spu = cal
            if n < warmup_n():
                cal[0] = n + 1
                cal[1] = (_CALIB_ALPHA * spu
                          + (1.0 - _CALIB_ALPHA) * ewma_spu)
                return
            residual = math.log2(dur / (ewma_spu * units))
            # Score against the PRE-update calibration, then let the
            # calibration track (slowly) so a permanent platform shift
            # re-centers instead of burning forever.
            cal[0] = n + 1
            cal[1] = (_CALIB_ALPHA * spu
                      + (1.0 - _CALIB_ALPHA) * ewma_spu)
            i = 0
            while (i < len(RESIDUAL_BUCKETS_LOG2)
                   and residual > RESIDUAL_BUCKETS_LOG2[i]):
                i += 1
            self._buckets[i] += 1
            self._n += 1
            self._ewma = (residual if self._n == 1 else
                          _EWMA_ALPHA * residual
                          + (1.0 - _EWMA_ALPHA) * self._ewma)
            if abs(residual) >= blowout_log2():
                self._blowouts += 1
                blow = (family, route, residual)
            self.version += 1
        self._c_obs.inc()
        if blow is not None:
            self._c_blowout.inc()
            if self._on_blowout is not None:
                try:
                    self._on_blowout(*blow)
                except Exception:
                    pass   # a capture hook must never cost a job

    def frame(self) -> dict:
        """The ``costmodel`` key of a telemetry frame (obs/fleet.py):
        compact, order-independently mergeable (histogram counts sum;
        EWMA is advisory per worker). Empty before the first scored
        residual — no key, no wire bytes."""
        with self._lock:
            if not self._n:
                return {}
            return {"n": self._n, "ewma": round(self._ewma, 4),
                    "buckets": list(self._buckets),
                    "blowouts": self._blowouts}

    def snapshot(self) -> dict:
        """Local debug view: calibration table + residual accumulators."""
        with self._lock:
            return {
                "calibration": {
                    f"{fam}:{route}": {"n": n, "spu": ewma}
                    for (fam, route), (n, ewma)
                    in sorted(self._calib.items())},
                "n": self._n, "ewma": round(self._ewma, 6),
                "buckets": list(self._buckets),
                "blowouts": self._blowouts}


_tracker: CostModelTracker | None = None
_tracker_lock = threading.Lock()


def _fire_residual_trigger(family: str, route: str,
                           residual: float) -> None:
    from . import flight

    flight.trigger("residual", subject=f"{family}:{route}",
                   residual=round(residual, 3))


def tracker() -> CostModelTracker:
    """The process-wide residual tracker, span listener installed on
    first use (the ``fleet.stage_stats`` pattern); blowouts fire the
    flight recorder's ``residual`` trigger."""
    global _tracker
    with _tracker_lock:
        if _tracker is None:
            _tracker = CostModelTracker(
                on_blowout=_fire_residual_trigger)
            trace.add_span_listener("costmodel", _tracker.observe)
        return _tracker


def reset_tracker() -> None:
    """Drop the singleton + its listener (test isolation — the
    ``configure_ring`` / ``reset_tenant_buckets`` precedent)."""
    global _tracker
    with _tracker_lock:
        if _tracker is not None:
            trace.remove_span_listener("costmodel")
            _tracker = None
