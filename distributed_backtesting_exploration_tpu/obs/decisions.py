"""Dispatch decision plane: per-take explainability + shadow placement.

Every ``JobQueue.take()`` resolution is a layered placement decision —
a WFQ virtual-time pick (PR 8), possibly an affinity deferral (PR 6), a
payload route (digest-only / full / delta, PR 5/6; scenario-coalesced,
PR 18) — and none of it was observable: "why did job J land on worker W,
and what would it have cost elsewhere?" had no answer. This module is
that answer, built to the flight-recorder posture (obs/flight.py):

- the dispatcher hands :meth:`DecisionPlane.submit` one small tuple per
  dispatched job (the record object plus the four values only the
  dispatch loop knows — no dict assembly, no snapshot, no model math on
  the take path), a single small-lock deque append per poll; the
  scoring budget (``DBX_DECISIONS_RATE``) is spent right there, and
  :meth:`DecisionPlane.want` lets an over-budget poll skip explain
  assembly and the submit entirely — past the budget the hot path is
  byte-identical to the kill-switch path;
- a daemon thread scores each batch against ONE ``FleetView.snapshot()``:
  for every live worker it estimates the job's stage cost from the op
  model ``obs/costmodel.py`` and ``bench.py`` already share — execute
  wall from model units x a per-worker seconds-per-unit EWMA (calibrated
  by completions), **carry-hit vs reprice** (an append job on a worker
  whose top-K digest sketch holds the base panel pays only the delta
  fraction), **page residency vs h2d** (payload bytes over a nominal
  link rate unless the panel digest is resident), **compile-cache hit
  vs cold wall** (first sighting of a strategy family on a worker pays
  the cold-compile constant);
- ``regret = cost(actual) − cost(best_shadow)`` is recorded per decision
  (>= 0 — the actual worker is always a candidate); round 19 ran this
  in pure shadow mode, the measure-before-commit discipline the
  locality scorer was held to.

Round 20 promotes the scorer to DUAL live/shadow mode. The same cost
model (ONE implementation: :func:`placement_cost`) now also feeds the
live dispatch path through a pre-computed :class:`PlacementTable` this
plane's daemon rebuilds every tick — off the take lock — from the
fleet's ``placement_view()`` export, the dispatcher's delivered-digest
ground truth, and the spu/family calibration below. The dispatcher's
take-path admit hook reads the table lock-free and defers a job (at
most ``DBX_PLACEMENT_DEFER_CAP`` polls, policy in ``sched.placement``)
when another worker's expected stage cost wins by the relative bar.
Stale or straggler-flagged workers are scored DOWN (penalty
multipliers), never excluded, so degraded telemetry degrades placement
quality, not liveness. The shadow scorer keeps running over the same
inputs, so measured regret now *validates* the live policy: live-mode
regret on a workload should sit strictly below the shadow-mode regret
the same workload records with ``DBX_PLACEMENT=0``.

Storage follows the span-ring discipline (obs/trace.py): a bounded
in-memory ring (``DBX_DECISIONS_RING``, default 256) serves
``/decisions.json`` and ``dbxwhy``'s live path, and each record also
lands in the opt-in JSONL event log (``DBX_OBS_JSONL``) as an
``ev="decision"`` line beside the spans it explains — one file,
``dbxwhy`` stitches both. Metrics stay bounded:
``dbx_dispatch_regret_seconds`` (no labels),
``dbx_decisions_total{route=...}`` over the fixed route vocabulary, and
agree/disagree shadow counters. Sustained high regret (EWMA past
``DBX_DECISIONS_REGRET_S`` for ``DBX_DECISIONS_REGRET_N`` consecutive
scored decisions) fires the flight recorder's ``regret`` trigger — a
fleet that keeps paying for placement is an incident, not a number.

``DBX_DECISIONS=0`` is the kill switch: the dispatcher stops building
raw dicts entirely (checked per RequestJobs, before any work).
Everything degrades to counting — a scoring error, an empty fleet, a
full queue, a dispatch rate past the ``DBX_DECISIONS_RATE`` scoring
budget — never a failed or delayed job.
"""

from __future__ import annotations

import collections
import math
import os
import threading
import time

from . import costmodel, events
from ..sched import placement as sched_placement
from .registry import get_registry, histogram_quantile

#: Payload-route vocabulary (bounded — metric label + record field).
#: ``held`` marks affinity-held jobs served outside the WFQ pop;
#: anything else folds to ``other``.
ROUTES = ("digest_only", "full", "delta", "scenario", "held")

#: Regret histogram bounds in seconds (one-sided latency-style; the
#: last bucket is +inf overflow). Finer than LATENCY_BUCKETS_S at the
#: low end — placement regret on a warm fleet is mostly milliseconds.
REGRET_BUCKETS_S = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0)

_SPU_ALPHA = 0.2      # per-worker seconds-per-model-unit EWMA
_REGRET_ALPHA = 0.25  # regret EWMA feeding the sustained-regret trigger
_DEFAULT_SPU = 1e-8   # pre-calibration seconds-per-unit (relative
#                       ranking only needs a shared starting point)


def route_bucket(route: str) -> str:
    """Bounded bucket for a payload route: one of ``ROUTES`` or
    ``"other"`` (the ``trigger_bucket`` discipline)."""
    return route if route in ROUTES else "other"


def enabled() -> bool:
    """``DBX_DECISIONS`` (default on): record dispatch decisions.
    ``0`` is the kill switch — the dispatcher skips record assembly
    entirely."""
    return os.environ.get("DBX_DECISIONS", "1").lower() not in (
        "0", "off", "false")


def ring_capacity() -> int:
    """``DBX_DECISIONS_RING`` (default 256): decision records retained
    in memory for ``/decisions.json`` and ``dbxwhy``."""
    try:
        return max(int(os.environ.get("DBX_DECISIONS_RING", 256)), 1)
    except ValueError:
        return 256


def h2d_rate_bps() -> float:
    """``DBX_DECISIONS_H2D_GBPS`` (default 2.0): nominal payload
    transfer rate used to price a non-resident panel's host-to-device
    (and wire) leg in the shadow score."""
    try:
        gbps = float(os.environ.get("DBX_DECISIONS_H2D_GBPS", 2.0))
    except ValueError:
        gbps = 2.0
    return max(gbps, 1e-3) * 1e9


def compile_wall_s() -> float:
    """``DBX_DECISIONS_COMPILE_S`` (default 0.531, the measured cold
    fused-sweep compile from DESIGN.md): cost charged when a strategy
    family has never been seen on a candidate worker."""
    try:
        return max(float(os.environ.get("DBX_DECISIONS_COMPILE_S",
                                        0.531)), 0.0)
    except ValueError:
        return 0.531


def score_rate() -> float:
    """``DBX_DECISIONS_RATE`` (default 50): scored decision records per
    second (token bucket, burst = one second of budget, floor 32).
    Scoring is pure-Python work on the
    plane's thread, and on a saturated small-core box an unbounded
    scorer would steal GIL time from the serving loop in proportion to
    the dispatch rate — so beyond the budget records degrade to a
    ``throttled`` counter (the flight posture: telemetry samples, it
    never taxes the fleet). ``0`` or negative disables the throttle
    (score everything — fine off the hot path on a multi-core box)."""
    try:
        return float(os.environ.get("DBX_DECISIONS_RATE", 50.0))
    except ValueError:
        return 50.0


def regret_bar_s() -> float:
    """``DBX_DECISIONS_REGRET_S`` (default 1.0): regret EWMA (seconds)
    past which the sustained-regret flight trigger arms."""
    try:
        return float(os.environ.get("DBX_DECISIONS_REGRET_S", 1.0))
    except ValueError:
        return 1.0


def regret_window() -> int:
    """``DBX_DECISIONS_REGRET_N`` (default 32): consecutive scored
    decisions the regret EWMA must stay past the bar before the flight
    trigger fires (one noisy decision is not an incident)."""
    try:
        return max(int(os.environ.get("DBX_DECISIONS_REGRET_N", 32)), 1)
    except ValueError:
        return 32


#: Score-down multipliers for degraded-but-live workers. Stale frames
#: mean the residency/warmth evidence is old; a straggler flag means the
#: worker is measurably slow this window. Multiplicative on the total
#: cost so a degraded worker loses ties and close calls but still wins
#: when it is the only one holding the state — scored down, never
#: excluded (the round-20 liveness rule).
STALE_PENALTY = 4.0
STRAGGLER_PENALTY = 2.0

#: (family, bars, combos) -> model units, module-wide: the op-model walk
#: is the expensive third of a score and shapes repeat across jobs,
#: planes, and the take-path ctx builder. Plain dict on purpose — every
#: operation is a single GIL-atomic get/set, a racy miss merely
#: recomputes the same value, and the bound clears wholesale (shapes are
#: wire-controlled input; nothing may grow per shape ever seen).
_UNITS_MEMO_MAX = 512
_units_memo: dict = {}


def model_units(family: str, bars: int, combos: int) -> float:
    """Model units for one job shape via the shared op model
    (``obs/costmodel.py``), memoized module-wide; falls back to raw
    cell-bars when the family is unmodelable. Pure Python/math (the op
    model imports no accelerator code), so the take-path ctx builder
    may call it under the queue lock."""
    family = str(family)
    bars = max(int(bars), 1)
    combos = max(int(combos), 1)
    key = (family, bars, combos)
    units = _units_memo.get(key)
    if units is not None:
        return units
    try:
        units = costmodel._model_units(family, bars, combos)
    except Exception:
        units = 0.0
    if units <= 0.0 or not math.isfinite(units):
        units = float(bars) * float(combos)
    if len(_units_memo) >= _UNITS_MEMO_MAX:
        _units_memo.clear()
    _units_memo[key] = units
    return units


def placement_cost(*, units: float, spu: float, panel_b: int = 0,
                   frac: float = 1.0, carry_hit: bool = False,
                   resident: bool = False, family_warm: bool = True,
                   rate: float | None = None, cold: float | None = None,
                   penalty: float = 1.0) -> dict:
    """THE op-model stage-cost estimate for (job shape, worker state) —
    the single implementation both the shadow scorer and the live
    placement table price with (the round-20 single-source rule; no
    second copy in ``sched/``):

    - execute wall: model ``units`` x the worker's seconds-per-unit
      ``spu``, times the delta fraction ``frac`` on a carry-store hit
      (an append job on the base holder prices only the new bars);
    - transfer: ``panel_b`` over the nominal h2d/wire ``rate`` unless
      the panel is resident (a carry hit implies the base is);
    - compile: the cold wall unless the strategy family is warm there;
    - ``penalty``: the stale/straggler score-down multiplier.
    """
    if rate is None:
        rate = h2d_rate_bps()
    if cold is None:
        cold = compile_wall_s()
    exec_s = units * spu
    if carry_hit:
        exec_s *= frac
    resident = bool(resident or carry_hit)
    transfer_s = 0.0 if resident else panel_b / rate
    compile_s = 0.0 if family_warm else cold
    return {"cost_s": (exec_s + transfer_s + compile_s) * penalty,
            "exec_s": exec_s, "transfer_s": transfer_s,
            "compile_s": compile_s, "carry_hit": carry_hit,
            "resident": resident, "penalty": penalty}


def placement_ctx(rec) -> dict:
    """Per-job scoring context for :meth:`PlacementTable.rank`, built
    from a dispatcher ``JobRecord`` (duck-typed — only plain field
    reads). Cheap enough for the take path: one memoized op-model
    lookup plus arithmetic; bars unknown at dispatch are estimated from
    the base length (appends) or panel bytes (~40 B/bar, the DBX1
    float64 row) exactly like the shadow scorer's raw view."""
    family = str(rec.strategy)
    combos = max(int(rec.combos), 1)
    base = str(rec.append_parent or "")
    base_len = int(rec.append_base_len or 0)
    panel_b = len(rec.ohlcv) if rec.ohlcv is not None else 0
    bars = int((rec.scenario or {}).get("n_bars", 0) or 0)
    if bars <= 0:
        bars = base_len if base_len > 0 else max(panel_b // 40, 1)
    if panel_b <= 0:
        panel_b = bars * 40
    frac = 1.0
    if base:
        frac = (bars - base_len) / bars if bars > base_len > 0 else 0.25
        frac = min(max(frac, 1e-3), 1.0)
    return {"units": model_units(family, bars, combos),
            "family": family,
            "digest": str(rec.panel_digest or ""),
            "base_digest": base,
            "panel_b": int(panel_b),
            "frac": frac,
            "rate": h2d_rate_bps(),
            "cold": compile_wall_s()}


class PlacementTable:
    """One immutable locality score table: everything the live
    placement stage needs to rank a job across the fleet, pre-computed
    OFF the take lock on the plane's daemon tick
    (:meth:`DecisionPlane.refresh_placement_table`). The dispatcher's
    admit hook reads the latest table with a single attribute load and
    calls :meth:`rank` under the queue lock — pure dict/math work over
    this frozen state, no locks, no I/O, no fleet folds.

    Per-worker state: calibrated seconds-per-unit, the stale/straggler
    score-down ``penalty``, the telemetry residency sketch (12-hex
    prefixes), the dispatcher's delivered-digest set (ground truth —
    held by reference; membership reads are GIL-atomic and a racy read
    is at worst one poll stale), and the compile-warm family set.

    ``any_warmth``: before ANY completion has calibrated a family
    anywhere, family warmth is unknown — charging everyone the cold
    wall would only drown the residency terms a fresh fleet CAN know
    (delivered digests), so an uncalibrated table treats every worker
    as warm. Once any family is known, unknown workers pay cold."""

    __slots__ = ("workers", "built_s", "default_spu", "any_warmth")

    def __init__(self, workers: dict, *, built_s: float,
                 default_spu: float):
        self.workers = workers
        self.built_s = built_s
        self.default_spu = default_spu
        self.any_warmth = any(w["fams"] for w in workers.values())

    _DEFAULT_W = {"spu": None, "penalty": 1.0, "prefixes": frozenset(),
                  "delivered": (), "fams": frozenset(),
                  "stale": False, "stragglers": ()}

    def score(self, ctx: dict, wid: str) -> dict:
        """Expected stage cost of ``ctx``'s job on one worker, via the
        shared :func:`placement_cost` (cross-pinned against the shadow
        scorer by test)."""
        w = self.workers.get(wid, self._DEFAULT_W)
        spu = w["spu"] if w["spu"] is not None else self.default_spu
        delivered = w["delivered"]
        base = ctx["base_digest"]
        digest = ctx["digest"]
        carry_hit = bool(base) and (base in delivered
                                    or base[:12] in w["prefixes"])
        resident = bool(digest) and (digest in delivered
                                     or digest[:12] in w["prefixes"])
        warm = (ctx["family"] in w["fams"]) if self.any_warmth else True
        return placement_cost(
            units=ctx["units"], spu=spu, panel_b=ctx["panel_b"],
            frac=ctx["frac"], carry_hit=carry_hit, resident=resident,
            family_warm=warm, rate=ctx["rate"], cold=ctx["cold"],
            penalty=w["penalty"])

    def rank(self, ctx: dict, polling: str) -> tuple:
        """Score ``ctx`` on every table worker plus the polling worker
        (which may be absent from the table — a worker's very first
        poll predates any frame or delivery); returns
        ``(my_cost, best_wid, best_cost)`` with ties by sorted wid."""
        mine = None
        best_wid = None
        best = None
        wids = set(self.workers)
        wids.add(polling)
        for wid in sorted(wids):
            c = self.score(ctx, wid)
            if wid == polling:
                mine = c
            if best is None or c["cost_s"] < best["cost_s"]:
                best_wid, best = wid, c
        return mine, best_wid, best


class DecisionPlane:
    """Per-dispatcher decision recorder + shadow placement scorer.

    Construction wires nothing global: the owning ``Dispatcher`` passes
    its ``FleetView`` and closes the plane in its own ``close()``. The
    scoring thread starts lazily on the first submit (the flight
    recorder's ``_ensure_thread`` discipline)."""

    QUEUE_MAX = 64        # pending decision batches; beyond this they drop
    _COMPLETIONS_MAX = 4096   # pending calibration obs (one per job)
    _SPU_MAX = 256        # per-worker calibration entries (hostile ids)
    _FAM_MAX = 64         # families remembered per worker
    _PENDING_UNITS_MAX = 2048   # jid -> units awaiting completion

    def __init__(self, *, fleet=None, registry=None,
                 clock=time.monotonic):
        self._fleet = fleet
        self._reg = registry or get_registry()
        self._clock = clock
        self._lock = threading.Lock()
        self._pending: collections.deque = collections.deque()
        # Completion side lane: appended without waking the thread (the
        # serving loop completes one job per call; per-job wakeups are a
        # GIL tax on a small-core box), drained whenever the score queue
        # goes idle or on the 5s housekeeping tick.
        self._completions: collections.deque = collections.deque()
        self._ring: collections.deque = collections.deque(
            maxlen=ring_capacity())
        self._wake = threading.Event()
        self._thread = None
        self._scoring = False
        self._closed = False
        # wid -> [n_obs, ewma seconds-per-model-unit]; completions feed
        # it (observe_completion), the shadow score reads it.
        self._spu: dict[str, list] = {}
        self._spu_global = [0, _DEFAULT_SPU]
        # wid -> set of strategy families completed there (compile-cache
        # hit proxy: first sighting pays the cold wall).
        self._fams: dict[str, set] = {}
        # jid -> (wid, family, model units) parked at scoring time so a
        # later completion can calibrate spu without re-deriving units.
        self._units_pending: "collections.OrderedDict" = \
            collections.OrderedDict()
        # Scoring-budget token bucket (score_rate): scoring-thread-only
        # state, no lock. Starts full (burst) so tests/short bursts are
        # never sampled.
        self._rate = score_rate()
        self._burst = max(self._rate, 32.0)
        self._tokens = self._burst
        self._t_refill = clock()
        # Live placement (round 20): armed by the owning dispatcher via
        # attach_placement; the daemon tick republishes _table (one
        # attribute swap — readers never lock) from the fleet view, the
        # dispatcher's delivered-digest callback, and the calibration
        # maps above.
        self._placement_armed = False
        self._delivered_fn = None
        self._table: PlacementTable | None = None
        self._n_scored = 0
        self._regret_sum = 0.0
        self._regret_ewma = 0.0
        self._regret_buckets = [0] * (len(REGRET_BUCKETS_S) + 1)
        self._hot_streak = 0
        self._agree = 0
        self._disagree = 0
        self._h_regret = self._reg.histogram(
            "dbx_dispatch_regret_seconds",
            help="shadow placement regret per dispatch decision: "
                 "cost(actual worker) - cost(best shadow candidate)",
            buckets=REGRET_BUCKETS_S)
        self._c_routes = {
            r: self._reg.counter(
                "dbx_decisions_total",
                help="dispatch decisions recorded, by payload route",
                route=r)
            for r in ROUTES + ("other",)}
        self._c_shadow = {
            o: self._reg.counter(
                "dbx_decisions_shadow_total",
                help="shadow scorer outcomes: did the actual placement "
                     "match the scorer's pick?",
                outcome=o)
            for o in ("agree", "disagree", "no_candidates")}
        self._c_dropped = {
            r: self._reg.counter(
                "dbx_decisions_dropped_total",
                help="decision batches/records not scored, by reason",
                reason=r)
            for r in ("queue_full", "closed", "error", "throttled")}

    # -- hot-path surface (dispatcher's RequestJobs) -------------------

    def want(self) -> bool:
        """Should the dispatcher bother recording the NEXT take()?
        True while the scoring budget (:func:`score_rate`) plausibly
        has a token. Read-only and lock-free — tokens are spent by
        :meth:`submit` on this same serving thread, so the estimate is
        exact between submits and a racy read is at worst one poll
        stale. This is the source-level throttle: an unarmed poll
        skips explain assembly, record tuples, and the submit
        entirely, so past the budget the hot path is byte-identical
        to the kill-switch path."""
        return (self._rate <= 0.0
                or self._tokens + (self._clock() - self._t_refill)
                * self._rate >= 1.0)

    def submit(self, batch: list, *, worker: str = "",
               t_take: float = 0.0) -> None:
        """Queue one take()'s decision records for async scoring.
        Items are either full raw dicts (tests, synthetic streams) or
        the dispatcher's deferred tuples ``(rec, route, digest,
        panel_b, wfq[, placement])`` — the record object plus the
        values only the dispatch loop knows, with ``worker``/``t_take``
        shared batch-wide. Tuple items cost the hot path one small allocation;
        the dict view is assembled on the scoring thread
        (:meth:`_raw_of`). The scoring budget is spent HERE, under the
        same lock the append needs anyway: records past the budget are
        dropped as ``throttled`` before they cost a queue slot, and
        the bucket state stays exact for :meth:`want`. Never raises,
        never blocks beyond that one small-lock crossing — the
        no-coordinator-on-the-hot-path bar applies verbatim."""
        if not batch:
            return
        if self._rate > 0.0:
            with self._lock:
                now = self._clock()
                self._tokens = min(
                    self._burst,
                    self._tokens + (now - self._t_refill) * self._rate)
                self._t_refill = now
                keep = min(len(batch), int(self._tokens))
                self._tokens -= keep
            if keep < len(batch):
                self._c_dropped["throttled"].inc(len(batch) - keep)
                if keep == 0:
                    return
                batch = batch[:keep]
        self._enqueue(("score", (list(batch), str(worker),
                                 float(t_take))), len(batch))

    def observe_completion(self, worker_id: str, jid: str,
                           elapsed_s: float) -> None:
        """Calibrate the per-worker seconds-per-unit EWMA from a real
        completion (measured end-to-end worker wall over the units the
        scorer parked for this jid) and mark the job's strategy family
        compile-warm on that worker. Completions ride a no-wake side
        lane the thread drains only once the score queue is idle — so a
        completion can never outrun its own decision's scoring, and the
        (per-job!) completion path never thrashes the scoring thread
        awake on a small-core box."""
        if elapsed_s <= 0.0:
            return
        self.observe_completions([(worker_id, jid, elapsed_s)])

    def observe_completions(self, batch: list[tuple]) -> None:
        """Batch form of :meth:`observe_completion` — one lock crossing
        for a whole CompleteJobs RPC's worth of ``(worker_id, jid,
        elapsed_s)`` tuples."""
        items = [(str(w), str(j), float(e)) for w, j, e in batch
                 if e > 0.0]
        if not items:
            return
        dropped = 0
        with self._lock:
            if self._closed:
                dropped = len(items)
            else:
                room = self._COMPLETIONS_MAX - len(self._completions)
                if room < len(items):
                    dropped = len(items) - max(room, 0)
                    items = items[:max(room, 0)]
                if items:
                    self._completions.extend(items)
                    self._ensure_thread()
        if dropped:
            self._c_dropped["queue_full"].inc(dropped)

    def _enqueue(self, item: tuple, weight: int) -> None:
        # No wake: the thread's own _TICK_S poll picks the batch up.
        # Event.set from the serving thread makes the scorer runnable
        # mid-RPC, and on a small-core box the forced context switch
        # costs the poll more than the whole record did; 50ms of
        # scoring latency costs telemetry nothing.
        drop = None
        with self._lock:
            if self._closed:
                drop = "closed"
            elif len(self._pending) >= self.QUEUE_MAX:
                drop = "queue_full"
            else:
                self._pending.append(item)
                self._ensure_thread()
        if drop is not None:
            self._c_dropped[drop].inc(weight)

    def _calibrate(self, worker_id: str, jid: str,
                   elapsed_s: float) -> None:
        with self._lock:
            hit = self._units_pending.pop(jid, None)
            if hit is None:
                return
            _, family, units = hit
            if units <= 0.0:
                return
            spu = elapsed_s / units
            per_worker = self._spu.get(worker_id)
            if per_worker is None:
                if len(self._spu) < self._SPU_MAX:
                    per_worker = self._spu[worker_id] = [
                        0, self._spu_global[1]]
                else:
                    per_worker = self._spu_global  # hostile-id cap
            cals = [per_worker]
            if per_worker is not self._spu_global:
                cals.append(self._spu_global)
            for cal in cals:
                n, ewma = cal
                cal[0] = n + 1
                cal[1] = spu if n == 0 else (
                    _SPU_ALPHA * spu + (1.0 - _SPU_ALPHA) * ewma)
            fams = self._fams.setdefault(worker_id, set())
            if len(fams) < self._FAM_MAX:
                fams.add(family)

    # -- live placement table (round 20) -------------------------------

    #: A table older than this is not served to the take path: a wedged
    #: scorer thread must degrade placement to pure WFQ, never freeze a
    #: view of a fleet that has moved on.
    TABLE_MAX_AGE_S = 2.0

    def attach_placement(self, delivered_fn=None) -> None:
        """Arm the live placement table: the daemon tick (the same 50 ms
        cadence that scores shadow batches) starts rebuilding the score
        table from the fleet's ``placement_view()`` export, the
        dispatcher's delivered-digest ground truth (``delivered_fn`` ->
        ``{wid: set-of-digests}``, sets held by REFERENCE — membership
        reads are GIL-atomic and at worst one poll stale), and this
        plane's spu/family calibration. Called once by the owning
        dispatcher while ``DBX_PLACEMENT`` is live; idempotent."""
        # Prime the op model's lazy tune.autotune import HERE, off every
        # lock: the take-path ctx builder calls model_units under the
        # queue lock, and a first-call import there would nest the
        # interpreter's import machinery inside it.
        model_units("sma_crossover", 2, 1)
        with self._lock:
            if self._closed:
                return
            self._delivered_fn = delivered_fn
            self._placement_armed = True
            self._ensure_thread()

    def refresh_placement_table(self) -> "PlacementTable":
        """Build and publish a fresh placement table NOW — the daemon
        tick's body, also the deterministic hook tests and bench call
        directly. Runs entirely off the take lock: one fleet fold, one
        delivered-map read, one pass over the calibration maps. The
        worker universe is fleet-view ∪ delivered-map: a worker with no
        telemetry frame (raw pollers, fresh fleets) still places by the
        dispatcher's own delivery ground truth."""
        view: dict = {}
        if self._fleet is not None:
            try:
                view = self._fleet.placement_view()
            except Exception:
                view = {}
        delivered: dict = {}
        fn = self._delivered_fn
        if fn is not None:
            try:
                delivered = fn() or {}
            except Exception:
                delivered = {}
        with self._lock:
            spu_of = {w: cal[1] for w, cal in self._spu.items()}
            default_spu = self._spu_global[1]
            fams = {w: frozenset(f) for w, f in self._fams.items()}
        workers = {}
        for wid in sorted(set(view) | set(delivered)):
            v = view.get(wid) or {}
            stale = bool(v.get("stale"))
            stragglers = tuple(v.get("stragglers") or ())
            penalty = 1.0
            if stale:
                penalty *= STALE_PENALTY
            if stragglers:
                penalty *= STRAGGLER_PENALTY
            workers[wid] = {
                "spu": spu_of.get(wid, default_spu),
                "penalty": penalty,
                "prefixes": frozenset(v.get("resident") or ()),
                "delivered": delivered.get(wid) or (),
                "fams": fams.get(wid, frozenset()),
                "stale": stale,
                "stragglers": stragglers,
            }
        table = PlacementTable(workers, built_s=self._clock(),
                               default_spu=default_spu)
        self._table = table
        return table

    def placement_table(self, max_age_s: float | None = None):
        """The latest placement table, or ``None`` when placement is
        unarmed, nothing has been built yet, or the builder has not
        ticked within ``max_age_s`` (degrade to pure WFQ). Lock-free:
        one attribute load plus a clock read."""
        t = self._table
        if t is None:
            return None
        bound = self.TABLE_MAX_AGE_S if max_age_s is None else max_age_s
        if self._clock() - t.built_s > bound:
            return None
        return t

    # -- scoring thread ------------------------------------------------

    def _ensure_thread(self) -> None:
        # Called under self._lock.
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="dbx-decisions", daemon=True)
            self._thread.start()

    _TICK_S = 0.05   # scoring-thread poll cadence (no hot-path wakes)

    def _run(self) -> None:
        while True:
            self._wake.wait(timeout=self._TICK_S)
            self._wake.clear()
            if self._placement_armed and not self._closed:
                # Live placement table refresh rides the same tick the
                # shadow scorer wakes on — "off the take lock" is this
                # thread, one attribute swap publishes the result.
                try:
                    self.refresh_placement_table()
                except Exception:
                    self._c_dropped["error"].inc()
            while True:
                completions = None
                payload = None
                with self._lock:
                    if self._closed:
                        return
                    if self._pending:
                        _op, payload = self._pending.popleft()
                        self._scoring = True
                    elif self._completions:
                        # Score queue idle: every decision enqueued
                        # before these completions has been scored (or
                        # dropped), so calibration can't outrun it.
                        completions = tuple(self._completions)
                        self._completions.clear()
                        self._scoring = True
                    else:
                        break
                try:
                    if payload is not None:
                        self._score_batch(payload)
                    else:
                        # One lock to discard completions the scorer
                        # never parked units for (throttled/unscored
                        # jobs — most of them under load).
                        with self._lock:
                            completions = [
                                c for c in completions
                                if c[1] in self._units_pending]
                        for comp in completions:
                            self._calibrate(*comp)
                except Exception:
                    self._c_dropped["error"].inc()
                finally:
                    with self._lock:
                        self._scoring = False

    @staticmethod
    def _raw_of(item, worker: str, t_take: float) -> dict:
        """Dict view of one submitted item — a raw dict verbatim, or
        the dispatcher's deferred ``(rec, route, digest, panel_b,
        wfq[, placement])`` tuple expanded from the job record's own
        fields HERE, on the scoring thread, so the take path never
        builds it. The optional 6th element is the live placement
        verdict the round-20 admit hook stashed for this job."""
        if isinstance(item, dict):
            return dict(item)
        rec, route, digest, panel_b, wfq = item[:5]
        placement = item[5] if len(item) > 5 else None
        return {
            **({"placement": placement} if placement else {}),
            "jid": rec.id, "trace_id": rec.trace_id,
            "worker": worker, "tenant": rec.tenant,
            "strategy": rec.strategy, "combos": float(rec.combos),
            "affinity_skips": int(rec.affinity_skips),
            "wfq": wfq, "digest": digest, "panel_b": int(panel_b),
            "append_parent": rec.append_parent,
            "base_len": int(rec.append_base_len),
            "bars": int((rec.scenario or {}).get("n_bars", 0)),
            "route": route, "t_take": t_take,
        }

    def _score_batch(self, payload) -> None:
        # Throttling happened at submit(); everything queued is scored.
        batch, worker, t_take = payload
        snap = None   # (workers, spu_of, spu_default, fams) per batch
        for item in batch:
            if snap is None:
                workers = {}
                if self._fleet is not None:
                    try:
                        workers = self._fleet.snapshot().get("workers",
                                                             {})
                    except Exception:
                        workers = {}
                delivered = {}
                if self._delivered_fn is not None:
                    try:
                        delivered = self._delivered_fn() or {}
                    except Exception:
                        delivered = {}
                with self._lock:
                    spu_of = {w: cal[1] for w, cal in self._spu.items()}
                    spu_default = self._spu_global[1]
                    fams = {w: set(f) for w, f in self._fams.items()}
                snap = (workers, spu_of, spu_default, fams, delivered)
            try:
                rec = self._score_one(self._raw_of(item, worker, t_take),
                                      *snap)
            except Exception:
                self._c_dropped["error"].inc()
                continue
            with self._lock:
                self._ring.append(rec)
            events.emit_record({"ev": "decision", **rec})

    @staticmethod
    def _resident(wentry: dict, digest: str) -> bool:
        """Panel residency by the worker's top-K digest sketch (the
        telemetry frame's ``caches.panel_topk`` 12-hex prefixes)."""
        if not digest:
            return False
        topk = (wentry.get("caches") or {}).get("panel_topk") or ()
        prefix = digest[:12]
        return any(str(e.get("d", "")) == prefix for e in topk
                   if isinstance(e, dict))

    def _units_for(self, raw: dict) -> tuple[float, str]:
        """Model units for this job via the shared module-wide memo
        (:func:`model_units`). Bars not known at dispatch are estimated
        from the full panel byte size (DBX1 ~ 5 float64 columns =>
        ~40 B/bar)."""
        family = str(raw.get("strategy", ""))
        combos = max(int(raw.get("combos", 0) or 0), 1)
        bars = int(raw.get("bars", 0) or 0)
        if bars <= 0:
            bars = max(int(int(raw.get("panel_b", 0) or 0) / 40), 1)
        return model_units(family, bars, combos), family

    def _score_one(self, raw: dict, workers: dict, spu_of: dict,
                   spu_default: float, fams: dict,
                   delivered: dict | None = None) -> dict:
        actual = str(raw.get("worker", ""))
        route = route_bucket(str(raw.get("route", "")))
        self._c_routes[route].inc()
        units, family = self._units_for(raw)
        digest = str(raw.get("digest", ""))
        base_digest = str(raw.get("append_parent", ""))
        panel_b = int(raw.get("panel_b", 0) or 0)
        # Delta fraction: the share of the sweep an append carry-hit
        # still has to price (new bars over total). Unknown => 0.25.
        frac = 1.0
        if base_digest:
            bars = int(raw.get("bars", 0) or 0)
            base_len = int(raw.get("base_len", 0) or 0)
            frac = ((bars - base_len) / bars
                    if bars > base_len > 0 else 0.25)
            frac = min(max(frac, 1e-3), 1.0)
        rate = h2d_rate_bps()
        cold = compile_wall_s()
        delivered = delivered or {}

        def score(wid: str, wentry: dict) -> dict:
            dlv = delivered.get(wid) or ()
            carry_hit = False
            if base_digest:
                # Carry-hit vs reprice: ground truth for the actual
                # worker (a delta route means the dispatcher verified
                # the base is held) and for any delivered-set holder;
                # the digest sketch for the rest of the shadows.
                carry_hit = (wid == actual and route == "delta") or \
                    self._resident(wentry, base_digest) or \
                    base_digest in dlv
            resident = (wid == actual and route in
                        ("digest_only", "delta", "scenario")) or \
                self._resident(wentry, digest) or \
                (bool(digest) and digest in dlv)
            # Degraded-but-live workers are scored down, never dropped
            # from the candidate set (the round-20 liveness rule).
            penalty = 1.0
            if wentry.get("stale"):
                penalty *= STALE_PENALTY
            if wentry.get("stragglers"):
                penalty *= STRAGGLER_PENALTY
            return placement_cost(
                units=units, spu=spu_of.get(wid, spu_default),
                panel_b=panel_b, frac=frac, carry_hit=carry_hit,
                resident=resident,
                family_warm=family in fams.get(wid, ()),
                rate=rate, cold=cold, penalty=penalty)

        candidates = dict(workers)
        for wid in delivered:
            candidates.setdefault(wid, {})
        if actual and actual not in candidates:
            candidates[actual] = {}
        scored = {wid: score(wid, e) for wid, e in
                  sorted(candidates.items())}
        shadow: dict = {"candidates": len(scored)}
        regret = None
        if scored:
            actual_cost = scored.get(actual, {}).get("cost_s")
            best = min(scored, key=lambda w: (scored[w]["cost_s"], w))
            if actual_cost is not None and \
                    actual_cost <= scored[best]["cost_s"]:
                best = actual   # ties go to the placement that happened
            shadow["best"] = best
            shadow["best_cost_s"] = round(scored[best]["cost_s"], 9)
            if actual_cost is not None:
                regret = max(actual_cost - scored[best]["cost_s"], 0.0)
                shadow["actual_cost_s"] = round(actual_cost, 9)
                shadow["regret_s"] = round(regret, 9)
                shadow["agree"] = best == actual
            # Bounded per-candidate breakdown: cheapest 8, always
            # including the actual worker.
            keep = sorted(scored, key=lambda w: (scored[w]["cost_s"], w))
            keep = list(dict.fromkeys(keep[:8] + [actual]))
            shadow["costs"] = {
                w: {k: (round(v, 9) if isinstance(v, float) else v)
                    for k, v in scored[w].items()}
                for w in keep if w in scored}
        age = workers.get(actual, {}).get("age_s")
        rec = {
            "jid": str(raw.get("jid", "")),
            "trace_id": str(raw.get("trace_id", "")),
            "worker": actual,
            "tenant": str(raw.get("tenant", "")),
            "route": route,
            "strategy": family,
            "combos": int(raw.get("combos", 0) or 0),
            "affinity_skips": int(raw.get("affinity_skips", 0) or 0),
            "fleet_age_s": age,
            "units": round(units, 3),
            "shadow": shadow,
            "t_take": float(raw.get("t_take", 0.0)),
        }
        placement = raw.get("placement")
        if placement:
            # The live placement verdict the admit hook stashed at
            # take time (round 20): chosen-vs-best worker, score gap,
            # defers spent. Shadow ranking above stays independent —
            # dual mode is the point (regret validates the policy).
            rec["placement"] = dict(placement)
        wfq = raw.get("wfq")
        if wfq is not None:
            # take() hands back live PickExplain objects; serializing
            # them (sort + round per pick) happens HERE, off the take
            # path. held_explain entries are already plain dicts.
            rec["wfq"] = (wfq.as_dict()
                          if hasattr(wfq, "as_dict") else wfq)
        self._account(rec, regret, family, units)
        return rec

    def _account(self, rec: dict, regret, family: str,
                 units: float) -> None:
        fire = None
        with self._lock:
            self._n_scored += 1
            jid = rec["jid"]
            if jid and units > 0.0:
                while len(self._units_pending) >= self._PENDING_UNITS_MAX:
                    self._units_pending.popitem(last=False)
                self._units_pending[jid] = (rec["worker"], family, units)
            if regret is None:
                self._c_shadow["no_candidates"].inc()
                return
            if rec["shadow"].get("agree"):
                self._agree += 1
            else:
                self._disagree += 1
            self._regret_sum += regret
            self._regret_ewma = (
                regret if self._n_scored == 1 else
                _REGRET_ALPHA * regret
                + (1.0 - _REGRET_ALPHA) * self._regret_ewma)
            i = 0
            while (i < len(REGRET_BUCKETS_S)
                   and regret > REGRET_BUCKETS_S[i]):
                i += 1
            self._regret_buckets[i] += 1
            if self._regret_ewma > regret_bar_s():
                self._hot_streak += 1
                if self._hot_streak >= regret_window():
                    fire = (rec["worker"], self._regret_ewma)
                    self._hot_streak = 0
            else:
                self._hot_streak = 0
        self._h_regret.observe(regret)
        self._c_shadow["agree" if rec["shadow"].get("agree")
                       else "disagree"].inc()
        if fire is not None:
            from . import flight

            flight.trigger(
                "regret", subject=fire[0],
                regret_ewma_s=round(fire[1], 4),
                window=regret_window(), bar_s=regret_bar_s())

    # -- read surface --------------------------------------------------

    def recent(self, n: int | None = None) -> list[dict]:
        """Newest-last tail of the decision ring."""
        with self._lock:
            if n is None or n >= len(self._ring):
                return list(self._ring)
            return list(self._ring)[len(self._ring) - n:]

    def snapshot(self, tail: int = 32) -> dict:
        """The ``/decisions.json`` document (and the flight recorder's
        ``decisions`` source): aggregate regret/agreement plus the
        record tail."""
        with self._lock:
            n = self._n_scored
            agree, disagree = self._agree, self._disagree
            buckets = list(self._regret_buckets)
            scored = sum(buckets)
            doc = {
                "enabled": enabled(),
                "n_scored": n,
                "ring": len(self._ring),
                "regret": {
                    "n": scored,
                    "sum_s": round(self._regret_sum, 9),
                    "ewma_s": round(self._regret_ewma, 9),
                    "p50_s": round(histogram_quantile(
                        buckets, REGRET_BUCKETS_S, 0.5), 9),
                    "p95_s": round(histogram_quantile(
                        buckets, REGRET_BUCKETS_S, 0.95), 9),
                },
                "calibrated_workers": len(self._spu),
                "recent": list(self._ring)[-max(tail, 0):],
            }
            table = self._table
            doc["placement"] = {
                "live": sched_placement.enabled(),
                "armed": self._placement_armed,
                "defer_cap": sched_placement.defer_cap(),
                "table": ({"workers": len(table.workers),
                           "age_s": round(max(
                               self._clock() - table.built_s, 0.0), 3)}
                          if table is not None else None),
            }
        judged = agree + disagree
        doc["agreement"] = {
            "agree": agree, "disagree": disagree,
            "pct": round(100.0 * agree / judged, 2) if judged else 0.0}
        return doc

    # -- lifecycle -----------------------------------------------------

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait for queued batches to score (tests / bench)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if (not self._pending and not self._completions
                        and not self._scoring):
                    return True
            self._wake.set()   # completions don't wake the thread
            time.sleep(0.005)
        return False

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._pending.clear()
            self._completions.clear()
        self._wake.set()
