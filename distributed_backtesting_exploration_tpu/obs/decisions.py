"""Dispatch decision plane: per-take explainability + shadow placement.

Every ``JobQueue.take()`` resolution is a layered placement decision —
a WFQ virtual-time pick (PR 8), possibly an affinity deferral (PR 6), a
payload route (digest-only / full / delta, PR 5/6; scenario-coalesced,
PR 18) — and none of it was observable: "why did job J land on worker W,
and what would it have cost elsewhere?" had no answer. This module is
that answer, built to the flight-recorder posture (obs/flight.py):

- the dispatcher hands :meth:`DecisionPlane.submit` one small tuple per
  dispatched job (the record object plus the four values only the
  dispatch loop knows — no dict assembly, no snapshot, no model math on
  the take path), a single small-lock deque append per poll; the
  scoring budget (``DBX_DECISIONS_RATE``) is spent right there, and
  :meth:`DecisionPlane.want` lets an over-budget poll skip explain
  assembly and the submit entirely — past the budget the hot path is
  byte-identical to the kill-switch path;
- a daemon thread scores each batch against ONE ``FleetView.snapshot()``:
  for every live worker it estimates the job's stage cost from the op
  model ``obs/costmodel.py`` and ``bench.py`` already share — execute
  wall from model units x a per-worker seconds-per-unit EWMA (calibrated
  by completions), **carry-hit vs reprice** (an append job on a worker
  whose top-K digest sketch holds the base panel pays only the delta
  fraction), **page residency vs h2d** (payload bytes over a nominal
  link rate unless the panel digest is resident), **compile-cache hit
  vs cold wall** (first sighting of a strategy family on a worker pays
  the cold-compile constant);
- ``regret = cost(actual) − cost(best_shadow)`` is recorded per decision
  (>= 0 — the actual worker is always a candidate) WITHOUT ever
  influencing dispatch: this is ROADMAP item 2 run in shadow mode, the
  measure-before-commit discipline the locality scorer will be held to.

Storage follows the span-ring discipline (obs/trace.py): a bounded
in-memory ring (``DBX_DECISIONS_RING``, default 256) serves
``/decisions.json`` and ``dbxwhy``'s live path, and each record also
lands in the opt-in JSONL event log (``DBX_OBS_JSONL``) as an
``ev="decision"`` line beside the spans it explains — one file,
``dbxwhy`` stitches both. Metrics stay bounded:
``dbx_dispatch_regret_seconds`` (no labels),
``dbx_decisions_total{route=...}`` over the fixed route vocabulary, and
agree/disagree shadow counters. Sustained high regret (EWMA past
``DBX_DECISIONS_REGRET_S`` for ``DBX_DECISIONS_REGRET_N`` consecutive
scored decisions) fires the flight recorder's ``regret`` trigger — a
fleet that keeps paying for placement is an incident, not a number.

``DBX_DECISIONS=0`` is the kill switch: the dispatcher stops building
raw dicts entirely (checked per RequestJobs, before any work).
Everything degrades to counting — a scoring error, an empty fleet, a
full queue, a dispatch rate past the ``DBX_DECISIONS_RATE`` scoring
budget — never a failed or delayed job.
"""

from __future__ import annotations

import collections
import math
import os
import threading
import time

from . import costmodel, events
from .registry import get_registry, histogram_quantile

#: Payload-route vocabulary (bounded — metric label + record field).
#: ``held`` marks affinity-held jobs served outside the WFQ pop;
#: anything else folds to ``other``.
ROUTES = ("digest_only", "full", "delta", "scenario", "held")

#: Regret histogram bounds in seconds (one-sided latency-style; the
#: last bucket is +inf overflow). Finer than LATENCY_BUCKETS_S at the
#: low end — placement regret on a warm fleet is mostly milliseconds.
REGRET_BUCKETS_S = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0)

_SPU_ALPHA = 0.2      # per-worker seconds-per-model-unit EWMA
_REGRET_ALPHA = 0.25  # regret EWMA feeding the sustained-regret trigger
_DEFAULT_SPU = 1e-8   # pre-calibration seconds-per-unit (relative
#                       ranking only needs a shared starting point)


def route_bucket(route: str) -> str:
    """Bounded bucket for a payload route: one of ``ROUTES`` or
    ``"other"`` (the ``trigger_bucket`` discipline)."""
    return route if route in ROUTES else "other"


def enabled() -> bool:
    """``DBX_DECISIONS`` (default on): record dispatch decisions.
    ``0`` is the kill switch — the dispatcher skips record assembly
    entirely."""
    return os.environ.get("DBX_DECISIONS", "1").lower() not in (
        "0", "off", "false")


def ring_capacity() -> int:
    """``DBX_DECISIONS_RING`` (default 256): decision records retained
    in memory for ``/decisions.json`` and ``dbxwhy``."""
    try:
        return max(int(os.environ.get("DBX_DECISIONS_RING", 256)), 1)
    except ValueError:
        return 256


def h2d_rate_bps() -> float:
    """``DBX_DECISIONS_H2D_GBPS`` (default 2.0): nominal payload
    transfer rate used to price a non-resident panel's host-to-device
    (and wire) leg in the shadow score."""
    try:
        gbps = float(os.environ.get("DBX_DECISIONS_H2D_GBPS", 2.0))
    except ValueError:
        gbps = 2.0
    return max(gbps, 1e-3) * 1e9


def compile_wall_s() -> float:
    """``DBX_DECISIONS_COMPILE_S`` (default 0.531, the measured cold
    fused-sweep compile from DESIGN.md): cost charged when a strategy
    family has never been seen on a candidate worker."""
    try:
        return max(float(os.environ.get("DBX_DECISIONS_COMPILE_S",
                                        0.531)), 0.0)
    except ValueError:
        return 0.531


def score_rate() -> float:
    """``DBX_DECISIONS_RATE`` (default 50): scored decision records per
    second (token bucket, burst = one second of budget, floor 32).
    Scoring is pure-Python work on the
    plane's thread, and on a saturated small-core box an unbounded
    scorer would steal GIL time from the serving loop in proportion to
    the dispatch rate — so beyond the budget records degrade to a
    ``throttled`` counter (the flight posture: telemetry samples, it
    never taxes the fleet). ``0`` or negative disables the throttle
    (score everything — fine off the hot path on a multi-core box)."""
    try:
        return float(os.environ.get("DBX_DECISIONS_RATE", 50.0))
    except ValueError:
        return 50.0


def regret_bar_s() -> float:
    """``DBX_DECISIONS_REGRET_S`` (default 1.0): regret EWMA (seconds)
    past which the sustained-regret flight trigger arms."""
    try:
        return float(os.environ.get("DBX_DECISIONS_REGRET_S", 1.0))
    except ValueError:
        return 1.0


def regret_window() -> int:
    """``DBX_DECISIONS_REGRET_N`` (default 32): consecutive scored
    decisions the regret EWMA must stay past the bar before the flight
    trigger fires (one noisy decision is not an incident)."""
    try:
        return max(int(os.environ.get("DBX_DECISIONS_REGRET_N", 32)), 1)
    except ValueError:
        return 32


class DecisionPlane:
    """Per-dispatcher decision recorder + shadow placement scorer.

    Construction wires nothing global: the owning ``Dispatcher`` passes
    its ``FleetView`` and closes the plane in its own ``close()``. The
    scoring thread starts lazily on the first submit (the flight
    recorder's ``_ensure_thread`` discipline)."""

    QUEUE_MAX = 64        # pending decision batches; beyond this they drop
    _COMPLETIONS_MAX = 4096   # pending calibration obs (one per job)
    _SPU_MAX = 256        # per-worker calibration entries (hostile ids)
    _FAM_MAX = 64         # families remembered per worker
    _PENDING_UNITS_MAX = 2048   # jid -> units awaiting completion

    def __init__(self, *, fleet=None, registry=None,
                 clock=time.monotonic):
        self._fleet = fleet
        self._reg = registry or get_registry()
        self._clock = clock
        self._lock = threading.Lock()
        self._pending: collections.deque = collections.deque()
        # Completion side lane: appended without waking the thread (the
        # serving loop completes one job per call; per-job wakeups are a
        # GIL tax on a small-core box), drained whenever the score queue
        # goes idle or on the 5s housekeeping tick.
        self._completions: collections.deque = collections.deque()
        self._ring: collections.deque = collections.deque(
            maxlen=ring_capacity())
        self._wake = threading.Event()
        self._thread = None
        self._scoring = False
        self._closed = False
        # wid -> [n_obs, ewma seconds-per-model-unit]; completions feed
        # it (observe_completion), the shadow score reads it.
        self._spu: dict[str, list] = {}
        self._spu_global = [0, _DEFAULT_SPU]
        # wid -> set of strategy families completed there (compile-cache
        # hit proxy: first sighting pays the cold wall).
        self._fams: dict[str, set] = {}
        # jid -> (wid, family, model units) parked at scoring time so a
        # later completion can calibrate spu without re-deriving units.
        self._units_pending: "collections.OrderedDict" = \
            collections.OrderedDict()
        # Scoring-budget token bucket (score_rate): scoring-thread-only
        # state, no lock. Starts full (burst) so tests/short bursts are
        # never sampled.
        self._rate = score_rate()
        self._burst = max(self._rate, 32.0)
        self._tokens = self._burst
        self._t_refill = clock()
        # (family, bars, combos) -> model units memo: the op-model walk
        # is ~1/3 of a record's scoring cost and fleets dispatch long
        # runs of identically-shaped jobs. Scoring-thread-only, bounded.
        self._units_memo: dict[tuple, float] = {}
        self._n_scored = 0
        self._regret_sum = 0.0
        self._regret_ewma = 0.0
        self._regret_buckets = [0] * (len(REGRET_BUCKETS_S) + 1)
        self._hot_streak = 0
        self._agree = 0
        self._disagree = 0
        self._h_regret = self._reg.histogram(
            "dbx_dispatch_regret_seconds",
            help="shadow placement regret per dispatch decision: "
                 "cost(actual worker) - cost(best shadow candidate)",
            buckets=REGRET_BUCKETS_S)
        self._c_routes = {
            r: self._reg.counter(
                "dbx_decisions_total",
                help="dispatch decisions recorded, by payload route",
                route=r)
            for r in ROUTES + ("other",)}
        self._c_shadow = {
            o: self._reg.counter(
                "dbx_decisions_shadow_total",
                help="shadow scorer outcomes: did the actual placement "
                     "match the scorer's pick?",
                outcome=o)
            for o in ("agree", "disagree", "no_candidates")}
        self._c_dropped = {
            r: self._reg.counter(
                "dbx_decisions_dropped_total",
                help="decision batches/records not scored, by reason",
                reason=r)
            for r in ("queue_full", "closed", "error", "throttled")}

    # -- hot-path surface (dispatcher's RequestJobs) -------------------

    def want(self) -> bool:
        """Should the dispatcher bother recording the NEXT take()?
        True while the scoring budget (:func:`score_rate`) plausibly
        has a token. Read-only and lock-free — tokens are spent by
        :meth:`submit` on this same serving thread, so the estimate is
        exact between submits and a racy read is at worst one poll
        stale. This is the source-level throttle: an unarmed poll
        skips explain assembly, record tuples, and the submit
        entirely, so past the budget the hot path is byte-identical
        to the kill-switch path."""
        return (self._rate <= 0.0
                or self._tokens + (self._clock() - self._t_refill)
                * self._rate >= 1.0)

    def submit(self, batch: list, *, worker: str = "",
               t_take: float = 0.0) -> None:
        """Queue one take()'s decision records for async scoring.
        Items are either full raw dicts (tests, synthetic streams) or
        the dispatcher's deferred 5-tuples ``(rec, route, digest,
        panel_b, wfq)`` — the record object plus the four values only
        the dispatch loop knows, with ``worker``/``t_take`` shared
        batch-wide. Tuple items cost the hot path one small allocation;
        the dict view is assembled on the scoring thread
        (:meth:`_raw_of`). The scoring budget is spent HERE, under the
        same lock the append needs anyway: records past the budget are
        dropped as ``throttled`` before they cost a queue slot, and
        the bucket state stays exact for :meth:`want`. Never raises,
        never blocks beyond that one small-lock crossing — the
        no-coordinator-on-the-hot-path bar applies verbatim."""
        if not batch:
            return
        if self._rate > 0.0:
            with self._lock:
                now = self._clock()
                self._tokens = min(
                    self._burst,
                    self._tokens + (now - self._t_refill) * self._rate)
                self._t_refill = now
                keep = min(len(batch), int(self._tokens))
                self._tokens -= keep
            if keep < len(batch):
                self._c_dropped["throttled"].inc(len(batch) - keep)
                if keep == 0:
                    return
                batch = batch[:keep]
        self._enqueue(("score", (list(batch), str(worker),
                                 float(t_take))), len(batch))

    def observe_completion(self, worker_id: str, jid: str,
                           elapsed_s: float) -> None:
        """Calibrate the per-worker seconds-per-unit EWMA from a real
        completion (measured end-to-end worker wall over the units the
        scorer parked for this jid) and mark the job's strategy family
        compile-warm on that worker. Completions ride a no-wake side
        lane the thread drains only once the score queue is idle — so a
        completion can never outrun its own decision's scoring, and the
        (per-job!) completion path never thrashes the scoring thread
        awake on a small-core box."""
        if elapsed_s <= 0.0:
            return
        self.observe_completions([(worker_id, jid, elapsed_s)])

    def observe_completions(self, batch: list[tuple]) -> None:
        """Batch form of :meth:`observe_completion` — one lock crossing
        for a whole CompleteJobs RPC's worth of ``(worker_id, jid,
        elapsed_s)`` tuples."""
        items = [(str(w), str(j), float(e)) for w, j, e in batch
                 if e > 0.0]
        if not items:
            return
        dropped = 0
        with self._lock:
            if self._closed:
                dropped = len(items)
            else:
                room = self._COMPLETIONS_MAX - len(self._completions)
                if room < len(items):
                    dropped = len(items) - max(room, 0)
                    items = items[:max(room, 0)]
                if items:
                    self._completions.extend(items)
                    self._ensure_thread()
        if dropped:
            self._c_dropped["queue_full"].inc(dropped)

    def _enqueue(self, item: tuple, weight: int) -> None:
        # No wake: the thread's own _TICK_S poll picks the batch up.
        # Event.set from the serving thread makes the scorer runnable
        # mid-RPC, and on a small-core box the forced context switch
        # costs the poll more than the whole record did; 50ms of
        # scoring latency costs telemetry nothing.
        drop = None
        with self._lock:
            if self._closed:
                drop = "closed"
            elif len(self._pending) >= self.QUEUE_MAX:
                drop = "queue_full"
            else:
                self._pending.append(item)
                self._ensure_thread()
        if drop is not None:
            self._c_dropped[drop].inc(weight)

    def _calibrate(self, worker_id: str, jid: str,
                   elapsed_s: float) -> None:
        with self._lock:
            hit = self._units_pending.pop(jid, None)
            if hit is None:
                return
            _, family, units = hit
            if units <= 0.0:
                return
            spu = elapsed_s / units
            per_worker = self._spu.get(worker_id)
            if per_worker is None:
                if len(self._spu) < self._SPU_MAX:
                    per_worker = self._spu[worker_id] = [
                        0, self._spu_global[1]]
                else:
                    per_worker = self._spu_global  # hostile-id cap
            cals = [per_worker]
            if per_worker is not self._spu_global:
                cals.append(self._spu_global)
            for cal in cals:
                n, ewma = cal
                cal[0] = n + 1
                cal[1] = spu if n == 0 else (
                    _SPU_ALPHA * spu + (1.0 - _SPU_ALPHA) * ewma)
            fams = self._fams.setdefault(worker_id, set())
            if len(fams) < self._FAM_MAX:
                fams.add(family)

    # -- scoring thread ------------------------------------------------

    def _ensure_thread(self) -> None:
        # Called under self._lock.
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="dbx-decisions", daemon=True)
            self._thread.start()

    _TICK_S = 0.05   # scoring-thread poll cadence (no hot-path wakes)

    def _run(self) -> None:
        while True:
            self._wake.wait(timeout=self._TICK_S)
            self._wake.clear()
            while True:
                completions = None
                payload = None
                with self._lock:
                    if self._closed:
                        return
                    if self._pending:
                        _op, payload = self._pending.popleft()
                        self._scoring = True
                    elif self._completions:
                        # Score queue idle: every decision enqueued
                        # before these completions has been scored (or
                        # dropped), so calibration can't outrun it.
                        completions = tuple(self._completions)
                        self._completions.clear()
                        self._scoring = True
                    else:
                        break
                try:
                    if payload is not None:
                        self._score_batch(payload)
                    else:
                        # One lock to discard completions the scorer
                        # never parked units for (throttled/unscored
                        # jobs — most of them under load).
                        with self._lock:
                            completions = [
                                c for c in completions
                                if c[1] in self._units_pending]
                        for comp in completions:
                            self._calibrate(*comp)
                except Exception:
                    self._c_dropped["error"].inc()
                finally:
                    with self._lock:
                        self._scoring = False

    @staticmethod
    def _raw_of(item, worker: str, t_take: float) -> dict:
        """Dict view of one submitted item — a raw dict verbatim, or
        the dispatcher's deferred ``(rec, route, digest, panel_b,
        wfq)`` tuple expanded from the job record's own fields HERE,
        on the scoring thread, so the take path never builds it."""
        if isinstance(item, dict):
            return dict(item)
        rec, route, digest, panel_b, wfq = item
        return {
            "jid": rec.id, "trace_id": rec.trace_id,
            "worker": worker, "tenant": rec.tenant,
            "strategy": rec.strategy, "combos": float(rec.combos),
            "affinity_skips": int(rec.affinity_skips),
            "wfq": wfq, "digest": digest, "panel_b": int(panel_b),
            "append_parent": rec.append_parent,
            "base_len": int(rec.append_base_len),
            "bars": int((rec.scenario or {}).get("n_bars", 0)),
            "route": route, "t_take": t_take,
        }

    def _score_batch(self, payload) -> None:
        # Throttling happened at submit(); everything queued is scored.
        batch, worker, t_take = payload
        snap = None   # (workers, spu_of, spu_default, fams) per batch
        for item in batch:
            if snap is None:
                workers = {}
                if self._fleet is not None:
                    try:
                        workers = self._fleet.snapshot().get("workers",
                                                             {})
                    except Exception:
                        workers = {}
                with self._lock:
                    spu_of = {w: cal[1] for w, cal in self._spu.items()}
                    spu_default = self._spu_global[1]
                    fams = {w: set(f) for w, f in self._fams.items()}
                snap = (workers, spu_of, spu_default, fams)
            try:
                rec = self._score_one(self._raw_of(item, worker, t_take),
                                      *snap)
            except Exception:
                self._c_dropped["error"].inc()
                continue
            with self._lock:
                self._ring.append(rec)
            events.emit_record({"ev": "decision", **rec})

    @staticmethod
    def _resident(wentry: dict, digest: str) -> bool:
        """Panel residency by the worker's top-K digest sketch (the
        telemetry frame's ``caches.panel_topk`` 12-hex prefixes)."""
        if not digest:
            return False
        topk = (wentry.get("caches") or {}).get("panel_topk") or ()
        prefix = digest[:12]
        return any(str(e.get("d", "")) == prefix for e in topk
                   if isinstance(e, dict))

    def _units_for(self, raw: dict) -> tuple[float, str]:
        """Model units for this job via the shared op model; falls back
        to raw cell-bars when the family is unmodelable. Bars not known
        at dispatch are estimated from the full panel byte size (DBX1 ~
        5 float64 columns => ~40 B/bar)."""
        family = str(raw.get("strategy", ""))
        combos = max(int(raw.get("combos", 0) or 0), 1)
        bars = int(raw.get("bars", 0) or 0)
        if bars <= 0:
            bars = max(int(int(raw.get("panel_b", 0) or 0) / 40), 1)
        key = (family, bars, combos)
        units = self._units_memo.get(key)
        if units is not None:
            return units, family
        try:
            units = costmodel._model_units(family, bars, combos)
        except Exception:
            units = 0.0
        if units <= 0.0 or not math.isfinite(units):
            units = float(bars) * float(combos)
        if len(self._units_memo) >= 512:    # shapes are wire-controlled
            self._units_memo.clear()
        self._units_memo[key] = units
        return units, family

    def _score_one(self, raw: dict, workers: dict, spu_of: dict,
                   spu_default: float, fams: dict) -> dict:
        actual = str(raw.get("worker", ""))
        route = route_bucket(str(raw.get("route", "")))
        self._c_routes[route].inc()
        units, family = self._units_for(raw)
        digest = str(raw.get("digest", ""))
        base_digest = str(raw.get("append_parent", ""))
        panel_b = int(raw.get("panel_b", 0) or 0)
        # Delta fraction: the share of the sweep an append carry-hit
        # still has to price (new bars over total). Unknown => 0.25.
        frac = 1.0
        if base_digest:
            bars = int(raw.get("bars", 0) or 0)
            base_len = int(raw.get("base_len", 0) or 0)
            frac = ((bars - base_len) / bars
                    if bars > base_len > 0 else 0.25)
            frac = min(max(frac, 1e-3), 1.0)
        rate = h2d_rate_bps()
        cold = compile_wall_s()

        def score(wid: str, wentry: dict) -> dict:
            spu = spu_of.get(wid, spu_default)
            exec_s = units * spu
            carry_hit = False
            if base_digest:
                # Carry-hit vs reprice: ground truth for the actual
                # worker (a delta route means the dispatcher verified
                # the base is held); the digest sketch for shadows.
                carry_hit = (wid == actual and route == "delta") or \
                    self._resident(wentry, base_digest)
                if carry_hit:
                    exec_s *= frac
            resident = (wid == actual and route in
                        ("digest_only", "delta", "scenario")) or \
                self._resident(wentry, digest) or carry_hit
            transfer_s = 0.0 if resident else panel_b / rate
            compile_s = 0.0 if family in fams.get(wid, ()) else cold
            return {"cost_s": exec_s + transfer_s + compile_s,
                    "exec_s": exec_s, "transfer_s": transfer_s,
                    "compile_s": compile_s, "carry_hit": carry_hit,
                    "resident": resident}

        candidates = {wid: e for wid, e in workers.items()
                      if not e.get("stale")}
        if actual and actual not in candidates:
            candidates[actual] = workers.get(actual, {})
        scored = {wid: score(wid, e) for wid, e in
                  sorted(candidates.items())}
        shadow: dict = {"candidates": len(scored)}
        regret = None
        if scored:
            actual_cost = scored.get(actual, {}).get("cost_s")
            best = min(scored, key=lambda w: (scored[w]["cost_s"], w))
            if actual_cost is not None and \
                    actual_cost <= scored[best]["cost_s"]:
                best = actual   # ties go to the placement that happened
            shadow["best"] = best
            shadow["best_cost_s"] = round(scored[best]["cost_s"], 9)
            if actual_cost is not None:
                regret = max(actual_cost - scored[best]["cost_s"], 0.0)
                shadow["actual_cost_s"] = round(actual_cost, 9)
                shadow["regret_s"] = round(regret, 9)
                shadow["agree"] = best == actual
            # Bounded per-candidate breakdown: cheapest 8, always
            # including the actual worker.
            keep = sorted(scored, key=lambda w: (scored[w]["cost_s"], w))
            keep = list(dict.fromkeys(keep[:8] + [actual]))
            shadow["costs"] = {
                w: {k: (round(v, 9) if isinstance(v, float) else v)
                    for k, v in scored[w].items()}
                for w in keep if w in scored}
        age = workers.get(actual, {}).get("age_s")
        rec = {
            "jid": str(raw.get("jid", "")),
            "trace_id": str(raw.get("trace_id", "")),
            "worker": actual,
            "tenant": str(raw.get("tenant", "")),
            "route": route,
            "strategy": family,
            "combos": int(raw.get("combos", 0) or 0),
            "affinity_skips": int(raw.get("affinity_skips", 0) or 0),
            "fleet_age_s": age,
            "units": round(units, 3),
            "shadow": shadow,
            "t_take": float(raw.get("t_take", 0.0)),
        }
        wfq = raw.get("wfq")
        if wfq is not None:
            # take() hands back live PickExplain objects; serializing
            # them (sort + round per pick) happens HERE, off the take
            # path. held_explain entries are already plain dicts.
            rec["wfq"] = (wfq.as_dict()
                          if hasattr(wfq, "as_dict") else wfq)
        self._account(rec, regret, family, units)
        return rec

    def _account(self, rec: dict, regret, family: str,
                 units: float) -> None:
        fire = None
        with self._lock:
            self._n_scored += 1
            jid = rec["jid"]
            if jid and units > 0.0:
                while len(self._units_pending) >= self._PENDING_UNITS_MAX:
                    self._units_pending.popitem(last=False)
                self._units_pending[jid] = (rec["worker"], family, units)
            if regret is None:
                self._c_shadow["no_candidates"].inc()
                return
            if rec["shadow"].get("agree"):
                self._agree += 1
            else:
                self._disagree += 1
            self._regret_sum += regret
            self._regret_ewma = (
                regret if self._n_scored == 1 else
                _REGRET_ALPHA * regret
                + (1.0 - _REGRET_ALPHA) * self._regret_ewma)
            i = 0
            while (i < len(REGRET_BUCKETS_S)
                   and regret > REGRET_BUCKETS_S[i]):
                i += 1
            self._regret_buckets[i] += 1
            if self._regret_ewma > regret_bar_s():
                self._hot_streak += 1
                if self._hot_streak >= regret_window():
                    fire = (rec["worker"], self._regret_ewma)
                    self._hot_streak = 0
            else:
                self._hot_streak = 0
        self._h_regret.observe(regret)
        self._c_shadow["agree" if rec["shadow"].get("agree")
                       else "disagree"].inc()
        if fire is not None:
            from . import flight

            flight.trigger(
                "regret", subject=fire[0],
                regret_ewma_s=round(fire[1], 4),
                window=regret_window(), bar_s=regret_bar_s())

    # -- read surface --------------------------------------------------

    def recent(self, n: int | None = None) -> list[dict]:
        """Newest-last tail of the decision ring."""
        with self._lock:
            if n is None or n >= len(self._ring):
                return list(self._ring)
            return list(self._ring)[len(self._ring) - n:]

    def snapshot(self, tail: int = 32) -> dict:
        """The ``/decisions.json`` document (and the flight recorder's
        ``decisions`` source): aggregate regret/agreement plus the
        record tail."""
        with self._lock:
            n = self._n_scored
            agree, disagree = self._agree, self._disagree
            buckets = list(self._regret_buckets)
            scored = sum(buckets)
            doc = {
                "enabled": enabled(),
                "n_scored": n,
                "ring": len(self._ring),
                "regret": {
                    "n": scored,
                    "sum_s": round(self._regret_sum, 9),
                    "ewma_s": round(self._regret_ewma, 9),
                    "p50_s": round(histogram_quantile(
                        buckets, REGRET_BUCKETS_S, 0.5), 9),
                    "p95_s": round(histogram_quantile(
                        buckets, REGRET_BUCKETS_S, 0.95), 9),
                },
                "calibrated_workers": len(self._spu),
                "recent": list(self._ring)[-max(tail, 0):],
            }
        judged = agree + disagree
        doc["agreement"] = {
            "agree": agree, "disagree": disagree,
            "pct": round(100.0 * agree / judged, 2) if judged else 0.0}
        return doc

    # -- lifecycle -----------------------------------------------------

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait for queued batches to score (tests / bench)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if (not self._pending and not self._completions
                        and not self._scoring):
                    return True
            self._wake.set()   # completions don't wake the thread
            time.sleep(0.005)
        return False

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._pending.clear()
            self._completions.clear()
        self._wake.set()
