"""Flight recorder: anomaly-triggered black-box capture.

The fleet's steady-state telemetry (metrics registry, span ring,
FleetView gossip) is rich but EPHEMERAL: the 512-entry span ring rolls
over in seconds at floor throughput, gauges move on, and by the time an
operator looks at an incident the evidence is gone. This module is the
black-box counterpart — always armed, near-zero cost until a trigger
fires, and on a trigger it snapshots everything the process knows into
one content-addressed JSON bundle:

- the full span ring (``trace.recent_spans()``),
- every registered source's scrape (metrics text, FleetView snapshot,
  queue/journal stats, schedule registry, lockdep edge table — sources
  are keyed callables registered by the owning subsystem),
- a stitched fleet timeline (``timeline.summarize_spans``) plus the
  end-to-end timeline + critical path of the offending job(s).

Trigger catalogue (the ``_KINDS`` tuple): job failure, SLO queue-wait
breach, straggler flag, requeue-expiry, lockdep violation, cost-model
residual blowout, worker collect failure, explicit ``TriggerDump``
admin RPC, SIGUSR2, sustained placement regret (obs/decisions.py).

Operational posture, in order of importance:

1. **Never block the hot path.** ``trigger()`` takes the recorder's own
   small lock for a dedupe-map probe and a deque append, then returns;
   the capture itself (scrapes + JSON + fsync-free atomic write) runs
   on a daemon thread. No source is scraped under the recorder lock —
   each source callable takes only its own scrape-path locks, which is
   exactly what the lockdep gate (``DBX_LOCKDEP=1``) verifies in tests.
2. **Never fail a job.** Unwritable ``DBX_FLIGHT_DIR``, a crashing
   source, a full disk — all degrade to a counter
   (``dbx_flight_dropped_total``) and a log line.
3. **Bounded everything.** Bundles are retention-bounded by count and
   size (``DBX_FLIGHT_MAX_BUNDLES`` / ``DBX_FLIGHT_MAX_MB``, oldest
   evicted first); a crash loop dedupes by (kind, subject) within
   ``DBX_FLIGHT_DEDUPE_S`` to ONE bundle; the pending queue is 8 deep;
   the dedupe map is capped.

Bundles are content-addressed: the filename embeds a blake2b digest of
the serialized bundle, so a byte-identical capture (same ring, same
sources) is free, and ``dbxflight diff`` can compare two bundles by
name alone. ``dbxflight`` (console script) lists/inspects/diffs bundles
and renders embedded timelines via ``obs.timeline``'s renderer.
"""

from __future__ import annotations

import argparse
import collections
import difflib
import hashlib
import json
import logging
import os
import queue
import sys
import threading
import time

from . import timeline, trace
from .registry import get_registry

log = logging.getLogger("dbx.flight")

#: The trigger catalogue. ``trigger_bucket`` folds anything else into
#: "other" so the ``trigger`` metric label (and bundle filenames) stay
#: bounded — the obs-cardinality lint sanctions this call the same way
#: it sanctions ``tenant_bucket``.
_KINDS = ("job_fail", "slo_breach", "straggler", "requeue_expired",
          "lockdep", "residual", "collect_fail", "admin", "signal",
          "regret")

#: Lock-free trigger inbox for hostile acquire-site contexts. The
#: lockdep violation hook fires while the offending locks are still
#: held — any ``threading.Lock`` taken there (the recorder's, the
#: registry's) would stitch the recorder into the caller's lock-order
#: graph and distort the very edge table being reported.
#: ``queue.SimpleQueue`` is C-level and untouched by lockdep's
#: ``threading.Lock`` factory patch, so ``trigger_deferred`` acquires
#: nothing; items drain through the normal ``trigger()`` path on the
#: capture thread (or a ``flush()``) where no caller locks are held.
_DEFERRED: "queue.SimpleQueue" = queue.SimpleQueue()


def trigger_bucket(kind: str) -> str:
    """Bounded bucket for a trigger kind: one of ``_KINDS`` or
    ``"other"``. Used for metric labels and bundle filenames."""
    return kind if kind in _KINDS else "other"


def known_kinds() -> frozenset:
    """The bundle-kind vocabulary THIS binary understands — the
    ``dbxflight`` CLI's forward-compat gate (the PR-16 skip-and-count
    seam extended to kinds): a bundle written by a newer binary with a
    kind outside this set is skipped-and-counted by ``list`` and
    rendered generically by ``show``, never a crash."""
    return frozenset(_KINDS + ("other",))


def flight_dir() -> str:
    """``DBX_FLIGHT_DIR``: where bundles land. Unset/empty means the
    recorder counts triggers but writes nothing (safe default — no
    surprise files)."""
    return os.environ.get("DBX_FLIGHT_DIR", "")


def max_mb() -> float:
    """``DBX_FLIGHT_MAX_MB`` (default 64): total bundle bytes kept;
    oldest evicted first."""
    try:
        return max(float(os.environ.get("DBX_FLIGHT_MAX_MB", 64.0)), 0.0)
    except ValueError:
        return 64.0


def max_bundles() -> int:
    """``DBX_FLIGHT_MAX_BUNDLES`` (default 32): bundle count kept;
    oldest evicted first."""
    try:
        return max(int(os.environ.get("DBX_FLIGHT_MAX_BUNDLES", 32)), 1)
    except ValueError:
        return 32


def dedupe_s() -> float:
    """``DBX_FLIGHT_DEDUPE_S`` (default 60): window within which a
    repeated (kind, subject) trigger is dropped — a crash loop yields
    one bundle, not hundreds."""
    try:
        return max(float(os.environ.get("DBX_FLIGHT_DEDUPE_S", 60.0)), 0.0)
    except ValueError:
        return 60.0


class FlightRecorder:
    """Always-armed bounded black-box. One per process in practice
    (module singleton below); tests construct their own against a fresh
    registry."""

    QUEUE_MAX = 8           # pending triggers; beyond this they drop
    _RECENT_MAX = 256       # dedupe map bound (hostile subject storm)

    def __init__(self, *, registry=None, clock=time.monotonic):
        self._reg = registry or get_registry()
        self._clock = clock
        self._lock = threading.Lock()
        self._pending = collections.deque()
        self._recent: dict[tuple[str, str], float] = {}
        self._sources: dict[str, object] = {}
        self._thread = None
        self._wake = threading.Event()
        self._capturing = False
        self._closed = False
        self._c_bundles = self._reg.counter(
            "dbx_flight_bundles_total",
            help="flight bundles written to DBX_FLIGHT_DIR")
        self._c_dropped = {
            r: self._reg.counter(
                "dbx_flight_dropped_total",
                help="triggers that produced no new bundle, by reason",
                reason=r)
            for r in ("dedupe", "disabled", "queue_full", "error")}
        self._c_triggers = {
            b: self._reg.counter(
                "dbx_flight_triggers_total",
                help="flight triggers fired, by bounded trigger bucket",
                trigger=b)
            for b in _KINDS + ("other",)}

    # -- sources ------------------------------------------------------

    def add_source(self, name: str, fn) -> None:
        """Register a keyed zero-arg scrape callable (last-wins, the
        registry ``add_collector`` discipline). The callable runs on
        the capture thread and may take only its own scrape-path locks."""
        with self._lock:
            self._sources[str(name)] = fn

    def remove_source(self, name: str) -> None:
        with self._lock:
            self._sources.pop(str(name), None)

    # -- triggering ---------------------------------------------------

    def trigger(self, kind: str, subject: str = "", **detail) -> None:
        """Fire-and-forget: count, dedupe, enqueue for async capture.
        Never raises, never blocks beyond one small-lock probe."""
        try:
            self._trigger(kind, subject, detail)
        except Exception:
            log.exception("flight trigger failed (kind=%s)", kind)

    def _trigger(self, kind: str, subject: str, detail: dict) -> None:
        self._c_triggers[trigger_bucket(kind)].inc()
        now = self._clock()
        drop = None
        with self._lock:
            if self._closed:
                drop = "disabled"
            else:
                key = (str(kind), str(subject))
                stamp = self._recent.get(key)
                if stamp is not None and now - stamp < dedupe_s():
                    drop = "dedupe"
                elif not flight_dir():
                    drop = "disabled"
                elif len(self._pending) >= self.QUEUE_MAX:
                    drop = "queue_full"
                else:
                    self._remember(key, now)
                    self._pending.append(
                        (str(kind), str(subject), dict(detail)))
                    self._ensure_thread()
        if drop is not None:
            self._c_dropped[drop].inc()
        else:
            self._wake.set()

    def _remember(self, key, now) -> None:
        # Called under self._lock.
        if len(self._recent) >= self._RECENT_MAX:
            for old in sorted(self._recent,
                              key=self._recent.get)[:self._RECENT_MAX // 2]:
                del self._recent[old]
        self._recent[key] = now

    def capture_now(self, kind: str, subject: str = "",
                    detail: dict | None = None) -> str | None:
        """Synchronous capture (admin RPC / SIGUSR2 / tests): bypasses
        dedupe and the queue, returns the bundle path or None."""
        self._c_triggers[trigger_bucket(kind)].inc()
        if not flight_dir():
            self._c_dropped["disabled"].inc()
            return None
        return self._capture(str(kind), str(subject), dict(detail or {}))

    def _drain_deferred(self) -> None:
        """Route deferred (lock-free inbox) triggers through the normal
        path. Runs only where no caller locks are held: the capture
        thread's loop and ``flush``. The inbox is process-global, so
        only the process singleton drains it — a test-private recorder
        must not adopt incidents deposited for (or by) another
        generation."""
        if _recorder is not self:
            return
        while True:
            try:
                kind, subject, detail = _DEFERRED.get_nowait()
            except queue.Empty:
                return
            self.trigger(kind, subject, **detail)

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait for pending async captures to land (test helper)."""
        self._drain_deferred()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._pending and not self._capturing:
                    return True
            time.sleep(0.01)
        return False

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._pending.clear()
        self._wake.set()

    # -- capture thread ----------------------------------------------

    def _ensure_thread(self) -> None:
        # Called under self._lock.
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="dbx-flight", daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while True:
            self._wake.wait(timeout=5.0)
            self._wake.clear()
            self._drain_deferred()
            while True:
                with self._lock:
                    if self._closed:
                        return
                    if not self._pending:
                        break
                    kind, subject, detail = self._pending.popleft()
                    self._capturing = True
                try:
                    self._capture(kind, subject, detail)
                finally:
                    with self._lock:
                        self._capturing = False

    # -- bundle assembly ---------------------------------------------

    def _capture(self, kind: str, subject: str,
                 detail: dict) -> str | None:
        try:
            doc = self._build_bundle(kind, subject, detail)
            return self._write_bundle(doc)
        except Exception:
            log.exception("flight capture failed (kind=%s)", kind)
            self._c_dropped["error"].inc()
            return None

    def _build_bundle(self, kind: str, subject: str,
                      detail: dict) -> dict:
        spans = trace.recent_spans()
        with self._lock:
            sources = dict(self._sources)
        scraped = {}
        for name, fn in sorted(sources.items()):
            try:
                scraped[name] = fn()
            except Exception as e:  # a broken source must not void the rest
                scraped[name] = {"error": repr(e)}
        doc = {
            "v": 1,
            "kind": str(kind),
            "subject": str(subject),
            "detail": detail,
            "t_wall": time.time(),
            "pid": os.getpid(),
            "spans": spans,
            "sources": scraped,
        }
        try:
            doc["timeline"] = timeline.summarize_spans(spans)
        except Exception as e:
            doc["timeline"] = {"error": repr(e)}
        doc["jobs"] = self._job_timelines(
            spans, str(detail.get("job") or subject))
        return doc

    @staticmethod
    def _job_timelines(spans, job: str) -> list:
        """End-to-end stitch of the offending job(s): reconstructed
        timelines whose job id (or trace id prefix) matches, with the
        per-stage critical path — no torn-job filter, a failed job's
        partial timeline is exactly the evidence we want."""
        if not job:
            return []
        out = []
        try:
            for tid, tl in sorted(timeline.reconstruct(spans).items()):
                if tl.job_id != job and not tid.startswith(job):
                    continue
                t0, t1 = tl.window
                out.append({
                    "trace_id": tid,
                    "job_id": tl.job_id,
                    "worker": tl.worker,
                    "t0": t0,
                    "dur_s": max(t1 - t0, 0.0),
                    "stages": timeline.critical_path(tl),
                    "spans": [dict(s) for s in tl.spans],
                })
        except Exception as e:
            return [{"error": repr(e)}]
        return out

    def _write_bundle(self, doc: dict) -> str | None:
        d = flight_dir()
        payload = json.dumps(doc, sort_keys=True, default=str)
        digest = hashlib.blake2b(
            payload.encode(), digest_size=8).hexdigest()
        stamp = time.strftime("%Y%m%dT%H%M%S",
                              time.gmtime(doc.get("t_wall", 0.0)))
        name = f"{stamp}-{trigger_bucket(doc['kind'])}-{digest}.json"
        path = os.path.join(d, name)
        try:
            os.makedirs(d, exist_ok=True)
            if os.path.exists(path):
                # Content-addressed: identical capture already on disk.
                self._c_dropped["dedupe"].inc()
                return path
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, path)
            self._c_bundles.inc()
            self._retain(d)
            log.info("flight bundle %s (%s/%s)", name, doc["kind"],
                     doc["subject"])
            return path
        except OSError:
            log.exception("flight dir %r unwritable; dropping bundle", d)
            self._c_dropped["error"].inc()
            return None

    @staticmethod
    def _retain(d: str) -> None:
        """Evict oldest bundles past the count/size caps. Best-effort —
        racing evictors (two processes, one dir) tolerate ENOENT."""
        try:
            entries = []
            for name in os.listdir(d):
                if not name.endswith(".json"):
                    continue
                p = os.path.join(d, name)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                entries.append((st.st_mtime, name, st.st_size, p))
            entries.sort()
            total = sum(e[2] for e in entries)
            cap_b = max_mb() * 1024 * 1024
            cap_n = max_bundles()
            while entries and (len(entries) > cap_n or total > cap_b):
                _, _, size, p = entries.pop(0)
                try:
                    os.remove(p)
                except OSError:
                    pass
                total -= size
        except OSError:
            pass


# -- module singleton (the get_registry() discipline) -----------------

_recorder: FlightRecorder | None = None
_recorder_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def reset(registry=None) -> None:
    """Replace the singleton (test isolation: bind a fresh recorder to
    a given registry so counter assertions don't see prior state)."""
    global _recorder
    with _recorder_lock:
        if _recorder is not None:
            _recorder.close()
        _recorder = FlightRecorder(registry=registry) \
            if registry is not None else None
    while True:  # stale deferred triggers die with the generation
        try:
            _DEFERRED.get_nowait()
        except queue.Empty:
            break


def trigger(kind: str, subject: str = "", **detail) -> None:
    """Module-level convenience: fire the process recorder."""
    get_recorder().trigger(kind, subject, **detail)


def trigger_deferred(kind: str, subject: str = "", **detail) -> None:
    """Lock-free trigger for callers holding instrumented locks (the
    lockdep violation hook). See the ``_DEFERRED`` note."""
    _DEFERRED.put((str(kind), str(subject), dict(detail)))


def capture_now(kind: str, subject: str = "",
                detail: dict | None = None) -> str | None:
    return get_recorder().capture_now(kind, subject, detail)


def add_source(name: str, fn) -> None:
    get_recorder().add_source(name, fn)


def remove_source(name: str) -> None:
    get_recorder().remove_source(name)


# -- dbxflight CLI ----------------------------------------------------

def _load_bundle(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _bundle_paths(d: str) -> list:
    try:
        names = [n for n in os.listdir(d) if n.endswith(".json")]
    except OSError:
        return []
    return [os.path.join(d, n) for n in sorted(names)]


def _fmt_row(cols, widths) -> str:
    return "  ".join(str(c).ljust(w) for c, w in zip(cols, widths)).rstrip()


def _cmd_list(d: str) -> int:
    paths = _bundle_paths(d)
    if not paths:
        print(f"dbxflight: no bundles in {d or '(no dir)'}",
              file=sys.stderr)
        return 2
    rows = []
    unknown = 0
    for p in paths:
        try:
            doc = _load_bundle(p)
        except (OSError, ValueError):
            rows.append((os.path.basename(p), "?", "?", "?", "?"))
            continue
        if doc.get("kind", "?") not in known_kinds():
            # Forward-compat: a newer binary's bundle kind. Skip and
            # count — an old CLI must not crash on (or misrender) a
            # schema it predates.
            unknown += 1
            continue
        rows.append((os.path.basename(p), doc.get("kind", "?"),
                     doc.get("subject", "") or "-",
                     len(doc.get("spans", ())),
                     len(doc.get("jobs", ()))))
    if unknown:
        print(f"dbxflight: skipped {unknown} bundle(s) with unknown "
              "kind (written by a newer binary?)", file=sys.stderr)
    if not rows:
        print(f"dbxflight: no listable bundles in {d}", file=sys.stderr)
        return 2
    header = ("bundle", "kind", "subject", "spans", "jobs")
    widths = [max(len(str(r[i])) for r in rows + [header])
              for i in range(len(header))]
    print(_fmt_row(header, widths))
    for r in rows:
        print(_fmt_row(r, widths))
    return 0


def _resolve(d: str, ref: str) -> str | None:
    """A bundle ref: a path, a basename, or a unique name prefix."""
    if os.path.isfile(ref):
        return ref
    hits = [p for p in _bundle_paths(d)
            if os.path.basename(p).startswith(ref)]
    return hits[0] if len(hits) == 1 else None


def _cmd_show(d: str, ref: str, as_json: bool) -> int:
    path = _resolve(d, ref)
    if path is None:
        print(f"dbxflight: no unique bundle matches {ref!r}",
              file=sys.stderr)
        return 2
    try:
        doc = _load_bundle(path)
    except (OSError, ValueError) as e:
        print(f"dbxflight: unreadable bundle {path}: {e}",
              file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0
    if doc.get("kind", "?") not in known_kinds():
        # The kind seam, show-side: render only the generic envelope —
        # the kind-specific body belongs to a newer schema.
        print(f"bundle   {os.path.basename(path)}")
        print(f"kind     {doc.get('kind', '?')} (unknown to this "
              "binary; use --json for the raw bundle)")
        return 0
    print(f"bundle   {os.path.basename(path)}")
    print(f"kind     {doc.get('kind', '?')}  subject "
          f"{doc.get('subject', '') or '-'}")
    print(f"captured {time.strftime('%Y-%m-%d %H:%M:%S', time.gmtime(float(doc.get('t_wall', 0.0))))}Z"
          f"  pid {doc.get('pid', '?')}  spans {len(doc.get('spans', ()))}")
    if doc.get("detail"):
        print(f"detail   {json.dumps(doc['detail'], sort_keys=True)}")
    sources = doc.get("sources", {})
    if sources:
        print("sources  " + ", ".join(sorted(sources)))
    for job in doc.get("jobs", ()):
        if "error" in job:
            continue
        stages = ", ".join(f"{k}={v * 1e3:.1f}ms" for k, v in
                           sorted(job.get("stages", {}).items()))
        print(f"\njob {job.get('job_id') or job.get('trace_id', '?')}"
              f"  worker={job.get('worker') or '-'}"
              f"  dur={job.get('dur_s', 0.0) * 1e3:.1f}ms")
        if stages:
            print(f"  critical path: {stages}")
    summary = doc.get("timeline")
    if isinstance(summary, dict) and "error" not in summary \
            and summary.get("jobs"):
        print()
        try:
            print(timeline.render_text(summary))
        except Exception as e:
            print(f"(timeline render failed: {e!r})")
    return 0


def _source_text(doc: dict, name: str) -> str:
    v = doc.get("sources", {}).get(name)
    if isinstance(v, str):
        return v
    return json.dumps(v, indent=2, sort_keys=True, default=str)


def _cmd_diff(d: str, ref_a: str, ref_b: str) -> int:
    pa, pb = _resolve(d, ref_a), _resolve(d, ref_b)
    if pa is None or pb is None:
        missing = ref_a if pa is None else ref_b
        print(f"dbxflight: no unique bundle matches {missing!r}",
              file=sys.stderr)
        return 2
    try:
        a, b = _load_bundle(pa), _load_bundle(pb)
    except (OSError, ValueError) as e:
        print(f"dbxflight: unreadable bundle: {e}", file=sys.stderr)
        return 2
    for key in ("kind", "subject", "t_wall", "pid"):
        va, vb = a.get(key), b.get(key)
        marker = " " if va == vb else "*"
        print(f"{marker} {key:8s} {va!r} -> {vb!r}")
    print(f"  spans    {len(a.get('spans', ()))} -> "
          f"{len(b.get('spans', ()))}")
    names = sorted(set(a.get("sources", {})) | set(b.get("sources", {})))
    for name in names:
        in_a, in_b = (name in a.get("sources", {}),
                      name in b.get("sources", {}))
        if not (in_a and in_b):
            print(f"* source {name}: "
                  f"{'present' if in_a else 'absent'} -> "
                  f"{'present' if in_b else 'absent'}")
    if "metrics" in a.get("sources", {}) and \
            "metrics" in b.get("sources", {}):
        diff = difflib.unified_diff(
            _source_text(a, "metrics").splitlines(),
            _source_text(b, "metrics").splitlines(),
            fromfile=os.path.basename(pa), tofile=os.path.basename(pb),
            lineterm="", n=0)
        lines = list(diff)
        if lines:
            print()
            print("\n".join(lines))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dbxflight",
        description="list/inspect/diff flight-recorder bundles")
    ap.add_argument("--dir", default=None,
                    help="bundle dir (default: $DBX_FLIGHT_DIR)")
    sub = ap.add_subparsers(dest="cmd")
    sub.add_parser("list", help="list bundles")
    p_show = sub.add_parser("show", help="inspect one bundle")
    p_show.add_argument("bundle", help="path, basename, or name prefix")
    p_show.add_argument("--json", action="store_true",
                        help="dump the raw bundle JSON")
    p_diff = sub.add_parser("diff", help="compare two bundles")
    p_diff.add_argument("bundle_a")
    p_diff.add_argument("bundle_b")
    args = ap.parse_args(argv)
    d = args.dir if args.dir is not None else flight_dir()
    if args.cmd in (None, "list"):
        return _cmd_list(d)
    if args.cmd == "show":
        return _cmd_show(d, args.bundle, args.json)
    return _cmd_diff(d, args.bundle_a, args.bundle_b)


if __name__ == "__main__":
    sys.exit(main())
