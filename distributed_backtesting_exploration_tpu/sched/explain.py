"""Pick-time explain records for the WFQ scheduler (round 19).

The dispatch decision plane (obs/decisions.py) answers "why did job J
land on worker W" — and the first half of that answer is scheduler
state: which tenant lane heads competed for this pop, what virtual tags
they carried, who got quota-demoted, and where the served tenant's
virtual finish landed. That state lives only inside
``WfqScheduler.pick`` and is gone the instant the pop returns, so the
scheduler exposes it through an explain hook: ``pick(n, explain=[...])``
appends one :class:`PickExplain` per served job, built from exactly the
values the pick itself used (no re-derivation — the record can never
disagree with the decision).

Determinism contract: the record is a pure function of the scheduler's
logical state (lanes, finish tags, quota charges) — never of wall
clocks, ids(), or map iteration order (competing heads are sorted by
tenant). Two queues with the same intake history produce bit-identical
explain dicts on BOTH state-machine substrates (the WFQ index is shared
Python either way), and a journal-replayed queue reproduces the original
run's records with virtual time restarting at 0 (the PR-8 replay
semantics). Tested in tests/test_decisions.py.

The hook costs nothing when unused: ``pick`` takes ``explain=None`` by
default and the record assembly is gated on it.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class PickExplain:
    """Scheduler state behind one served job, captured at pop time.

    ``heads`` is the competing-lane snapshot: tenant -> the virtual
    start tag its head carried this pop (the winner included), sorted by
    tenant name and bounded by tenants with live work. ``demoted`` lists
    the tenants whose over-quota heads were pushed behind every in-quota
    tenant on this pop (empty when no demotion happened). ``vtime`` is
    the scheduler's virtual time BEFORE the pop; ``tag`` the winning
    head's virtual start tag (which becomes the new virtual time);
    ``vfinish`` the served tenant's virtual finish AFTER the charge
    (``tag + cost / weight``)."""

    jid: str
    tenant: str
    tag: float
    vtime: float
    vfinish: float
    cost: float
    weight: float
    over_quota: bool
    demoted: list[str] = dataclasses.field(default_factory=list)
    heads: dict[str, float] = dataclasses.field(default_factory=dict)

    #: Competing-head snapshot bound: tenants beyond this many (sorted
    #: by tenant name) are dropped from ``heads`` and counted in
    #: ``heads_dropped`` — tenant ids are wire-controlled strings and a
    #: decision record must stay O(1), not O(tenants).
    MAX_HEADS = 8

    def as_dict(self) -> dict:
        """JSON-able form, floats rounded to stable widths (the span
        ring's ``round`` discipline — reproducible bytes, not 17-digit
        float noise)."""
        heads = dict(sorted(self.heads.items())[:self.MAX_HEADS])
        out = {
            "jid": self.jid,
            "tenant": self.tenant,
            "tag": round(self.tag, 9),
            "vtime": round(self.vtime, 9),
            "vfinish": round(self.vfinish, 9),
            "cost": round(self.cost, 9),
            "weight": round(self.weight, 9),
            "over_quota": bool(self.over_quota),
            "demoted": sorted(self.demoted),
            "heads": {t: round(v, 9) for t, v in heads.items()},
        }
        dropped = len(self.heads) - len(heads)
        if dropped > 0:
            out["heads_dropped"] = dropped
        return out


def held_explain(jid: str) -> dict:
    """The explain record of a job served from the placement-held list:
    it skipped the WFQ pop entirely this round (front-of-line service
    after a locality deferral — round 20's generalization of the old
    one-shot append-affinity hold), so there is no pick-time scheduler
    state to report — only the fact of the hold. The ``affinity_held``
    key name survives from round 6 for record-schema stability."""
    return {"jid": jid, "affinity_held": True}
