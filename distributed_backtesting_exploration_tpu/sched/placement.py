"""Locality-scored placement: the deferral budget (round 20).

The decision plane scored every take against the fleet for a full round
in shadow (obs/decisions.py, round 19) — regret told us what dispatch
was leaving on the table: a carry-store hit prices only the ΔT fraction
of an append sweep (98.3x on BENCH_r07), panel residency skips the h2d
leg, a compile-cache hit skips the 531 ms cold wall (BENCH_r10). This
module is the LIVE half's policy core: given the polling worker's
expected stage cost and the best candidate's, decide whether a job may
wait one more poll for a better-placed worker.

Design split (the no-coordinator-on-the-hot-path bar):

- **Scoring** lives in obs/decisions.py — ONE op-model implementation
  (``placement_cost``) shared by the shadow scorer and the live score
  table, which the plane's daemon refreshes off the take lock.
- **Policy** lives HERE, in the scheduling package, as pure functions
  over two numbers and a counter: :func:`should_defer` is the entire
  deferral budget. The dispatcher's admit hook composes the two.

Deferral semantics (generalizing — and replacing — the round-6 one-shot
append-affinity special case):

- A job is deferred only while the best-scored worker beats the polling
  worker by at least ``PLACEMENT_RATIO`` (a *relative* bar: the op
  model's absolute seconds are calibration-dependent, but the ratio
  between a carry hit and a full reprice, or resident vs h2d, is not).
- Each deferral increments ``JobRecord.affinity_skips`` (NOT journaled
  — restarts restart locality cold); at ``DBX_PLACEMENT_DEFER_CAP``
  the job is served to whoever polls. Work-conserving by construction:
  a better worker that never polls costs at most ``cap`` poll rounds,
  never a starved job.
- Stale or straggler-flagged workers are scored DOWN by the table
  (penalty multipliers), never excluded — a flapping telemetry frame
  must degrade placement quality, not dispatch liveness.
- ``DBX_PLACEMENT=0`` kills the whole stage: the dispatcher passes no
  admit hook and take() degrades to pure WFQ order, bit-identical to
  round 19.

Chain settling (:func:`should_wait_for_parent`): an append link whose
PARENT job has not yet dispatched scores "no holder anywhere" — every
worker prices the same full reprice, the ratio bar never clears, and
the link is served blind to whoever polls first, pinning the rest of
the chain to the wrong worker. The dispatcher therefore also defers a
link while its parent's digest is still pending in the queue
(``JobQueue._pending_digests``), charged against the SAME
``affinity_skips`` budget — a chain can wait for its parent to settle,
but never past the cap, so a parent that fails or never dispatches
costs at most ``cap`` poll rounds before the child serves anyway.
"""

from __future__ import annotations

import os

#: The best candidate must beat the polling worker's expected stage cost
#: by this factor before a deferral is worth a poll round. Relative on
#: purpose: pre-calibration the op model only ranks (shared default
#: seconds-per-unit), and shared terms (e.g. a family cold on every
#: worker) cancel out of the ratio's discriminating power but would
#: swamp any absolute threshold.
PLACEMENT_RATIO = 1.5


def enabled() -> bool:
    """``DBX_PLACEMENT`` (default on): locality-scored placement in the
    live take path. ``0`` is the kill switch — the dispatcher passes no
    admit hook at all and dispatch order is pure WFQ (round-19
    behavior, bit-identical)."""
    return os.environ.get("DBX_PLACEMENT", "1").lower() not in (
        "0", "off", "false")


def defer_cap() -> int:
    """``DBX_PLACEMENT_DEFER_CAP`` (default 2): how many polls a job may
    wait for its best-scored worker before anyone serves it. ``0``
    keeps scoring live (records, counters, dbxwhy rank) but never
    defers."""
    try:
        return max(int(os.environ.get("DBX_PLACEMENT_DEFER_CAP", 2)), 0)
    except ValueError:
        return 2


def should_defer(my_cost_s: float, best_cost_s: float,
                 skips: int, cap: int) -> bool:
    """The entire deferral budget: wait for the better worker iff the
    budget has room AND the best candidate wins by the relative bar.
    Ties (and any non-finite garbage from a poisoned model) serve
    immediately — placement may only ever *delay* a job, by at most
    ``cap`` polls, never park it."""
    if skips >= cap:
        return False
    if not (my_cost_s >= 0.0 and best_cost_s >= 0.0):   # NaN-safe
        return False
    return best_cost_s * PLACEMENT_RATIO < my_cost_s


def should_wait_for_parent(skips: int, cap: int) -> bool:
    """Chain-settling deferral: may an append link wait one more poll
    for its still-pending parent to dispatch (and so MINT the carry
    state the score table would route on)? Same budget as
    :func:`should_defer` — the two draw on one ``affinity_skips``
    counter, so waiting on a parent spends polls a locality deferral
    could have used, and the cap bounds the sum."""
    return skips < cap
