"""Multi-tenant scheduling: weighted fair queueing, quotas, tenant labels.

"Millions of users" (ROADMAP item 5) breaks the single-FIFO abstraction:
one whale tenant's 100k-combo grid sweep parks everyone else's latency
behind it. This package owns the two pieces the dispatcher composes:

- :mod:`.wfq` — a virtual-time weighted-fair-queueing index over the
  round-5 batched queue state machine (one per-tenant pending lane per
  pop), with per-tenant weights (``DBX_TENANT_WEIGHTS``) and in-flight
  quotas (``DBX_TENANT_QUOTA``) that demote over-quota *pending* work
  behind other tenants' virtual time — leased jobs are never yanked;
- :mod:`.tenancy` — the ``default`` tenant constant (proto3-default
  mapping for legacy clients) and the BOUNDED tenant-bucket label map
  that makes ``dbx_queue_jobs{tenant=...}`` safe under dbxlint's
  obs-cardinality rule;
- :mod:`.explain` — the pick-time explain records (round 19) the
  dispatch decision plane (obs/decisions.py) stitches into per-job
  "why this worker" reports;
- :mod:`.placement` — the locality-placement deferral budget (round
  20): pure policy (``should_defer`` + the ``DBX_PLACEMENT`` /
  ``DBX_PLACEMENT_DEFER_CAP`` knobs) over the stage costs the decision
  plane's score table computes off the take lock.
"""

from . import placement  # noqa: F401
from .explain import PickExplain, held_explain  # noqa: F401
from .tenancy import (  # noqa: F401
    DEFAULT_TENANT, OVERFLOW_BUCKET, reset_tenant_buckets,
    stream_bucket, tenant_bucket, worker_bucket)
from .wfq import WfqScheduler, parse_tenant_map  # noqa: F401
