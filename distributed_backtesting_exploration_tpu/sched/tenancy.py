"""Tenant identity + the bounded tenant-bucket metric label map.

Tenant ids are operator-chosen strings and therefore UNBOUNDED runtime
data from the metric registry's point of view: one gauge child per
distinct tenant, forever, in every ``/metrics`` scrape and every
``obs_json`` payload — exactly what dbxlint's obs-cardinality rule
exists to reject. Per-tenant observability still matters (a starved
tenant must be visible), so the label value goes through ONE process-
wide bounded map: the first ``DBX_TENANT_LABEL_MAX`` distinct tenants
keep their own name as the label, every later tenant shares the
``other`` bucket. The mapping is sticky for the process lifetime (a
tenant never changes buckets mid-run — its time series stays one
series) and the rule recognizes ``tenant_bucket(...)`` as a sanctioned
label source.
"""

from __future__ import annotations

import os
import threading

#: The tenant every legacy client lands in: proto3's default empty
#: ``JobSpec.tenant_id``, journal records without a ``tenant`` key, and
#: CLI runs without ``--tenant`` all map here — single-tenant dispatch
#: order through the WFQ lane is bit-identical to the pre-tenancy FIFO.
DEFAULT_TENANT = "default"

#: Shared label for every tenant past the bucket cap.
OVERFLOW_BUCKET = "other"

_DEFAULT_LABEL_MAX = 16

_BUCKET_LOCK = threading.Lock()
_BUCKETS: dict[str, str] = {}


def _label_max() -> int:
    """Bucket cap, read lazily (import-time capture would pin the knob
    before tests/operators can set it)."""
    return int(os.environ.get("DBX_TENANT_LABEL_MAX", _DEFAULT_LABEL_MAX))


def tenant_bucket(tenant: str) -> str:
    """The bounded metric label for ``tenant``.

    First ``DBX_TENANT_LABEL_MAX`` distinct tenants map to themselves,
    later ones to :data:`OVERFLOW_BUCKET`; assignment is first-contact
    sticky so a tenant's series never splits. This is THE sanctioned
    way to put tenant identity on a metric label (dbxlint
    obs-cardinality treats ``tenant_bucket(...)`` as bounded by
    construction).
    """
    t = tenant or DEFAULT_TENANT
    with _BUCKET_LOCK:
        hit = _BUCKETS.get(t)
        if hit is not None:
            return hit
        if len(_BUCKETS) < _label_max():
            _BUCKETS[t] = t
            return t
    # Past the cap nothing is stored: tenant ids are wire-controlled
    # strings, and one dict entry per distinct id ever seen would be an
    # unbounded leak in exactly the component built to bound tenant
    # cardinality. Overflow tenants recompute to the same answer every
    # call (only a mid-run DBX_TENANT_LABEL_MAX raise could re-home one
    # — an explicit operator action).
    return OVERFLOW_BUCKET


def reset_tenant_buckets() -> None:
    """Drop all sticky assignments (tests; a fresh process equivalent)."""
    with _BUCKET_LOCK:
        _BUCKETS.clear()
