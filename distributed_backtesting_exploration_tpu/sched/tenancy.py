"""Tenant identity + the bounded tenant-bucket metric label map.

Tenant ids are operator-chosen strings and therefore UNBOUNDED runtime
data from the metric registry's point of view: one gauge child per
distinct tenant, forever, in every ``/metrics`` scrape and every
``obs_json`` payload — exactly what dbxlint's obs-cardinality rule
exists to reject. Per-tenant observability still matters (a starved
tenant must be visible), so the label value goes through ONE process-
wide bounded map: the first ``DBX_TENANT_LABEL_MAX`` distinct tenants
keep their own name as the label, every later tenant shares the
``other`` bucket. The mapping is sticky for the process lifetime (a
tenant never changes buckets mid-run — its time series stays one
series) and the rule recognizes ``tenant_bucket(...)`` as a sanctioned
label source.
"""

from __future__ import annotations

import os
import threading

#: The tenant every legacy client lands in: proto3's default empty
#: ``JobSpec.tenant_id``, journal records without a ``tenant`` key, and
#: CLI runs without ``--tenant`` all map here — single-tenant dispatch
#: order through the WFQ lane is bit-identical to the pre-tenancy FIFO.
DEFAULT_TENANT = "default"

#: Shared label for every tenant past the bucket cap.
OVERFLOW_BUCKET = "other"

_DEFAULT_LABEL_MAX = 16

_BUCKET_LOCK = threading.Lock()
_BUCKETS: dict[str, str] = {}


def _label_max() -> int:
    """Bucket cap, read lazily (import-time capture would pin the knob
    before tests/operators can set it)."""
    return int(os.environ.get("DBX_TENANT_LABEL_MAX", _DEFAULT_LABEL_MAX))

def _sticky_bucket(store: dict, lock: threading.Lock, cap: int,
                   key: str, label: str) -> str:
    """The shared sticky-map core behind both bucket maps: first ``cap``
    distinct keys keep ``label`` (first-contact sticky — a series never
    splits), later ones share :data:`OVERFLOW_BUCKET` with NOTHING
    stored (both maps bound wire-controlled input; one dict entry per
    id ever seen would be an unbounded leak in exactly the components
    built to bound label cardinality). Overflow keys recompute to the
    same answer every call; only a mid-run cap raise could re-home one
    — an explicit operator action."""
    with lock:
        hit = store.get(key)
        if hit is not None:
            return hit
        if len(store) < cap:
            store[key] = label
            return label
    return OVERFLOW_BUCKET


def tenant_bucket(tenant: str) -> str:
    """The bounded metric label for ``tenant``.

    First ``DBX_TENANT_LABEL_MAX`` distinct tenants map to themselves,
    later ones to :data:`OVERFLOW_BUCKET`. This is THE sanctioned way
    to put tenant identity on a metric label (dbxlint obs-cardinality
    treats ``tenant_bucket(...)`` as bounded by construction).
    """
    t = tenant or DEFAULT_TENANT
    return _sticky_bucket(_BUCKETS, _BUCKET_LOCK, _label_max(), t, t)


def reset_tenant_buckets() -> None:
    """Drop all sticky assignments (tests; a fresh process equivalent)."""
    with _BUCKET_LOCK:
        _BUCKETS.clear()
    with _STREAM_BUCKET_LOCK:
        _STREAM_BUCKETS.clear()
    with _WORKER_BUCKET_LOCK:
        _WORKER_BUCKETS.clear()


# -- stream buckets ---------------------------------------------------------
#
# Stream keys (serve.stream_key — blake2b over strategy + grid + cost +
# ppy) are exactly as unbounded as tenant ids: one live fleet serves
# thousands of distinct param blocks, and a per-stream metric label would
# mint a permanent time series each. Same sticky core, own namespace +
# cap: the first DBX_STREAM_LABEL_MAX distinct keys keep a short
# recognizable prefix (a 32-hex digest is a terrible label; its first 12
# chars identify it in any log), later ones share ``other``.

_DEFAULT_STREAM_LABEL_MAX = 16
_STREAM_PREFIX_CHARS = 12

_STREAM_BUCKET_LOCK = threading.Lock()
_STREAM_BUCKETS: dict[str, str] = {}


def _stream_label_max() -> int:
    """Bucket cap, read lazily like :func:`_label_max`."""
    return int(os.environ.get("DBX_STREAM_LABEL_MAX",
                              _DEFAULT_STREAM_LABEL_MAX))


def stream_bucket(key: str) -> str:
    """The bounded metric label for a stream key.

    First ``DBX_STREAM_LABEL_MAX`` distinct keys map to their first 12
    hex chars, later ones to :data:`OVERFLOW_BUCKET`. This is THE
    sanctioned way to put stream identity on a metric label (dbxlint
    obs-cardinality treats ``stream_bucket(...)`` as bounded by
    construction, beside ``tenant_bucket``/``shape_bucket``).
    """
    k = key or "?"
    return _sticky_bucket(_STREAM_BUCKETS, _STREAM_BUCKET_LOCK,
                          _stream_label_max(), k,
                          k[:_STREAM_PREFIX_CHARS])


# -- worker buckets ---------------------------------------------------------
#
# Worker ids are worker-chosen wire strings (uuid-suffixed by default) and
# exactly as unbounded as tenant ids: a churning fleet registers a fresh id
# per restart, so a raw per-worker metric label would mint a permanent
# time series per registration. The fleet telemetry plane's label
# surfaces (obs/fleet.py FleetView.collect) route through this map; the
# full ids stay on the per-document JSON surfaces (/fleet.json frames),
# which are per-snapshot, not per-series.

_DEFAULT_WORKER_LABEL_MAX = 16

_WORKER_BUCKET_LOCK = threading.Lock()
_WORKER_BUCKETS: dict[str, str] = {}


def _worker_label_max() -> int:
    """Bucket cap, read lazily like :func:`_label_max`."""
    return int(os.environ.get("DBX_WORKER_LABEL_MAX",
                              _DEFAULT_WORKER_LABEL_MAX))


def worker_bucket(worker_id: str) -> str:
    """The bounded metric label for a worker id.

    First ``DBX_WORKER_LABEL_MAX`` distinct ids keep their own name
    (sticky — a worker's series never splits mid-run), later ones share
    :data:`OVERFLOW_BUCKET`. This is THE sanctioned way to put worker
    identity on a metric label (dbxlint obs-cardinality treats
    ``worker_bucket(...)`` as bounded by construction, beside
    ``tenant_bucket``/``shape_bucket``/``stream_bucket``).
    """
    w = worker_id or "?"
    return _sticky_bucket(_WORKER_BUCKETS, _WORKER_BUCKET_LOCK,
                          _worker_label_max(), w, w)
