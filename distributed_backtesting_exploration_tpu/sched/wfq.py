"""Virtual-time weighted fair queueing over per-tenant pending lanes.

The dispatcher's queue state machine (round 5: batched register / lease /
tombstone / completion transitions, native or pure-Python) stays the
authority on what a job's lifecycle state IS; this module decides WHICH
pending job is served next. Jobs are parked in per-tenant FIFO lanes (one
more per-tenant index per pop) and each pop runs start-time fair queueing
over the lane heads:

- a tenant's next job carries the virtual start tag
  ``max(F_t, V)`` where ``F_t`` is the tenant's virtual finish time and
  ``V`` the tag of the job served last;
- the lowest tag wins (ties broken by arrival sequence — deterministic,
  and single-tenant order is exactly the FIFO);
- serving a job of cost ``c`` (its combo count — the unit of backtest
  service) advances ``F_t`` by ``c / weight(t)``.

Weights come from ``DBX_TENANT_WEIGHTS`` (``"whale:4,small:1"``; ``*``
sets the default, otherwise 1.0). ``DBX_TENANT_QUOTA`` caps a tenant's
IN-FLIGHT combos (leased, not yet completed): while a tenant is at
quota its pending jobs are demoted behind every other tenant's virtual
time — skipped, not reordered within the lane, and never starved: the
discipline is work-conserving (an over-quota tenant is still served
when no one else has pending work), and leased jobs are never yanked.

NOT thread-safe on its own — every call arrives under ``JobQueue._lock``,
the same single-lock discipline the state machine itself is driven with.
"""

from __future__ import annotations

import collections
import os

from .explain import PickExplain
from .tenancy import DEFAULT_TENANT


def parse_tenant_map(spec: str) -> dict[str, float]:
    """``"whale:4,small:1,*:2"`` -> ``{"whale": 4.0, "small": 1.0,
    "*": 2.0}``. ``*`` is the default for unlisted tenants. A malformed
    entry raises ``ValueError`` — a typo'd env knob must fail the
    dispatcher loudly at construction, not silently schedule unfairly."""
    out: dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, val = part.rpartition(":")
        if not sep or not name.strip():
            raise ValueError(
                f"malformed tenant map entry {part!r} (want name:number)")
        try:
            out[name.strip()] = float(val)
        except ValueError:
            raise ValueError(
                f"malformed tenant map entry {part!r}: {val!r} is not a "
                "number") from None
    return out


class WfqScheduler:
    """Per-tenant pending lanes + the virtual-time pop (module docstring).

    ``weights``/``quotas`` default to the ``DBX_TENANT_WEIGHTS`` /
    ``DBX_TENANT_QUOTA`` env knobs, read lazily at construction (one
    scheduler per ``JobQueue``)."""

    def __init__(self, *, weights: dict[str, float] | None = None,
                 quotas: dict[str, float] | None = None):
        if weights is None:
            weights = parse_tenant_map(
                os.environ.get("DBX_TENANT_WEIGHTS", ""))
        if quotas is None:
            quotas = parse_tenant_map(
                os.environ.get("DBX_TENANT_QUOTA", ""))
        for t, w in weights.items():
            if w <= 0:
                # Same loud-failure policy as parse_tenant_map: silently
                # coercing a zero/negative weight to the default would
                # schedule the one tenant the operator meant to throttle
                # at full rate.
                raise ValueError(
                    f"tenant weight must be > 0 (got {t!r}: {w}); use a "
                    "small weight or DBX_TENANT_QUOTA to throttle")
        self._weights = weights
        self._quotas = quotas
        # tenant -> FIFO lane of (seq, jid, cost). Entries for discarded
        # (completed-while-parked) jobs are tombstoned in _gone and
        # skipped lazily at the next head read — a deque has no interior
        # removal, the same discipline as the state machine's FIFO.
        self._lanes: dict[str, collections.deque] = {}
        self._parked: dict[str, str] = {}        # jid -> tenant
        self._npend: collections.Counter = collections.Counter()
        self._gone: set[str] = set()
        self._finish: dict[str, float] = {}      # tenant -> virtual finish
        self._vtime = 0.0
        self._seq = 0          # arrival order (FIFO tie-break)
        self._front_seq = 0    # decreasing: requeued jobs sort first
        # jid -> (tenant, cost): every job charged against its tenant's
        # quota. The charge lands AT PICK TIME (under the caller's
        # lock), not at lease commit: the commit only happens after
        # take()'s unlocked payload-materialization window, and a
        # concurrent worker's pick in that window would otherwise read
        # a stale zero charge and hand an at-quota tenant another
        # batch. Every non-lease resolution (materialization failure,
        # completed-mid-take, exception re-park, requeue) releases.
        self._charged: dict[str, tuple[str, float]] = {}
        self._inflight: collections.Counter = collections.Counter()
        self._demoted: collections.Counter = collections.Counter()

    # -- config ------------------------------------------------------------

    def weight(self, tenant: str) -> float:
        return self._weights.get(tenant, self._weights.get("*", 1.0))

    def quota(self, tenant: str) -> float | None:
        return self._quotas.get(tenant, self._quotas.get("*"))

    # -- parked-lane surface (all calls under JobQueue._lock) --------------

    def push(self, jid: str, tenant: str, cost: float) -> None:
        """Park a pending job at the tail of its tenant's lane."""
        t = tenant or DEFAULT_TENANT
        self._lanes.setdefault(t, collections.deque()).append(
            (self._seq, jid, float(cost)))
        self._seq += 1
        self._parked[jid] = t
        self._npend[t] += 1

    def requeue_front(self, items: list[tuple[str, str, float]]) -> None:
        """Re-park jobs at the FRONT of their lanes, preserving ``items``
        service order (requeue-at-front: a retried job must not re-wait
        behind the whole backlog — the pre-tenancy FIFO's appendleft)."""
        for jid, tenant, cost in reversed(items):
            t = tenant or DEFAULT_TENANT
            self._front_seq -= 1
            self._lanes.setdefault(t, collections.deque()).appendleft(
                (self._front_seq, jid, float(cost)))
            self._parked[jid] = t
            self._npend[t] += 1

    def discard(self, jid: str) -> bool:
        """Drop a parked job (completed while pending). True when ``jid``
        was parked — the caller then clears the state machine's orphan
        tombstone so ``drained``/``pending`` accounting stays exact."""
        t = self._parked.pop(jid, None)
        if t is None:
            return False
        self._gone.add(jid)
        self._npend[t] -= 1
        return True

    def _live_head(self, lane: collections.deque):
        while lane and lane[0][1] in self._gone:
            self._gone.discard(lane.popleft()[1])
        return lane[0] if lane else None

    def pick(self, n: int,
             explain: list[PickExplain] | None = None) -> list[str]:
        """Pop up to ``n`` jids in virtual-time order (module docstring).
        Picked jobs are immediately charged against their tenant's quota
        (see ``_charged``) — the caller releases any that fail to
        lease.

        ``explain`` (a list, or None) is the decision plane's hook: one
        :class:`~.explain.PickExplain` is appended per served job, built
        from the very values this pop used — the record cannot disagree
        with the decision, and assembly is fully gated on the argument
        so unobserved picks pay nothing."""
        out: list[str] = []
        while len(out) < n:
            heads = []   # (tag, seq, tenant, jid, cost, over_quota)
            drained_lanes: list[str] = []
            any_over = False
            for t, lane in self._lanes.items():
                head = self._live_head(lane)
                if head is None:
                    drained_lanes.append(t)
                    continue
                seq, jid, cost = head
                q = self.quota(t)
                over = q is not None and self._inflight[t] + cost > q
                any_over = any_over or over
                heads.append((max(self._finish.get(t, 0.0), self._vtime),
                              seq, t, jid, cost, over))
            for t in drained_lanes:
                # Drop drained lanes — the head scan must stay
                # proportional to tenants with LIVE work — and, once a
                # tenant is fully idle (nothing parked, nothing leased),
                # its per-tenant bookkeeping too: tenant ids are
                # wire-controlled strings, and one entry per id ever
                # seen would be an unbounded leak (same refusal as
                # tenancy's bucket map). Discarding an idle tenant's
                # virtual finish merely re-admits it at the current
                # virtual time later — exactly what a fresh tenant id
                # would get anyway.
                del self._lanes[t]
                if not self._npend.get(t) and not self._inflight.get(t):
                    self._npend.pop(t, None)
                    self._inflight.pop(t, None)
                    self._finish.pop(t, None)
                    self._demoted.pop(t, None)
            if not heads:
                break
            in_quota = [h for h in heads if not h[5]]
            demoted_now: list[str] = []
            if in_quota and any_over:
                # The demotion event: an at-quota tenant's head was
                # pushed behind every in-quota tenant this pop.
                for h in heads:
                    if h[5]:
                        self._demoted[h[2]] += 1
                        demoted_now.append(h[2])
            # Work-conserving: quota demotes behind OTHER tenants' work,
            # it never idles the fleet when only over-quota work remains.
            tag, seq, t, jid, cost, over = min(
                in_quota or heads, key=lambda h: (h[0], h[1]))
            self._lanes[t].popleft()
            # pop-with-default: a duplicate enqueue of one id (already a
            # documented-undefined intake) must double-dispatch like the
            # pre-tenancy FIFO did, not crash the pop.
            if self._parked.pop(jid, None) is not None:
                self._npend[t] -= 1
            self._charged[jid] = (t, cost)
            self._inflight[t] += cost
            vtime_before = self._vtime
            self._finish[t] = tag + cost / self.weight(t)
            self._vtime = tag
            if explain is not None:
                explain.append(PickExplain(
                    jid=jid, tenant=t, tag=tag, vtime=vtime_before,
                    vfinish=self._finish[t], cost=cost,
                    weight=self.weight(t), over_quota=over,
                    demoted=demoted_now,
                    heads={h[2]: h[0] for h in heads}))
            out.append(jid)
        return out

    # -- quota bookkeeping -------------------------------------------------

    def on_lease(self, jid: str, tenant: str, cost: float) -> None:
        """Confirm a leased job's quota charge. Normally a no-op — the
        charge landed at pick time — but charges defensively for a jid
        this scheduler never picked (direct callers, tests)."""
        if jid in self._charged:
            return
        t = tenant or DEFAULT_TENANT
        self._charged[jid] = (t, float(cost))
        self._inflight[t] += float(cost)

    def release(self, jid: str) -> None:
        """Uncharge a leased job (completed / requeued). Idempotent —
        a late duplicate completion after a requeue already released.
        A tenant whose last charge releases while it has nothing parked
        drops ALL its per-tenant state here: the lane prune in pick()
        runs before leases land, so without this a one-shot tenant id
        would leave a zeroed entry behind forever (tenant ids are
        wire-controlled — nothing may grow per id ever seen)."""
        hit = self._charged.pop(jid, None)
        if hit is None:
            return
        t, cost = hit
        left = max(self._inflight[t] - cost, 0.0)
        if left > 0.0:
            self._inflight[t] = left
            return
        self._inflight.pop(t, None)
        if not self._npend.get(t) and not self._lanes.get(t):
            self._npend.pop(t, None)
            self._finish.pop(t, None)
            self._demoted.pop(t, None)

    # -- observability -----------------------------------------------------

    def pending(self) -> int:
        return len(self._parked)

    def tenants(self) -> list[str]:
        return sorted(set(self._npend) | set(self._inflight))

    def stats(self) -> dict[str, dict]:
        """Per-tenant scheduling state: parked backlog, in-flight combo
        charge, virtual finish time, quota-demotion count."""
        return {t: {"pending": int(self._npend.get(t, 0)),
                    "inflight_combos": float(self._inflight.get(t, 0.0)),
                    "vfinish": float(self._finish.get(t, 0.0)),
                    "demoted": int(self._demoted.get(t, 0)),
                    "weight": self.weight(t),
                    "quota": self.quota(t)}
                for t in self.tenants()}
