"""Multi-chip sweeps: device meshes, sharded data placement, SPMD execution.

The reference's only scale-out axis is job-level data parallelism across
worker *machines* over gRPC (reference ``README.md:6-7``); inside a worker its
intended thread parallelism is stubbed to a serial loop (reference
``src/worker/process.rs:21-25``). Here the intra-worker axis is a TPU slice:
a 1-D ``jax.sharding.Mesh`` over the worker's chips, the ticker axis of a
sweep sharded across it, and the parameter axis dense per chip. A sweep is
embarrassingly parallel over (ticker, param), so the SPMD program needs **no
collectives in the hot loop** — XLA compiles one program per chip and the only
cross-chip traffic is the final ``(tickers, params)`` metric gather (or an
on-device ``psum``-based argmax reduction, :func:`best_over_grid`).

Cross-*host* scale-out stays on the gRPC dispatcher contract over DCN
(``dist/``); this module is the ICI story within one worker.
"""

from __future__ import annotations

import functools
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._shardmap_compat import shard_map
from ..ops import metrics as metrics_mod
from ..parallel import sweep as sweep_mod

TICKER_AXIS = "tickers"


def make_mesh(devices=None, *, axis_name: str = TICKER_AXIS) -> Mesh:
    """1-D mesh over the worker's chips (default: all local devices).

    Backtest sweeps shard the ticker axis only, so the mesh is 1-D; the param
    axis stays dense per chip to keep each chip's XLA program a single fused
    (ticker-block x param) kernel.
    """
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def pad_tickers(n_tickers: int, n_shards: int) -> int:
    """Smallest multiple of ``n_shards`` >= ``n_tickers`` (shard-even padding)."""
    return -(-n_tickers // n_shards) * n_shards


def pad_rows(a: np.ndarray, n_pad: int) -> np.ndarray:
    """Pad a row-stacked array to ``n_pad`` rows by repeating the last row.

    THE shard-even padding discipline (used by :func:`device_put_sweep` and
    the worker's mesh dispatch): repeated rows are real, well-formed inputs
    whose outputs callers drop, so no kernel needs a validity mask."""
    a = np.asarray(a)
    n = a.shape[0]
    if n_pad == n:
        return a
    return np.concatenate([a, np.repeat(a[-1:], n_pad - n, axis=0)], axis=0)


def device_put_sweep(mesh: Mesh, ohlcv, grid: Mapping[str, jax.Array],
                     bar_mask=None):
    """Place a sweep's inputs: tickers sharded over the mesh, grid replicated.

    Pads the ticker axis (repeating the last ticker) to a multiple of the mesh
    size so every chip gets an equal block; returns
    ``(ohlcv, grid, bar_mask, n_real)`` with ``n_real`` the unpadded count —
    callers slice results back to ``[:n_real]``.
    """
    axis = mesh.axis_names[0]
    n = ohlcv.close.shape[0]
    n_pad = pad_tickers(n, mesh.devices.size)

    row = NamedSharding(mesh, P(axis, None))
    rep = NamedSharding(mesh, P())
    ohlcv = type(ohlcv)(*(jax.device_put(pad_rows(f, n_pad), row)
                          for f in ohlcv))
    grid = {k: jax.device_put(jnp.asarray(v), rep) for k, v in grid.items()}
    if bar_mask is not None:
        bar_mask = jax.device_put(pad_rows(bar_mask, n_pad), row)
    return ohlcv, grid, bar_mask, n


@functools.partial(
    jax.jit, static_argnames=("mesh", "strategy", "periods_per_year",
                              "param_chunk"))
def sharded_sweep(mesh: Mesh, ohlcv, strategy, grid, *, cost=0.0,
                  bar_mask=None, periods_per_year: int = 252,
                  param_chunk: int | None = None):
    """The multi-chip sweep: ``shard_map`` of the fused kernel over tickers.

    Each chip runs :func:`~.sweep.run_sweep` on its ticker block; outputs are
    ``(n_tickers, P)`` metrics sharded the same way, so nothing but the caller
    ever moves them. Inputs should be placed with :func:`device_put_sweep`.

    ``param_chunk`` composes the two memory valves: the mesh divides the
    ticker axis, the ``lax.map`` chunking bounds the param axis's live
    working set per chip (see :func:`~.sweep.chunked_sweep` — the bound
    survives under ``shard_map`` because ``lax.map`` is sequential).
    """
    axis = mesh.axis_names[0]
    row, rep = P(axis, None), P()
    mask_spec = rep if bar_mask is None else row

    def local(ohlcv_blk, grid_rep, mask_blk):
        if param_chunk:
            return sweep_mod.chunked_sweep(
                ohlcv_blk, strategy, grid_rep, param_chunk=param_chunk,
                cost=cost, bar_mask=mask_blk,
                periods_per_year=periods_per_year)
        return sweep_mod.run_sweep(
            ohlcv_blk, strategy, grid_rep, cost=cost, bar_mask=mask_blk,
            periods_per_year=periods_per_year)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(type(ohlcv)(*(row for _ in ohlcv)),
                  {k: rep for k in grid}, mask_spec),
        out_specs=metrics_mod.Metrics(*(row for _ in metrics_mod.Metrics._fields)),
        check_vma=False)
    return fn(ohlcv, grid, bar_mask)


@functools.partial(
    jax.jit, static_argnames=("mesh", "strategy", "metric", "periods_per_year"))
def best_over_grid(mesh: Mesh, ohlcv, strategy, grid, *, metric: str = "sharpe",
                   cost=0.0, bar_mask=None, periods_per_year: int = 252):
    """Sweep + on-device global argmax over the whole (ticker x param) grid.

    Returns ``(best_value, best_ticker_index, {param: value})`` as scalars —
    the all-reduce pattern for "find the best configuration anywhere in the
    fleet slice" without materializing the full metric matrix on the host.
    The cross-chip reduction is a single ``argmax`` over a gathered per-chip
    maximum (one scalar per chip over ICI).
    """
    axis = mesh.axis_names[0]
    row, rep = P(axis, None), P()
    mask_spec = rep if bar_mask is None else row

    sign = metrics_mod.metric_sign(metric)

    def local(ohlcv_blk, grid_rep, mask_blk):
        m = sweep_mod.run_sweep(
            ohlcv_blk, strategy, grid_rep, cost=cost, bar_mask=mask_blk,
            periods_per_year=periods_per_year)
        vals = sign * getattr(m, metric)               # (tickers/shard, P)
        flat = vals.reshape(-1)
        li = jnp.argmax(flat)
        lv = flat[li]
        # One (value, flat-index) pair per chip crosses ICI.
        all_v = jax.lax.all_gather(lv, axis)           # (n_shards,)
        all_i = jax.lax.all_gather(li, axis)           # (n_shards,)
        shard = jnp.argmax(all_v)
        best_v = all_v[shard]
        n_per = vals.shape[0]
        flat_idx = all_i[shard]
        ticker = shard * n_per + flat_idx // vals.shape[1]
        param = flat_idx % vals.shape[1]
        return best_v, ticker.astype(jnp.int32), param.astype(jnp.int32)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(type(ohlcv)(*(row for _ in ohlcv)),
                  {k: rep for k in grid}, mask_spec),
        out_specs=(rep, rep, rep), check_vma=False)
    best_v, ticker, param = fn(ohlcv, grid, bar_mask)
    chosen = {k: v[param] for k, v in grid.items()}
    return sign * best_v, ticker, chosen
