"""Portfolio-level composition of per-ticker backtests (TPU-first).

A parameter sweep answers "which params fit each ticker"; the question a
backtesting framework must answer next is portfolio-level: what do the
selected strategies earn TOGETHER — weighted, netted across the book, with
cross-sectional diagnostics — rather than per ticker in isolation. The
reference never reaches any compute (its worker slot is a sleep stub,
reference ``src/worker/process.rs:21-25``); this module is the aggregation
layer implied by its render-farm framing.

TPU-first design:

- **One jit over the panel.** Per-ticker positions come from the registered
  strategy families ``vmap``-ed over (ticker row, per-ticker param row) —
  the per-ticker parameter selection is data, not Python structure, so one
  compiled program serves any selection.
- **Aggregation is a weighted cross-sectional reduction** per bar (a single
  VPU pass over the ``(N, T)`` net-return panel), and the correlation
  diagnostic is one ``(N, T) x (T, N)`` matmul on the MXU.
- **Cross-chip portfolios ride one `psum`.** With tickers sharded over a
  mesh (`shard_map`), each chip reduces its local book and a single
  ``psum`` over the ticker axis produces the replicated portfolio series —
  the ICI collective IS the portfolio sum (see
  :func:`sharded_portfolio_returns`).

Semantics: portfolio net return per bar is ``sum_i w_i * net_i[t]`` with
``net_i`` each ticker's post-cost strategy return (``ops.pnl
.backtest_prefix``) and ``w`` normalized to sum to 1 — an additive
(non-compounding) book, matching the sweep engine's equity convention.
"""

from __future__ import annotations

import functools
from typing import Mapping

import jax
import jax.numpy as jnp

from ._shardmap_compat import shard_map
from ..models.base import Strategy
from ..ops import metrics as metrics_mod
from ..ops import pnl as pnl_mod
from . import sweep as sweep_mod

Array = jax.Array


def equal_weights(n: int) -> Array:
    """``(n,)`` weights summing to 1."""
    return jnp.full((n,), 1.0 / float(n), jnp.float32)


def _normalize_weights(weights, n: int) -> Array:
    """Normalize to unit GROSS exposure: ``w / sum(|w|)``.

    Abs-sum (not plain sum) normalization keeps long-short books sane: a
    dollar-neutral ``[1, -1]`` normalizes to ``[0.5, -0.5]`` instead of
    dividing by zero, and a net-short vector keeps its sign instead of
    silently trading inverted. For all-long weights this is the usual
    sum-to-1 normalization.
    """
    if weights is None:
        return equal_weights(n)
    w = jnp.asarray(weights, jnp.float32)
    return w / jnp.maximum(jnp.sum(jnp.abs(w)), 1e-12)


def inverse_vol_weights(close, *, eps: float = 1e-12) -> Array:
    """Full-sample inverse-volatility weights from a ``(N, T)`` close panel.

    ``w_i ∝ 1 / std(simple_returns_i)``, normalized to sum to 1. A
    risk-parity-flavored default that keeps one noisy ticker from owning
    the book; pass custom weights to :func:`portfolio_backtest` for
    anything fancier.
    """
    r = pnl_mod.simple_returns(jnp.asarray(close, jnp.float32))
    inv = 1.0 / (jnp.std(r, axis=-1) + eps)
    return inv / jnp.sum(inv)


def per_ticker_positions(ohlcv, strategy: Strategy,
                         params: Mapping[str, Array]) -> Array:
    """``(N, T)`` positions: each ticker runs ``strategy`` with ITS OWN
    scalar params (``params`` maps field name -> ``(N,)`` array)."""
    return jax.vmap(lambda o, p: strategy.positions(o, p))(
        ohlcv, dict(params))


def portfolio_returns(close, positions, *, weights=None,
                      cost: float = 0.0):
    """Aggregate an ``(N, T)`` book into one portfolio return series.

    Each ticker's post-cost net returns come from
    :func:`~..ops.pnl.backtest_prefix`; the portfolio nets them with
    ``weights`` (normalized to unit gross exposure, see
    :func:`_normalize_weights`; default equal). Returns ``(portfolio_net (T,),
    portfolio_equity (T,), net_exposure (T,))`` — net exposure is the
    weighted sum of per-ticker positions, the book's directional tilt.
    """
    close = jnp.asarray(close, jnp.float32)
    w = _normalize_weights(weights, close.shape[0])
    res = pnl_mod.backtest_prefix(close, positions, cost=cost)
    port_net = jnp.einsum("n,nt->t", w, res.returns)
    port_equity = 1.0 + jnp.cumsum(port_net, axis=-1)
    exposure = jnp.einsum("n,nt->t", w, positions)
    return port_net, port_equity, exposure


def portfolio_backtest(ohlcv, strategy: Strategy,
                       params: Mapping[str, Array], *, weights=None,
                       cost: float = 0.0,
                       periods_per_year: int = 252) -> metrics_mod.Metrics:
    """Scalar :class:`~..ops.metrics.Metrics` for the whole book.

    ``params`` maps each strategy field to an ``(N,)`` per-ticker value —
    typically the output of :func:`select_best_params`. Metrics follow the
    sweep engine's conventions; the ``positions`` feeding
    turnover/n_trades are the book's net exposure.
    """
    pos = per_ticker_positions(ohlcv, strategy, params)
    net, equity, exposure = portfolio_returns(
        ohlcv.close, pos, weights=weights, cost=cost)
    return metrics_mod.summary_metrics(
        net, equity, exposure, periods_per_year=periods_per_year)


def select_best_params(metric_values: Array, grid: Mapping[str, Array], *,
                       metric: str | None = None):
    """Per-ticker argmax over a sweep's ``(N, P)`` metric panel.

    Returns ``(best_values (N,), {field: (N,) best params})`` — the
    direction-aware, NaN-last selection (NaN cells lose to any finite
    cell, matching the worker-side top-k discipline). Delegates to
    :func:`~.sweep.best_params` — ONE selection implementation serves the
    walk-forward refits, the aggregate read path, and this book
    composition. The params dict plugs straight into
    :func:`portfolio_backtest`.
    """
    return sweep_mod.best_params(metric_values, grid, metric=metric)


@functools.partial(
    jax.jit, static_argnames=("strategy", "metric", "periods_per_year"))
def sweep_and_compose(ohlcv, strategy: Strategy, grid: Mapping[str, Array],
                      *, metric: str = "sharpe", weights=None,
                      cost: float = 0.0, periods_per_year: int = 252):
    """End to end: sweep the grid, pick per-ticker winners, price the book.

    Returns ``(portfolio_metrics, chosen_params)``. This is the one-call
    composition path — sweep (vmap over the grid), per-ticker selection,
    and portfolio aggregation all inside ONE jit (strategy/metric are
    static, mirroring ``sweep.jit_sweep``), so the intermediate ``(N, P)``
    matrices never leave the device and the whole composition costs one
    dispatch.
    """
    m = sweep_mod.run_sweep(ohlcv, strategy, grid, cost=cost,
                            periods_per_year=periods_per_year)
    _, chosen = select_best_params(getattr(m, metric), grid, metric=metric)
    pm = portfolio_backtest(ohlcv, strategy, chosen, weights=weights,
                            cost=cost, periods_per_year=periods_per_year)
    return pm, chosen


def correlation_matrix(returns, *, eps: float = 1e-12) -> Array:
    """``(N, N)`` Pearson correlation of an ``(N, T)`` return panel — one
    centered/normalized MXU matmul."""
    r = jnp.asarray(returns, jnp.float32)
    rc = r - jnp.mean(r, axis=-1, keepdims=True)
    norm = jnp.sqrt(jnp.sum(rc * rc, axis=-1, keepdims=True)) + eps
    rn = rc / norm
    return rn @ rn.T


def avg_pairwise_correlation(corr: Array) -> Array:
    """Mean off-diagonal correlation — the book's diversification scalar."""
    n = corr.shape[0]
    off = jnp.sum(corr) - jnp.trace(corr)
    return off / jnp.float32(max(n * (n - 1), 1))


def sharded_portfolio_returns(mesh, close, positions, *, weights=None,
                              cost: float = 0.0, axis: str | None = None):
    """:func:`portfolio_returns` with the ticker axis sharded over ``mesh``.

    Each chip prices its local book slice and reduces it to a weighted
    partial sum; ONE ``psum`` over the mesh axis yields the replicated
    portfolio series — cross-chip composition costs a single collective,
    not a gather of ``(N, T)`` panels. ``N`` must divide evenly by the mesh
    size (pad with zero-weight tickers otherwise). Returns the same
    ``(net, equity, exposure)`` triple, replicated on every chip.
    """
    from jax.sharding import PartitionSpec as P

    close = jnp.asarray(close, jnp.float32)
    n = close.shape[0]
    ax = axis or mesh.axis_names[0]
    n_dev = mesh.shape[ax]
    if n % n_dev:
        raise ValueError(
            f"N={n} tickers not divisible by the {n_dev}-way {ax!r} axis; "
            "pad the book with zero-weight tickers")
    w = _normalize_weights(weights, n)

    def local(close_blk, pos_blk, w_blk):
        res = pnl_mod.backtest_prefix(close_blk, pos_blk, cost=cost)
        part_net = jnp.einsum("n,nt->t", w_blk, res.returns)
        part_exp = jnp.einsum("n,nt->t", w_blk, pos_blk)
        net = jax.lax.psum(part_net, ax)
        exposure = jax.lax.psum(part_exp, ax)
        return net, 1.0 + jnp.cumsum(net, axis=-1), exposure

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(ax, None), P(ax, None), P(ax)),
        out_specs=(P(), P(), P()),
    )(close, positions, w)
