"""Walk-forward optimization (``BASELINE.json`` configs[4]).

Classic out-of-sample protocol: slide a (train, test) window over the bar
history; per window, evaluate the full parameter grid on the train span, pick
the best parameter per ticker, then realize that parameter's returns on the
held-out test span. The TPU shape of this is ``lax.scan`` over refit windows
(sequential by construction — window w+1's start depends only on the
schedule, but scanning keeps one compiled program) with the full
(ticker x param) ``vmap`` sweep *nested inside* each step — SURVEY.md §7's
"lax.scan over refit windows + nested vmap".

All shapes are static: every window is ``train + test`` bars long, sliced
with ``lax.dynamic_slice`` at traced offsets; train/test membership is a
mask, not a shape.
"""

from __future__ import annotations

import functools
from typing import Mapping, NamedTuple

import jax
import jax.numpy as jnp

from ..models.base import Strategy
from ..ops import metrics as metrics_mod
from ..ops import pnl as pnl_mod

Array = jax.Array


class WalkForwardResult(NamedTuple):
    """Outputs of a walk-forward run.

    Attributes:
        oos_returns: ``(n_tickers, n_windows * test)`` stitched out-of-sample
            net returns under the per-window chosen params, including the
            rebalance cost at window boundaries.
        oos_positions: ``(n_tickers, n_windows * test)`` stitched positions.
        oos_metrics: :class:`~..ops.metrics.Metrics` over the stitched series,
            each field ``(n_tickers,)`` — the honest performance estimate.
        chosen: dict param name -> ``(n_tickers, n_windows)`` selected values.
        train_metric: ``(n_tickers, n_windows)`` best in-sample metric value.
    """

    oos_returns: Array
    oos_positions: Array
    oos_metrics: metrics_mod.Metrics
    chosen: Mapping[str, Array]
    train_metric: Array


def window_starts_np(T: int, train: int, test: int):
    """Anchored-walk schedule, host-side numpy: windows advance by
    ``test`` bars. Number of windows is ``(T - train) // test`` — every
    test bar is covered at most once, and only bars with a full train
    span behind them are used. The ONE schedule definition: the generic
    scan and the fused two-phase route both derive from here (the fused
    route needs host values — a jnp array would be a tracer inside the
    worker's shard_map body)."""
    import numpy as np

    n = (T - train) // test
    if n <= 0:
        raise ValueError(f"history T={T} too short for train={train} test={test}")
    return np.arange(n) * test


def window_starts(T: int, train: int, test: int) -> jnp.ndarray:
    """:func:`window_starts_np` as a jnp array (the scan-carry form)."""
    return jnp.asarray(window_starts_np(T, train, test))


@functools.partial(
    jax.jit,
    static_argnames=("strategy", "train", "test", "metric", "periods_per_year"))
def walk_forward(
    ohlcv,
    strategy: Strategy,
    grid: Mapping[str, Array],
    *,
    train: int,
    test: int,
    metric: str = "sharpe",
    cost: float = 0.0,
    periods_per_year: int = 252,
) -> WalkForwardResult:
    """Run walk-forward optimization over a ``(n_tickers, T)`` OHLCV panel.

    Per window (scanned): slice ``train + test`` bars, sweep the grid with
    metrics masked to the train span, argmax per ticker, re-price the winning
    param with returns masked to the test span. The per-window sweep reuses
    the same fused (ticker x param) kernel as :func:`~.sweep.run_sweep`.
    """
    T = ohlcv.close.shape[-1]
    starts = window_starts(T, train, test)
    n_tickers = ohlcv.close.shape[0]
    span = train + test
    sign = metrics_mod.metric_sign(metric)

    def slice_win(a, s0):
        return jax.lax.dynamic_slice_in_dim(a, s0, span, axis=-1)

    def one_window(carry, s0):
        win = type(ohlcv)(*(slice_win(f, s0) for f in ohlcv))

        def per_param(ohlcv_1, params):
            pos = strategy.positions(ohlcv_1, params)
            res = pnl_mod.backtest_prefix(ohlcv_1.close, pos, cost=cost)
            # Positions at bar t use only bars <= t, so the full-window series
            # sliced to [:train] is identical to a train-only run — the train
            # metric sees *statically* train-span returns/equity/positions
            # (no test-span leakage for equity-based metrics either).
            train_m = getattr(metrics_mod.summary_metrics(
                res.returns[..., :train], res.equity[..., :train],
                res.positions[..., :train],
                periods_per_year=periods_per_year), metric)
            return (train_m, res.returns[..., train:],
                    res.positions[..., train:], res.positions[..., train - 1])

        def per_ticker(ohlcv_1):
            train_m, rets, poss, prevs = jax.vmap(
                lambda p: per_param(ohlcv_1, p))(dict(grid))  # (P,),(P,test)..
            best = jnp.argmax(sign * train_m)
            return train_m[best], best, rets[best], poss[best], prevs[best]

        best_val, best_idx, oos_r, oos_p, prev_in = jax.vmap(per_ticker)(win)
        rf = win.close[:, train] / win.close[:, train - 1] - 1.0
        return carry, (best_val, best_idx, oos_r, oos_p, prev_in, rf)

    _, (train_best, best_idx, oos_r, oos_p, prev_in, rf) = jax.lax.scan(
        one_window, 0, starts)
    # scan outputs are window-major: (n_windows, n_tickers, ...)
    chosen = {k: jnp.moveaxis(jnp.take(v, best_idx), 0, 1)
              for k, v in grid.items()}
    return _stitch(oos_r, oos_p, prev_in, rf, train_best, chosen,
                   n_tickers=n_tickers, cost=cost,
                   periods_per_year=periods_per_year)


@functools.partial(
    jax.jit,
    static_argnames=("train", "test", "metric", "periods_per_year"))
def walk_forward_pairs(
    y_close,
    x_close,
    grid: Mapping[str, Array],
    *,
    train: int,
    test: int,
    metric: str = "sharpe",
    cost: float = 0.0,
    periods_per_year: int = 252,
) -> WalkForwardResult:
    """Walk-forward optimization for the two-legged pairs strategy.

    Same protocol and scan structure as :func:`walk_forward` over
    ``(n_pairs, T)`` leg panels: per refit window, sweep the
    (lookback, z_entry[, z_exit]) grid on the train span (rolling OLS +
    z-score + band machine recomputed *within* the window — positions at
    bar t use only bars <= t, so span-slice train metrics equal a
    train-only run), argmax per pair, realize the winner on the test span.
    The stitched boundary fix-up replaces the single-asset underlying
    return with the window's *hedged* spread return factor at its first
    OOS bar — i.e. the deployed sequence re-hedges each window with the
    incoming window's chosen beta (positions carry over in spread units;
    ``models.pairs.pair_backtest`` cost semantics throughout).
    """
    from ..models import pairs as pairs_mod

    T = y_close.shape[-1]
    starts = window_starts(T, train, test)
    n_pairs = y_close.shape[0]
    span = train + test
    sign = metrics_mod.metric_sign(metric)

    def slice_win(a, s0):
        return jax.lax.dynamic_slice_in_dim(a, s0, span, axis=-1)

    def one_window(carry, s0):
        ywin = slice_win(y_close, s0)
        xwin = slice_win(x_close, s0)

        def per_param(y1, x1, params):
            # The one semantics-defining PnL (shared with run_pairs_sweep
            # via pair_backtest), so train metrics cannot drift from the
            # sweep's.
            pos, net, hr = pairs_mod.pair_net_returns(y1, x1, params,
                                                      cost=cost)
            equity_tr = 1.0 + jnp.cumsum(net[..., :train], axis=-1)
            train_m = getattr(metrics_mod.summary_metrics(
                net[..., :train], equity_tr, pos[..., :train],
                periods_per_year=periods_per_year), metric)
            return (train_m, net[..., train:], pos[..., train:],
                    pos[..., train - 1], hr[..., train])

        def per_pair(y1, x1):
            train_m, rets, poss, prevs, hrf = jax.vmap(
                lambda p: per_param(y1, x1, p))(dict(grid))
            best = jnp.argmax(sign * train_m)
            return (train_m[best], best, rets[best], poss[best],
                    prevs[best], hrf[best])

        best_val, best_idx, oos_r, oos_p, prev_in, hrf = jax.vmap(
            per_pair)(ywin, xwin)
        return carry, (best_val, best_idx, oos_r, oos_p, prev_in, hrf)

    _, (train_best, best_idx, oos_r, oos_p, prev_in, hrf) = jax.lax.scan(
        one_window, 0, starts)
    chosen = {k: jnp.moveaxis(jnp.take(v, best_idx), 0, 1)
              for k, v in grid.items()}
    return _stitch(oos_r, oos_p, prev_in, hrf, train_best, chosen,
                   n_tickers=n_pairs, cost=cost,
                   periods_per_year=periods_per_year)


def _stitch(oos_r, oos_p, prev_in, rf, train_best, chosen, *, n_tickers,
            cost, periods_per_year) -> WalkForwardResult:
    """Window-major per-window outputs -> stitched WalkForwardResult.

    Boundary fix-up: each window's first OOS bar was priced by
    backtest_prefix against that window's own train-span position at
    ``train-1`` (``prev_in``): it earned ``prev_in * r`` and paid turnover
    ``|pos - prev_in|``. A sequential deployment instead carries the
    *previous window's* final OOS position into that bar (window w's last
    test bar is the bar before window w+1's first one) — and starts flat at
    window 0. Swap both the earnings and the cost terms so the stitched
    series prices exactly the positions it reports.
    """
    first_pos = oos_p[:, :, 0]                                # (W, n_tickers)
    prev_deployed = jnp.concatenate(
        [jnp.zeros_like(first_pos[:1]), oos_p[:-1, :, -1]], axis=0)
    c = jnp.asarray(cost, oos_r.dtype)
    adj = (prev_deployed - prev_in) * rf - c * (
        jnp.abs(first_pos - prev_deployed) - jnp.abs(first_pos - prev_in))
    oos_r = oos_r.at[:, :, 0].add(adj)

    oos_returns = jnp.moveaxis(oos_r, 0, 1).reshape(n_tickers, -1)
    oos_positions = jnp.moveaxis(oos_p, 0, 1).reshape(n_tickers, -1)
    equity = 1.0 + jnp.cumsum(oos_returns, axis=-1)
    oos_metrics = metrics_mod.summary_metrics(
        oos_returns, equity, oos_positions,
        periods_per_year=periods_per_year)
    return WalkForwardResult(
        oos_returns=oos_returns,
        oos_positions=oos_positions,
        oos_metrics=oos_metrics,
        chosen=chosen,
        train_metric=jnp.moveaxis(train_best, 0, 1),
    )


@functools.partial(jax.jit, static_argnames=("starts", "train"))
def _stack_train_windows(close, starts: tuple, train: int):
    """All windows' train slices as one ``(W * n_tickers, train)`` panel."""
    rows = [jax.lax.dynamic_slice_in_dim(close, s0, train, axis=-1)
            for s0 in starts]
    stacked = jnp.stack(rows)                            # (W, N, train)
    return stacked.reshape(-1, train)


@functools.partial(jax.jit, static_argnames=("W", "n_tickers"))
def _window_argmax(vals, sign, W: int, n_tickers: int):
    """(W*N, P) metric values -> per-(window, ticker) argmax index + value."""
    v = vals.reshape(W, n_tickers, -1)
    idx = jnp.argmax(sign * v, axis=-1)
    best = jnp.take_along_axis(v, idx[..., None], -1)[..., 0]
    return idx, best


@functools.partial(
    jax.jit,
    static_argnames=("strategy", "train", "test", "periods_per_year"))
def _reprice_chosen(ohlcv, strategy: Strategy, chosen_per_window, starts, *,
                    train: int, test: int, cost=0.0,
                    periods_per_year: int = 252):
    """Phase 2 of the fused walk-forward: re-price each ticker's CHOSEN
    param per window (P=1 per ticker — the cheap part)."""
    span = train + test

    def slice_win(a, s0):
        return jax.lax.dynamic_slice_in_dim(a, s0, span, axis=-1)

    def one_window(carry, inp):
        s0, params_n = inp
        win = type(ohlcv)(*(slice_win(f, s0) for f in ohlcv))

        def per_ticker(ohlcv_1, p1):
            pos = strategy.positions(ohlcv_1, p1)
            res = pnl_mod.backtest_prefix(ohlcv_1.close, pos, cost=cost)
            return (res.returns[..., train:], res.positions[..., train:],
                    res.positions[..., train - 1])

        oos_r, oos_p, prev_in = jax.vmap(per_ticker)(win, params_n)
        rf = win.close[:, train] / win.close[:, train - 1] - 1.0
        return carry, (oos_r, oos_p, prev_in, rf)

    _, outs = jax.lax.scan(one_window, 0, (starts, chosen_per_window))
    return outs


def walk_forward_fused(
    ohlcv,
    strategy: Strategy,
    grid: Mapping[str, Array],
    train_metrics_fn,
    *,
    train: int,
    test: int,
    metric: str = "sharpe",
    cost: float = 0.0,
    periods_per_year: int = 252,
    fields: tuple = ("close",),
) -> WalkForwardResult:
    """Walk-forward with the TRAIN sweep on a fused Pallas kernel.

    The expensive phase — the full (ticker x param) grid per refit window —
    runs as ``train_metrics_fn(*field_slices) -> Metrics`` (e.g. a
    ``functools.partial`` of :func:`~..ops.fused.fused_sma_sweep` with the
    flat grid arrays bound); ``fields`` names the OHLCV columns the kernel
    consumes, in its positional order (``("close",)`` for the single-series
    families, ``("close", "high", "low")`` for the channel families, …).
    Only each ticker's argmax-chosen param is then re-priced over the
    (train+test) span, and the stitched result uses the same boundary
    fix-up as :func:`walk_forward`. Results match :func:`walk_forward`
    exactly wherever the fused and generic train metrics agree on the
    argmax (knife-edge metric ties can flip a chosen param — the caveat
    class ``bench.py --verify`` quantifies).
    """
    import numpy as np

    T = ohlcv.close.shape[-1]
    starts_np = window_starts_np(T, train, test)
    n_tickers = ohlcv.close.shape[0]
    W = len(starts_np)
    sign = metrics_mod.metric_sign(metric)

    # Phase 1: ONE fused train sweep over all windows at once — the W
    # train slices (of every field the kernel consumes) stack into
    # (W * n_tickers, train) panels so the whole phase is a single kernel
    # launch (a per-window python loop was ~5x slower end to end on a
    # remote-proxy chip: every eager slice/argmax op pays a dispatch
    # round trip).
    starts_tup = tuple(int(s) for s in starts_np)
    stacked = [_stack_train_windows(getattr(ohlcv, f), starts_tup, train)
               for f in fields]
    m = train_metrics_fn(*stacked)                       # (W*N, P) fields
    best_idx, train_best = _window_argmax(
        getattr(m, metric), sign, W, n_tickers)          # (W, N) each

    chosen_per_window = {k: jnp.take(jnp.asarray(v), best_idx)
                         for k, v in grid.items()}       # (W, n_tickers)
    oos_r, oos_p, prev_in, rf = _reprice_chosen(
        ohlcv, strategy, chosen_per_window, jnp.asarray(starts_np),
        train=train, test=test, cost=cost,
        periods_per_year=periods_per_year)
    chosen = {k: jnp.moveaxis(v, 0, 1)
              for k, v in chosen_per_window.items()}
    return _stitch(oos_r, oos_p, prev_in, rf, train_best, chosen,
                   n_tickers=n_tickers, cost=cost,
                   periods_per_year=periods_per_year)
