"""``shard_map`` across jax generations — one call site contract.

``jax.shard_map`` (with its ``check_vma`` flag) only exists on newer jax;
older releases ship it as ``jax.experimental.shard_map.shard_map`` with
the same flag named ``check_rep``. Every sharded program in this repo
goes through this wrapper so the call sites are written once against the
new spelling and still run on the older runtime (the container this repo
is verified in has shipped both generations). jax is imported lazily so
control-plane modules that import compute code keep their no-jax-until-
needed discipline.
"""

from __future__ import annotations


def axis_size(axis_name):
    """``jax.lax.axis_size`` if available, else the legacy axis-env query.

    Must return a STATIC Python int (callers build python-level fold
    loops and ppermute patterns from it); ``psum(1, axis)`` would trace.
    On the older runtime ``jax.core.axis_frame(name)`` resolves the bound
    axis to its concrete size."""
    import jax

    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return int(jax.core.axis_frame(axis_name))


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` if available, else the experimental spelling
    (``check_vma`` transparently mapped to legacy ``check_rep``)."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as legacy_shard_map

    kw = {} if check_vma is None else {"check_rep": check_vma}
    return legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, **kw)
