"""The sweep engine: one fused jit+vmap kernel over a (ticker x param) grid.

This is the unit of compute a worker runs per job — the TPU replacement for
the reference's serial sleep loop over a job batch (reference
``src/worker/process.rs:21-25``, 1 job/sec/worker). One call evaluates every
(ticker, parameter-set) combination in the job as a single XLA program:
indicators, positions, PnL, and the metric reductions all fuse, and only the
``(n_tickers, n_params)`` scalar metrics come back to the host.

Axis order: tickers outer, params inner — so sharding the leading ticker axis
across chips (``parallel.sharding``) leaves the param axis dense per-chip.
"""

from __future__ import annotations

import functools
from typing import Mapping

import jax
import jax.numpy as jnp

from ..models.base import Strategy
from ..ops import metrics as metrics_mod
from ..ops import pnl as pnl_mod

Array = jax.Array


def grid_size(grid: Mapping[str, Array]) -> int:
    (leaf,) = set(int(v.shape[0]) for v in grid.values())
    return leaf


def product_grid(**axes) -> dict:
    """Cartesian product of named 1-D parameter axes -> dict of flat (P,) arrays.

    ``product_grid(fast=[5,10], slow=[50,100])`` yields 4 combos. Axes are
    materialized with ``meshgrid`` so the flat order is row-major in the
    argument order.
    """
    names = list(axes)
    arrs = [jnp.asarray(axes[n]) for n in names]
    mesh = jnp.meshgrid(*arrs, indexing="ij")
    return {n: m.reshape(-1) for n, m in zip(names, mesh)}


def run_sweep(
    ohlcv,
    strategy: Strategy,
    grid: Mapping[str, Array],
    *,
    cost: float = 0.0,
    bar_mask: Array | None = None,
    periods_per_year: int = 252,
) -> metrics_mod.Metrics:
    """Evaluate ``strategy`` on every (ticker, param) combo.

    Args:
        ohlcv: OHLCV pytree with fields shaped ``(n_tickers, T)``.
        strategy: a registered :class:`~..models.base.Strategy`.
        grid: dict of ``(P,)`` parameter arrays (see :func:`product_grid`).
        cost: proportional transaction cost per unit turnover.
        bar_mask: optional ``(n_tickers, T)`` validity mask for ragged
            histories. MUST be a contiguous prefix-of-True / suffix-of-False
            mask as produced by :func:`~..utils.data.pad_and_stack` (padding
            repeats each ticker's final bar). Padded bars hold the last
            valid position — earning zero return and zero turnover — and
            are excluded from metric moments. It is NOT a general
            interior-bar exclusion mechanism: a mask with False before True
            would hold positions over bars with real price moves.

    Returns:
        :class:`~..ops.metrics.Metrics` with every field ``(n_tickers, P)``.
    """

    def per_param(ohlcv_1, mask_1, params):
        pos = strategy.positions(ohlcv_1, params)
        if mask_1 is not None:
            # Padding is a suffix (pad_and_stack): HOLD the last valid
            # position through padded bars instead of zeroing it. Padded
            # closes repeat the final bar, so held bars earn exactly zero
            # return and zero turnover — zeroing instead would charge a
            # phantom exit trade whenever the final position is open,
            # skewing total_return/turnover/n_trades vs the unpadded series.
            last_idx = jnp.maximum(
                jnp.sum(mask_1.astype(jnp.int32), axis=-1) - 1, 0)
            pos_last = jnp.take(pos, last_idx, axis=-1)
            pos = jnp.where(mask_1, pos, pos_last)
        res = pnl_mod.backtest_prefix(ohlcv_1.close, pos, cost=cost)
        return metrics_mod.summary_metrics(
            res.returns, res.equity, res.positions,
            periods_per_year=periods_per_year, mask=mask_1)

    def per_ticker(ohlcv_1, mask_1):
        return jax.vmap(lambda p: per_param(ohlcv_1, mask_1, p))(dict(grid))

    if bar_mask is None:
        return jax.vmap(lambda o: per_ticker(o, None))(ohlcv)
    return jax.vmap(per_ticker)(ohlcv, bar_mask)


@functools.partial(jax.jit, static_argnames=("strategy", "periods_per_year"))
def jit_sweep(ohlcv, strategy, grid, *, cost=0.0, bar_mask=None,
              periods_per_year=252):
    """``run_sweep`` under ``jit`` (strategy is a static argument)."""
    return run_sweep(ohlcv, strategy, grid, cost=cost, bar_mask=bar_mask,
                     periods_per_year=periods_per_year)


def map_param_chunks(grid: Mapping[str, Array], param_chunk: int, one_chunk):
    """Memory-bounding pattern: ``lax.map`` a sweep over param-axis chunks.

    ``one_chunk(sub_grid)`` evaluates a ``(param_chunk,)``-sized grid and
    returns :class:`~..ops.metrics.Metrics` with ``(..., param_chunk)``
    fields; the chunk results are reassembled into ``(..., P)`` fields in the
    original flat-grid order. ``P`` must be divisible by ``param_chunk``.
    Shared by the single-asset and pairs chunked sweeps so the
    chunk/map/reassemble machinery cannot diverge.
    """
    P = grid_size(grid)
    if P % param_chunk:
        raise ValueError(f"grid size {P} not divisible by chunk {param_chunk}")
    chunked = {k: jnp.reshape(v, (P // param_chunk, param_chunk))
               for k, v in grid.items()}
    out = jax.lax.map(one_chunk, chunked)   # fields: (n_chunks, ..., chunk)
    return metrics_mod.Metrics(*(
        jnp.reshape(jnp.moveaxis(f, 0, 1), (f.shape[1], P)) for f in out))


@functools.partial(
    jax.jit, static_argnames=("strategy", "param_chunk", "periods_per_year"))
def chunked_sweep(ohlcv, strategy, grid, *, param_chunk: int, cost=0.0,
                  bar_mask=None, periods_per_year=252):
    """Memory-bounded sweep: ``lax.map`` over param chunks of a vmapped kernel.

    A fully-vmapped sweep materializes ``(tickers, P, T)`` intermediates —
    ~``tickers*P*T*4`` bytes per live tensor, which blows past HBM once
    ``tickers*P`` reaches the millions the north star calls for. Chunking the
    param axis bounds live memory to the chunk's working set while the
    sequential ``lax.map`` keeps one compiled program; per-chunk compute stays
    a fused (ticker x chunk) kernel big enough to saturate the VPU.

    ``P`` must be divisible by ``param_chunk``.
    """

    def one_chunk(g):
        return run_sweep(ohlcv, strategy, g, cost=cost, bar_mask=bar_mask,
                         periods_per_year=periods_per_year)

    return map_param_chunks(grid, param_chunk, one_chunk)


def best_params(metric_values: Array, grid: Mapping[str, Array], *, axis=-1,
                metric: str | None = None, return_index: bool = False):
    """Select the best point of a ``(..., P)`` metric over the param axis.

    Returns ``(best_value, {name: best_param})`` with the leading shape of
    ``metric_values`` minus the param axis — plus the flat-grid argmax
    indices as a third element when ``return_index`` is true. Used by
    walk-forward refits, the worker's best-returns (DBXP) path, and
    dispatcher-side result aggregation. Pass ``metric`` (the
    :class:`~..ops.metrics.Metrics` field name) so lower-is-better metrics
    (max_drawdown, volatility, turnover) select the minimum.

    This is THE selection implementation: every path that picks a winning
    combo routes through here so the NaN/direction discipline cannot drift
    between the worker, walk-forward, and portfolio surfaces.

    NaN cells rank LAST (``jnp.argmax`` alone would rank them first —
    NaN wins float comparisons), matching the worker-side top-k and
    aggregate-side disciplines; an all-NaN row still returns a NaN best.
    """
    sign = metrics_mod.metric_sign(metric) if metric is not None else 1.0
    score = jnp.where(jnp.isnan(metric_values), -jnp.inf,
                      sign * metric_values)
    idx = jnp.argmax(score, axis=axis)
    best = jnp.take_along_axis(
        metric_values, jnp.expand_dims(idx, axis), axis=axis).squeeze(axis)
    chosen = {n: jnp.take(v, idx) for n, v in grid.items()}
    if return_index:
        return best, chosen, idx
    return best, chosen
