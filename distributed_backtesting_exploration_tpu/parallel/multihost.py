"""Multi-host scale-out: jax.distributed bring-up + host-sharded sweeps.

Two independent layers scale this framework beyond one host, mirroring how
the reference scales only by adding worker machines (reference
``README.md:6-7``):

1. **Job-level (the default).** Each host runs an independent worker process
   against the dispatcher (``rpc/``); no JAX-level coordination is needed, no
   collective ever crosses DCN, and hosts can join/leave freely — this is
   the reference's elasticity model and remains the recommended deployment.
2. **Slice-level (one logical JAX program over a multi-host slice).** When a
   single sweep must span more chips than one host owns, initialize
   ``jax.distributed`` (this module) and use the same
   :mod:`~.sharding` mesh helpers — ``jax.devices()`` then spans the slice,
   the ticker axis shards globally, and XLA routes the (tiny) cross-chip
   collectives over ICI within the slice. The code path is identical to the
   single-host mesh; only initialization differs.

No multi-host hardware is present in CI, so :func:`initialize` is exercised
by its single-process no-op path; the mesh math it feeds is covered by the
8-virtual-device tests (``tests/test_sharding.py``).
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("dbx.multihost")


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> int:
    """Bring up jax.distributed for a multi-host slice; returns process count.

    With no arguments and no cluster environment this is a safe no-op
    (single-process). On TPU pods the three parameters are auto-detected from
    the environment; pass them explicitly for manual bring-up:

        initialize("host0:8476", num_processes=4, process_id=int(os.environ["ID"]))

    Call before any other JAX API. Idempotent per process.
    """
    import jax

    single = (coordinator_address is None and num_processes is None
              and process_id is None
              and not os.environ.get("COORDINATOR_ADDRESS")
              and not os.environ.get("TPU_WORKER_HOSTNAMES", "").count(","))
    if single:
        log.info("multihost: single-process mode (no coordinator configured)")
        return 1
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    n = jax.process_count()
    log.info("multihost: process %d/%d, %d local / %d global devices",
             jax.process_index(), n,
             jax.local_device_count(), jax.device_count())
    return n


def host_shard(n_items: int) -> slice:
    """This host's contiguous shard of a length-``n_items`` work list.

    For dispatcher-less multi-host runs (e.g. a pod job reading a shared
    ticker universe): every host computes the same deterministic split and
    takes its slice, the multi-host analogue of the dispatcher's take-n
    batching.
    """
    import jax

    pid, n = jax.process_index(), jax.process_count()
    per = -(-n_items // n)
    return slice(pid * per, min((pid + 1) * per, n_items))
