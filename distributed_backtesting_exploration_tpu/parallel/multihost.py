"""Multi-host scale-out: jax.distributed bring-up + host-sharded sweeps.

Two independent layers scale this framework beyond one host, mirroring how
the reference scales only by adding worker machines (reference
``README.md:6-7``):

1. **Job-level (the default).** Each host runs an independent worker process
   against the dispatcher (``rpc/``); no JAX-level coordination is needed, no
   collective ever crosses DCN, and hosts can join/leave freely — this is
   the reference's elasticity model and remains the recommended deployment.
2. **Slice-level (one logical JAX program over a multi-host slice).** When a
   single sweep must span more chips than one host owns, initialize
   ``jax.distributed`` (this module) and use the same
   :mod:`~.sharding` mesh helpers — ``jax.devices()`` then spans the slice,
   the ticker axis shards globally, and XLA routes the (tiny) cross-chip
   collectives over ICI within the slice. The code path is identical to the
   single-host mesh; only initialization differs.

The distributed path runs under test without multi-host hardware: two OS
processes with 4 virtual CPU devices each form one 8-device slice through a
loopback coordinator and run a ticker-sharded sweep over the global mesh
(``tests/test_multihost.py``); the mesh math is additionally covered by the
single-process 8-virtual-device tests (``tests/test_sharding.py``).
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("dbx.multihost")


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> int:
    """Bring up jax.distributed for a multi-host slice; returns process count.

    With no arguments and no cluster environment this is a safe no-op
    (single-process). On TPU pods the three parameters are auto-detected from
    the environment; pass them explicitly for manual bring-up:

        initialize("host0:8476", num_processes=4, process_id=int(os.environ["ID"]))

    Call before any other JAX API. Idempotent per process.
    """
    import jax

    single = (coordinator_address is None and num_processes is None
              and process_id is None
              and not os.environ.get("COORDINATOR_ADDRESS")
              and not os.environ.get("TPU_WORKER_HOSTNAMES", "").count(","))
    if single:
        log.info("multihost: single-process mode (no coordinator configured)")
        return 1
    platforms = str(getattr(jax.config, "jax_platforms", "") or "")
    if not platforms or "cpu" in platforms.split(","):
        # Multi-process CPU slices need a cross-process collectives backend;
        # without gloo the cpu client ignores the distributed runtime and
        # reports a single-process world (process_count() == 1) even though
        # the coordination handshake succeeded. Harmless when another
        # platform wins backend selection — the setting only affects the
        # cpu client.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    n = jax.process_count()
    if num_processes is not None and n != num_processes:
        # Never degrade silently: a backend that ignored the distributed
        # runtime would make every host redo the full work list and split
        # the "global" mesh into disjoint per-host worlds.
        raise RuntimeError(
            f"multihost: coordination handshake succeeded but the "
            f"{jax.default_backend()!r} backend reports "
            f"process_count()={n}, expected {num_processes}. For CPU "
            f"slices this usually means cross-process collectives are "
            f"unavailable (gloo).")
    log.info("multihost: process %d/%d, %d local / %d global devices",
             jax.process_index(), n,
             jax.local_device_count(), jax.device_count())
    return n


def host_shard(n_items: int) -> slice:
    """This host's contiguous shard of a length-``n_items`` work list.

    For dispatcher-less multi-host runs (e.g. a pod job reading a shared
    ticker universe): every host computes the same deterministic split and
    takes its slice, the multi-host analogue of the dispatcher's take-n
    batching.
    """
    import jax

    pid, n = jax.process_index(), jax.process_count()
    per = -(-n_items // n)
    return slice(pid * per, min((pid + 1) * per, n_items))
