"""Time-axis (sequence) parallelism: blockwise scan with ICI carry handoff.

The long-context axis of a backtest is bar time. Indicators are prefix-sum
algebra and the PnL/hysteresis machines are first-order recurrences — the
domain analogue of sequence parallelism is therefore not ring *attention*
(there is no all-pairs interaction) but a **blockwise scan**: shard the time
axis across chips, run the local recurrence per block, then fix up each
block with the carry from the chips to its left. Two primitives cover every
kernel in this framework:

- :func:`sharded_cumsum` — distributed inclusive prefix sum. Local cumsum,
  then one ``psum``-style exclusive scan of per-block totals over ICI
  (implemented with ``all_gather`` of one scalar-per-chip + a masked sum;
  O(T/n) compute, O(n) tiny collective). Rolling sum/mean/var/OLS are all
  cumsum differences, so this makes every indicator time-shardable.
- :func:`sharded_linear_scan` — distributed first-order linear recurrence
  ``y[t] = a[t] * y[t-1] + b[t]`` (EMA, decayed state). Local associative
  scan per block, then a log(n)-step ``ppermute`` ladder combines block
  summaries across chips, and a final local fixup applies each block's
  incoming carry. Exact same math as the single-device
  ``lax.associative_scan`` — verified bit-for-bit in tests.

The general hysteresis machine (``backtest_scan``) is *not* associative, so
it cannot be time-sharded exactly; long histories there use
:func:`chunked_scan` (sequential over chunks, carry threaded on one chip)
which bounds peak memory instead. This mirrors SURVEY.md §5's call: blockwise
scan with carried state, not attention-style ring exchange.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

TIME_AXIS = "time"


def _exclusive_block_offset(block_total, axis: str):
    """Sum of ``block_total`` over all chips strictly left of this one.

    ``all_gather`` of one value per chip + masked sum — O(n_chips) scalars
    over ICI, no host round-trip.
    """
    idx = jax.lax.axis_index(axis)
    totals = jax.lax.all_gather(block_total, axis)          # (n, ...)
    n = totals.shape[0]
    mask = (jnp.arange(n) < idx).astype(totals.dtype)
    mask = mask.reshape((n,) + (1,) * (totals.ndim - 1))
    return jnp.sum(totals * mask, axis=0)


def sharded_cumsum(mesh: Mesh, x, *, axis_name: str = TIME_AXIS):
    """Inclusive cumsum along a time axis sharded over ``mesh``.

    ``x`` is ``(..., T)`` with T sharded; result has the same sharding.
    """
    spec = P(*((None,) * (x.ndim - 1) + (axis_name,)))

    def local(x_blk):
        cs = jnp.cumsum(x_blk, axis=-1)
        offset = _exclusive_block_offset(cs[..., -1], axis_name)
        return cs + offset[..., None]

    return jax.shard_map(local, mesh=mesh, in_specs=spec, out_specs=spec,
                         check_vma=False)(x)


def sharded_linear_scan(mesh: Mesh, a, b, *, axis_name: str = TIME_AXIS):
    """Distributed ``y[t] = a[t]*y[t-1] + b[t]`` (y[-1] = 0), T sharded.

    Per block, the composition of all steps is itself a first-order map
    ``y_out = A*y_in + B`` with ``A = prod(a)``, ``B`` = the local scan's
    last element. The cross-chip combine gathers one (A, B) pair per chip and
    left-folds the pairs for blocks to this chip's left; each block then
    applies its incoming carry locally: ``y = scan_local + prefix_a * carry_in``
    where ``prefix_a[t] = prod(a[block_start..t])``. At backtest scale the
    n-chip fold of scalars is cheaper than a log-depth ``ppermute`` ladder
    and exact for any mesh size.
    """
    spec = P(*((None,) * (a.ndim - 1) + (axis_name,)))

    def combine(left, right):
        a1, b1 = left
        a2, b2 = right
        return a1 * a2, a2 * b1 + b2

    def local_simple(a_blk, b_blk):
        prefix_a, y_local = jax.lax.associative_scan(
            combine, (a_blk, b_blk), axis=-1)
        A = prefix_a[..., -1]
        B = y_local[..., -1]
        n = jax.lax.axis_size(axis_name)
        idx = jax.lax.axis_index(axis_name)
        all_A = jax.lax.all_gather(A, axis_name)   # (n, ...)
        all_B = jax.lax.all_gather(B, axis_name)
        # Exclusive left-fold of (A, B) maps for blocks < idx, in order.
        carry = jnp.zeros_like(B)
        for j in range(n):
            take = jnp.asarray(j < idx)
            carry = jnp.where(take, all_A[j] * carry + all_B[j], carry)
        return y_local + prefix_a * carry[..., None]

    return jax.shard_map(local_simple, mesh=mesh, in_specs=(spec, spec),
                         out_specs=spec, check_vma=False)(a, b)


def chunked_scan(step, init_carry, inputs, *, chunk: int, unroll: int = 8):
    """Memory-bounded sequential scan for non-associative state machines.

    Splits the time axis (leading axis of each leaf of ``inputs``) into
    ``chunk``-sized pieces and runs ``lax.scan`` over chunks of ``lax.scan``
    over bars. Semantically identical to one big scan; peak live activation
    memory drops from O(T) to O(chunk) under ``jax.checkpoint`` of the inner
    scan — the long-history escape hatch for hysteresis strategies.
    """
    leaves = jax.tree_util.tree_leaves(inputs)
    T = leaves[0].shape[0]
    if T % chunk:
        raise ValueError(f"T={T} not divisible by chunk={chunk}")
    n_chunks = T // chunk
    chunked = jax.tree_util.tree_map(
        lambda x: x.reshape((n_chunks, chunk) + x.shape[1:]), inputs)

    @jax.checkpoint
    def run_chunk(carry, xs):
        return jax.lax.scan(step, carry, xs, unroll=unroll)

    carry, ys = jax.lax.scan(run_chunk, init_carry, chunked)
    return carry, jax.tree_util.tree_map(
        lambda y: y.reshape((T,) + y.shape[2:]), ys)
