"""Time-axis (sequence) parallelism: blockwise scan with ICI carry handoff.

The long-context axis of a backtest is bar time. Indicators are prefix-sum
algebra and the PnL/hysteresis machines are first-order recurrences — the
domain analogue of sequence parallelism is therefore not ring *attention*
(there is no all-pairs interaction) but a **blockwise scan**: shard the time
axis across chips, run the local recurrence per block, then fix up each
block with the carry from the chips to its left. Two primitives cover every
kernel in this framework:

- :func:`sharded_cumsum` — distributed inclusive prefix sum. Local cumsum,
  then one ``psum``-style exclusive scan of per-block totals over ICI
  (implemented with ``all_gather`` of one scalar-per-chip + a masked sum;
  O(T/n) compute, O(n) tiny collective). Rolling sum/mean/var/OLS are all
  cumsum differences, so this makes every indicator time-shardable.
- :func:`sharded_linear_scan` — distributed first-order linear recurrence
  ``y[t] = a[t] * y[t-1] + b[t]`` (EMA, decayed state). Local associative
  scan per block, then a log(n)-step ``ppermute`` ladder combines block
  summaries across chips, and a final local fixup applies each block's
  incoming carry. Exact same math as the single-device
  ``lax.associative_scan`` — verified bit-for-bit in tests.

The band-hysteresis machine — the stateful core of Bollinger/RSI/VWAP/pairs
— time-shards **exactly** as well: its per-bar update is a map on the
3-state space {-1, 0, +1}, and map composition is associative
(``ops.signals.band_transition_maps``), so a block composes into one
3-vector summary, the block summaries fold across chips like the linear
scan's carries, and a local fixup applies each block's incoming state
(:func:`sharded_band_positions`). The Donchian breakout latch is the same
shape of machine, so it shards through the identical fold
(:func:`_transition_positions_local`).

Rolling-extrema state (Donchian channels, stochastic %K) is the fourth
and last state shape: rolling max/min have no cumsum form, but the
reduction never spans more than ``window`` bars, so a bounded halo
(``ppermute`` of the left neighbor's last ``window`` bars) plus a local
sliding ``reduce_window`` reproduces the trailing extrema exactly — no
carry fixup at all (:func:`sharded_donchian_backtest`,
:func:`sharded_stochastic_backtest`).

Only a *general* non-associative state machine (arbitrary
``backtest_scan`` bodies) cannot shard; long histories there use
:func:`chunked_scan` (sequential over chunks, carry threaded on one chip),
which bounds peak memory instead. This mirrors SURVEY.md §5's call:
blockwise scan with carried state, not attention-style ring exchange.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ._shardmap_compat import axis_size, shard_map

TIME_AXIS = "time"


def _exclusive_block_reduce(block_val, axis: str, op, identity):
    """Reduce ``block_val`` with ``op`` over all chips strictly left of this
    one (``identity`` on chip 0).

    ``all_gather`` of one value per chip + masked reduce — O(n_chips)
    scalars over ICI, no host round-trip. The exclusive-prefix pattern
    behind the distributed cumsum (op=sum) and the cross-chip running peak
    (op=max).
    """
    idx = jax.lax.axis_index(axis)
    vals = jax.lax.all_gather(block_val, axis)              # (n, ...)
    n = vals.shape[0]
    mask = (jnp.arange(n) < idx).reshape((n,) + (1,) * block_val.ndim)
    return op(jnp.where(mask, vals, identity), axis=0)


def _exclusive_block_offset(block_total, axis: str):
    """Sum of ``block_total`` over all chips strictly left of this one."""
    return _exclusive_block_reduce(block_total, axis, jnp.sum, 0.0)


def sharded_cumsum(mesh: Mesh, x, *, axis_name: str = TIME_AXIS):
    """Inclusive cumsum along a time axis sharded over ``mesh``.

    ``x`` is ``(..., T)`` with T sharded; result has the same sharding.
    """
    spec = P(*((None,) * (x.ndim - 1) + (axis_name,)))

    def local(x_blk):
        cs = jnp.cumsum(x_blk, axis=-1)
        offset = _exclusive_block_offset(cs[..., -1], axis_name)
        return cs + offset[..., None]

    return shard_map(local, mesh=mesh, in_specs=spec, out_specs=spec,
                         check_vma=False)(x)


def sharded_linear_scan(mesh: Mesh, a, b, *, axis_name: str = TIME_AXIS):
    """Distributed ``y[t] = a[t]*y[t-1] + b[t]`` (y[-1] = 0), T sharded.

    Per block, the composition of all steps is itself a first-order map
    ``y_out = A*y_in + B`` with ``A = prod(a)``, ``B`` = the local scan's
    last element. The cross-chip combine gathers one (A, B) pair per chip and
    left-folds the pairs for blocks to this chip's left; each block then
    applies its incoming carry locally: ``y = scan_local + prefix_a * carry_in``
    where ``prefix_a[t] = prod(a[block_start..t])``. At backtest scale the
    n-chip fold of scalars is cheaper than a log-depth ``ppermute`` ladder
    and exact for any mesh size.
    """
    spec = P(*((None,) * (a.ndim - 1) + (axis_name,)))

    def local_simple(a_blk, b_blk):
        return _linear_scan_local(a_blk, b_blk, axis_name)

    return shard_map(local_simple, mesh=mesh, in_specs=(spec, spec),
                         out_specs=spec, check_vma=False)(a, b)


def _linear_scan_local(a_blk, b_blk, axis_name: str):
    """Blockwise body of :func:`sharded_linear_scan`, composable inside a
    larger ``shard_map`` (the sharded RSI backtest builds its Wilder EMAs
    with this in the same SPMD program as the band machine)."""
    def combine(left, right):
        a1, b1 = left
        a2, b2 = right
        return a1 * a2, a2 * b1 + b2

    prefix_a, y_local = jax.lax.associative_scan(
        combine, (a_blk, b_blk), axis=-1)
    A = prefix_a[..., -1]
    B = y_local[..., -1]
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    all_A = jax.lax.all_gather(A, axis_name)   # (n, ...)
    all_B = jax.lax.all_gather(B, axis_name)
    # Exclusive left-fold of (A, B) maps for blocks < idx, in order.
    carry = jnp.zeros_like(B)
    for j in range(n):
        take = jnp.asarray(j < idx)
        carry = jnp.where(take, all_A[j] * carry + all_B[j], carry)
    return y_local + prefix_a * carry[..., None]


def _ema_local(x_blk, gidx, alpha, axis_name: str):
    """Blockwise EMA with ``rolling.ema``'s exact seed semantics
    (``y[0] = x[0]``, encoded as ``a[0] = 0, b[0] = x[0]`` at the *global*
    first bar)."""
    t0 = gidx == 0
    a = jnp.where(t0, 0.0, 1.0 - alpha) * jnp.ones_like(x_blk)
    b = jnp.where(t0, x_blk, alpha * x_blk)
    return _linear_scan_local(a, b, axis_name)


def sharded_ema(mesh: Mesh, x, *, span=None, alpha=None,
                axis_name: str = TIME_AXIS):
    """EMA of a ``(..., T)`` series with the TIME axis sharded over ``mesh``.

    Same recurrence and seed as :func:`~..ops.rolling.ema`
    (``y[t] = (1-a)*y[t-1] + a*x[t]``, ``y[0] = x[0]``); the cross-block
    carry is one ``(A, B)`` pair per chip over ICI. An EMA has no window —
    its state is O(1) — so unlike the rolling-window backtests there is no
    halo-fits-one-block constraint: any block size works.
    """
    if (span is None) == (alpha is None):
        raise ValueError("pass exactly one of span= or alpha=")
    if alpha is None:
        alpha = 2.0 / (float(span) + 1.0)
    alpha = jnp.float32(alpha)
    spec = P(*((None,) * (x.ndim - 1) + (axis_name,)))
    n_dev = mesh.shape[axis_name]
    T = x.shape[-1]
    if T % n_dev:
        raise ValueError(
            f"T={T} not divisible by the {n_dev}-way {axis_name!r} axis")

    def local(x_blk):
        Tb = x_blk.shape[-1]
        gidx = jnp.arange(Tb) + jax.lax.axis_index(axis_name) * Tb
        return _ema_local(x_blk, gidx, alpha, axis_name)

    return shard_map(local, mesh=mesh, in_specs=spec, out_specs=spec,
                         check_vma=False)(x)


def chunked_scan(step, init_carry, inputs, *, chunk: int, unroll: int = 8):
    """Memory-bounded sequential scan for non-associative state machines.

    Splits the time axis (leading axis of each leaf of ``inputs``) into
    ``chunk``-sized pieces and runs ``lax.scan`` over chunks of ``lax.scan``
    over bars. Semantically identical to one big scan; peak live activation
    memory drops from O(T) to O(chunk) under ``jax.checkpoint`` of the inner
    scan — the long-history escape hatch for hysteresis strategies.
    """
    leaves = jax.tree_util.tree_leaves(inputs)
    T = leaves[0].shape[0]
    if T % chunk:
        raise ValueError(f"T={T} not divisible by chunk={chunk}")
    n_chunks = T // chunk
    chunked = jax.tree_util.tree_map(
        lambda x: x.reshape((n_chunks, chunk) + x.shape[1:]), inputs)

    @jax.checkpoint
    def run_chunk(carry, xs):
        return jax.lax.scan(step, carry, xs, unroll=unroll)

    carry, ys = jax.lax.scan(run_chunk, init_carry, chunked)
    return carry, jax.tree_util.tree_map(
        lambda y: y.reshape((T,) + y.shape[2:]), ys)


def _from_left(x_blk, k: int, axis_name: str):
    """Last ``k`` elements of the LEFT neighbor's block (zeros on chip 0)."""
    n = axis_size(axis_name)
    perm = [(i, i + 1) for i in range(n - 1)]
    return jax.lax.ppermute(x_blk[..., -k:], axis_name, perm)


def _pnl_metrics_local(pos, r, gidx, T: int, *, cost: float,
                       periods_per_year: int, axis_name: str,
                       eps: float = 1e-12, prev_pos=None):
    """Blockwise PnL + summary metrics for a time-sharded position path.

    Shared tail of every time-sharded backtest (SMA, Bollinger): one-bar
    position halo for the lagged exposure, net returns locally, then the
    moments / running-peak drawdown / final equity as ``psum``/``pmax``
    reductions with an exclusive cross-chip max for the peak. A caller
    that already exchanged a one-bar halo for its own state (pairs stacks
    beta with pos) passes ``prev_pos`` to keep that single collective.

    ``T`` is the SEMANTIC history length: bars with ``gidx >= T`` (the
    right padding a caller adds to make the panel divisible by the mesh)
    are dead — they contribute zero net return, turnover, and activity,
    the equity curve stays flat through them, and every denominator uses
    ``T``. With repeat-last padding this makes the padded computation
    bit-equal in semantics to the unpadded one (the ``t_real`` contract
    of the ``sharded_*_backtest`` family)."""
    from ..ops.metrics import metrics_from_reductions

    n_f = jnp.float32(T)
    live = gidx < T
    if prev_pos is None:
        prev_pos = jnp.concatenate(
            [_from_left(pos, 1, axis_name), pos[..., :-1]], axis=-1)
    net = prev_pos * r - jnp.float32(cost) * jnp.abs(pos - prev_pos)
    net = jnp.where(live, net, 0.0)

    # Moments / downside via global sums.
    s1 = jax.lax.psum(jnp.sum(net, axis=-1), axis_name)
    s2 = jax.lax.psum(jnp.sum(net * net, axis=-1), axis_name)
    down = jnp.minimum(net, 0.0)
    down_sq = jax.lax.psum(jnp.sum(down * down, axis=-1), axis_name)

    # Equity + running peak across blocks for drawdown.
    eq = 1.0 + jnp.cumsum(net, axis=-1)
    eq = eq + _exclusive_block_offset(net.sum(-1), axis_name)[..., None]
    peak_local = jax.lax.cummax(eq, axis=eq.ndim - 1)
    left_peak = _exclusive_block_reduce(
        jnp.max(eq, axis=-1), axis_name, jnp.max, -jnp.inf)
    peak = jnp.maximum(peak_local, left_peak[..., None])
    dd = (peak - eq) / jnp.maximum(peak, eps)
    mdd = jax.lax.pmax(jnp.max(dd, axis=-1), axis_name)
    eq_final = jax.lax.psum(
        jnp.sum(jnp.where(gidx == T - 1, eq, 0.0), axis=-1), axis_name)

    active = (jnp.abs(prev_pos) > 0) & live
    wins = (net > 0) & active
    wins_sum = jax.lax.psum(
        jnp.sum(wins.astype(jnp.float32), -1), axis_name)
    active_sum = jax.lax.psum(
        jnp.sum(active.astype(jnp.float32), -1), axis_name)
    turnover = jax.lax.psum(
        jnp.sum(jnp.where(live, jnp.abs(pos - prev_pos), 0.0), axis=-1),
        axis_name)
    return metrics_from_reductions(
        s1=s1, s2=s2, downside_sq_sum=down_sq, mdd=mdd,
        eq_final=eq_final, wins_sum=wins_sum, active_sum=active_sum,
        turnover=turnover, n=n_f, periods_per_year=periods_per_year,
        eps=eps)


def _block_returns(close_blk, gidx, axis_name: str):
    """Per-bar simple returns with a one-bar halo (r[0] = 0 globally)."""
    prev_close = jnp.concatenate(
        [_from_left(close_blk, 1, axis_name), close_blk[..., :-1]], axis=-1)
    return jnp.where(gidx == 0, 0.0,
                     close_blk / jnp.where(gidx == 0, 1.0, prev_close) - 1.0)


def _cumsum_ext(series_blk, halo_w: int, axis_name: str):
    """Global prefix sum of a time-sharded series, plus a ``halo_w``-bar
    left halo — the lagged-read window every cumsum-difference rolling sum
    needs. Returns ``(cs, cs_ext)``."""
    cs = jnp.cumsum(series_blk, axis=-1)
    cs = cs + _exclusive_block_offset(cs[..., -1], axis_name)[..., None]
    return cs, jnp.concatenate(
        [_from_left(cs, halo_w, axis_name), cs], axis=-1)


def _windowed_sum_blk(cs, cs_ext, gidx, w: int, halo_w: int):
    """Trailing-``w`` rolling sum from the extended prefix sum:
    ``cs[t] - cs[t-w]`` with a zero lagged read in the global warmup
    (``t < w``) — ``rolling.rolling_sum``'s semantics, blockwise."""
    Tb = cs.shape[-1]
    lagged = jax.lax.slice_in_dim(
        cs_ext, halo_w - w, halo_w - w + Tb, axis=-1)
    return cs - jnp.where(gidx >= w, lagged, 0.0)


def _windowed_zscore_local(series_blk, gidx, window: int, halo_w: int,
                           T: int, axis_name: str, *, eps: float = 1e-12):
    """Blockwise rolling z-score with series-mean centering — the shared
    signal head of the Bollinger and pairs time-sharded backtests
    (``rolling.rolling_zscore``'s formula: ddof=0, centered second moments
    against the FULL-history mean as the f32 cancellation guard).

    The three windowed sums (centered, centered², raw) ride ONE stacked
    ``_cumsum_ext`` — collectives are latency-bound and XLA will not CSE
    them, so one ``all_gather`` + one ``ppermute`` serve all three (the
    same one-collective discipline as ``_band_positions_local``).
    Per-series numerics are identical to separate calls: the stack axis is
    leading, the scans are per-row.
    """
    w_f = jnp.float32(window)
    # Mean over the LIVE history only (gidx < T): with a right-padded
    # panel the pad bars must not shift the full-history centering.
    mean = (jax.lax.psum(
        jnp.sum(jnp.where(gidx < T, series_blk, 0.0), axis=-1), axis_name)
            / jnp.float32(T))[..., None]
    sc = series_blk - mean
    stacked = jnp.stack([sc, sc * sc, series_blk])
    cs, cs_ext = _cumsum_ext(stacked, halo_w, axis_name)
    s = _windowed_sum_blk(cs, cs_ext, gidx, window, halo_w)
    s1, s2, ssum = s[0], s[1], s[2]
    var = jnp.maximum((s2 - s1 * s1 / w_f) / w_f, 0.0)
    return (series_blk - ssum / w_f) / (jnp.sqrt(var) + eps)


def _transition_positions_local(maps, axis_name: str):
    """Position path of ANY {-1,0,+1} transition-map machine, one time
    block, exact across blocks.

    The block's prefix maps come from a local shift-doubling prefix
    composition, the whole block composes into one 3-vector summary, and
    the state
    *entering* this block is the exclusive left-fold of block summaries
    over ICI (same carry pattern as :func:`sharded_linear_scan` — one
    3-vector per chip crosses the wire). The fixup routes each bar's
    prefix map through the incoming state. Shared by the band-hysteresis
    machine (Bollinger/RSI/pairs/stochastic) and the Donchian breakout
    latch — any stateful strategy whose per-bar update is a map on the
    3-state space shards through here."""
    from ..ops import signals

    # Shift-doubling ladder, not associative_scan: bit-identical for
    # select-only map composition and avoids the scan lowering's
    # load-sensitive native compile (signals.prefix_compose_maps).
    pm, p0, pp = signals.prefix_compose_maps(maps)

    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    # One latency-bound collective, not three: the block summary is a
    # stacked (3, ...) map — (next state from -1, from 0, from +1).
    summary = jnp.stack([pm[..., -1], p0[..., -1], pp[..., -1]])
    alls = jax.lax.all_gather(summary, axis_name)           # (n, 3, ...)
    # Exclusive left-fold: start flat, apply each earlier block's map.
    state = jnp.zeros_like(p0[..., -1])
    for j in range(n):
        nxt = jnp.where(state < 0, alls[j, 0],
                        jnp.where(state > 0, alls[j, 2], alls[j, 1]))
        state = jnp.where(j < idx, nxt, state)
    state = state[..., None]
    return jnp.where(state < 0, pm, jnp.where(state > 0, pp, p0))


def _band_positions_local(z_blk, valid_blk, z_entry, z_exit, axis_name: str):
    """Band-hysteresis positions for one time block, exact across blocks
    (``ops.signals.band_transition_maps`` composed through
    :func:`_transition_positions_local`)."""
    from ..ops import signals

    maps = signals.band_transition_maps(z_blk, valid_blk, z_entry, z_exit)
    return _transition_positions_local(maps, axis_name)


def _latch_maps(up, down, valid):
    """Per-bar transition maps of the Donchian breakout latch
    (``models.donchian._latch``'s step): break above the prior channel
    high -> +1 from any state, below the prior low -> -1, else hold;
    invalid bars force flat. ``up`` wins over ``down`` (a bar clearing
    both channels goes long), exactly as the scan's nested ``where``."""
    def nxt_from(prev):
        return jnp.where(up, 1.0, jnp.where(down, -1.0, prev))

    one = jnp.ones(up.shape, jnp.float32)
    zero = jnp.zeros_like(one)
    v = jnp.broadcast_to(valid, up.shape)
    return (jnp.where(v, nxt_from(-one), zero),
            jnp.where(v, nxt_from(zero), zero),
            jnp.where(v, nxt_from(one), zero))


def _reduce_window_last(x, w: int, mode: str):
    """Sliding extrema over the last axis: ``out[..., j] = mode(x[..., j:j+w])``
    (VALID — output length ``x.shape[-1] - w + 1``)."""
    init = -jnp.inf if mode == "max" else jnp.inf
    comp = jax.lax.max if mode == "max" else jax.lax.min
    dims = (1,) * (x.ndim - 1) + (w,)
    return jax.lax.reduce_window(x, init, comp, dims, (1,) * x.ndim, "VALID")


def sharded_band_positions(mesh: Mesh, z, valid, z_entry, z_exit=0.0, *,
                           axis_name: str = TIME_AXIS):
    """Band-hysteresis position path with the TIME axis sharded.

    Exact (bit-level) match to ``ops.signals.band_hysteresis_assoc`` on the
    unsharded inputs: states are small integers in float32 and every
    comparison sees the same values, so sharding changes nothing but where
    the composition happens. ``z``/``valid`` are ``(..., T)`` with T
    sharded over ``mesh``'s ``axis_name``; ``z_entry``/``z_exit`` are
    scalars (replicated)."""
    spec = P(*((None,) * (z.ndim - 1) + (axis_name,)))

    def local(z_blk, valid_blk):
        return _band_positions_local(z_blk, valid_blk, z_entry, z_exit,
                                     axis_name)

    return shard_map(local, mesh=mesh, in_specs=(spec, spec),
                         out_specs=spec, check_vma=False)(
        z, jnp.broadcast_to(valid, z.shape))


def sharded_sma_backtest(mesh: Mesh, close, fast: int, slow: int, *,
                         cost: float = 0.0, periods_per_year: int = 252,
                         axis_name: str = TIME_AXIS,
                         t_real: int | None = None):
    """End-to-end SMA-crossover backtest with the TIME axis sharded.

    The composed long-context path: for a ``(..., T)`` close panel whose
    bar axis is sharded across ``mesh``, every stage runs blockwise with
    O(1)-per-chip ICI traffic — returns via a one-bar halo exchange
    (``ppermute``), rolling SMAs via the distributed cumsum plus a
    ``max(fast, slow)``-bar halo for the lagged prefix, PnL locally, and
    the summary metrics as ``psum``/``pmax`` reductions (the running-peak
    drawdown uses an exclusive cross-chip max of block maxima). One
    history longer than any single chip's memory therefore backtests
    without ever materializing the full series in one place.

    ``fast``/``slow`` are static ints with ``slow <= block length`` (the
    halo must fit one neighbor block). Returns
    :class:`~..ops.metrics.Metrics` with scalar-per-series fields,
    replicated across the mesh. Matches the unsharded
    single-device computation to f32 tolerance.
    """
    from ..ops.metrics import Metrics

    if not (0 < fast < slow):
        raise ValueError(f"need 0 < fast < slow, got {fast}, {slow}")
    n_dev = mesh.shape[axis_name]   # the TIME axis size, not total devices
    T_pad = close.shape[-1]
    if T_pad % n_dev:
        raise ValueError(
            f"T={T_pad} not divisible by the {n_dev}-way {axis_name!r} axis")
    if slow > T_pad // n_dev:
        raise ValueError(
            f"slow={slow} exceeds the {T_pad // n_dev}-bar block; the halo "
            "exchange needs the window to fit one neighbor block")
    T = _resolve_t_real(T_pad, t_real)
    halo_w = slow
    spec = P(*((None,) * (close.ndim - 1) + (axis_name,)))
    rep = P(*((None,) * (close.ndim - 1)))   # metrics drop the time axis

    def local(close_blk):
        Tb = close_blk.shape[-1]
        idx = jax.lax.axis_index(axis_name)
        gidx = jnp.arange(Tb) + idx * Tb                  # global bar index
        r = _block_returns(close_blk, gidx, axis_name)

        # Global prefix sum of closes; lagged reads via a slow-bar halo.
        cs, cs_ext = _cumsum_ext(close_blk, halo_w, axis_name)

        def sma(w):
            return _windowed_sum_blk(cs, cs_ext, gidx, w,
                                     halo_w) / jnp.float32(w)

        valid = gidx >= slow - 1
        pos = jnp.where(valid, jnp.sign(sma(fast) - sma(slow)), 0.0)
        return _pnl_metrics_local(pos, r, gidx, T, cost=cost,
                                  periods_per_year=periods_per_year,
                                  axis_name=axis_name)

    out_specs = Metrics(*(rep for _ in Metrics._fields))
    return shard_map(local, mesh=mesh, in_specs=spec,
                         out_specs=out_specs, check_vma=False)(close)


def sharded_bollinger_backtest(mesh: Mesh, close, window: int, k: float, *,
                               z_exit: float = 0.0, cost: float = 0.0,
                               periods_per_year: int = 252,
                               axis_name: str = TIME_AXIS,
                               t_real: int | None = None):
    """End-to-end Bollinger mean-reversion backtest, TIME axis sharded.

    The long-context composition for a *stateful* strategy: blockwise
    rolling z-score (distributed cumsums of the series-centered moments +
    a ``window``-bar halo, ``rolling.rolling_zscore``'s formula) feeding
    the exactly-sharded band machine (:func:`_band_positions_local`) and
    the shared blockwise PnL/metrics tail. One history longer than any
    single chip's memory runs the full hysteresis strategy without ever
    materializing the series in one place — the reference has no analogue
    (its compute slot is a sleep stub, reference
    ``src/worker/process.rs:21-25``).

    ``window`` is a static int with ``window <= block length`` (halo
    bound). Returns scalar-per-series :class:`~..ops.metrics.Metrics`,
    replicated. Matches the single-device computation to f32 tolerance.
    """
    from ..ops.metrics import Metrics

    n_dev = mesh.shape[axis_name]
    T_pad = close.shape[-1]
    if T_pad % n_dev:
        raise ValueError(
            f"T={T_pad} not divisible by the {n_dev}-way {axis_name!r} axis")
    if window > T_pad // n_dev:
        raise ValueError(
            f"window={window} exceeds the {T_pad // n_dev}-bar block; the "
            "halo exchange needs the window to fit one neighbor block")
    T = _resolve_t_real(T_pad, t_real)
    halo_w = window
    eps = 1e-12
    spec = P(*((None,) * (close.ndim - 1) + (axis_name,)))
    rep = P(*((None,) * (close.ndim - 1)))

    def local(close_blk):
        Tb = close_blk.shape[-1]
        idx = jax.lax.axis_index(axis_name)
        gidx = jnp.arange(Tb) + idx * Tb
        r = _block_returns(close_blk, gidx, axis_name)

        z = _windowed_zscore_local(close_blk, gidx, window, halo_w, T,
                                   axis_name, eps=eps)
        valid = gidx >= window - 1
        z = jnp.where(valid, z, 0.0)

        pos = _band_positions_local(z, jnp.broadcast_to(valid, z.shape),
                                    jnp.float32(k), jnp.float32(z_exit),
                                    axis_name)
        return _pnl_metrics_local(pos, r, gidx, T, cost=cost,
                                  periods_per_year=periods_per_year,
                                  axis_name=axis_name)

    out_specs = Metrics(*(rep for _ in Metrics._fields))
    return shard_map(local, mesh=mesh, in_specs=spec,
                         out_specs=out_specs, check_vma=False)(close)


def sharded_rsi_backtest(mesh: Mesh, close, period: int, band: float, *,
                         cost: float = 0.0, periods_per_year: int = 252,
                         axis_name: str = TIME_AXIS,
                         t_real: int | None = None):
    """End-to-end RSI mean-reversion backtest, TIME axis sharded.

    The *EMA-state* long-context composition (Bollinger covers the
    rolling-window case): Wilder's smoothed gain/loss averages are
    first-order linear recurrences, so each runs blockwise through
    :func:`_linear_scan_local` with one ``(A, B)`` carry pair per chip over
    ICI — no halo at all, since an EMA's state is O(1) rather than a
    window of bars. The resulting centered RSI feeds the exactly-sharded
    band machine (:func:`_band_positions_local`, ``models.rsi`` semantics:
    long below ``50 - band``, short above ``50 + band``, exit at 50) and
    the shared blockwise PnL/metrics tail. Only the one-bar return/diff
    halo constrains the block size.

    ``period`` is a static int (the per-chip sweep path vmaps over traced
    periods; this is the one-long-history path). Returns scalar-per-series
    :class:`~..ops.metrics.Metrics`, replicated. Matches the unsharded
    ``rsi`` strategy backtest to f32 tolerance.
    """
    from ..ops.metrics import Metrics

    n_dev = mesh.shape[axis_name]
    T_pad = close.shape[-1]
    if T_pad % n_dev:
        raise ValueError(
            f"T={T_pad} not divisible by the {n_dev}-way {axis_name!r} axis")
    if period < 1:
        raise ValueError(f"period must be >= 1, got {period}")
    T = _resolve_t_real(T_pad, t_real)
    alpha = jnp.float32(1.0 / period)   # Wilder's decay (models.rsi)
    spec = P(*((None,) * (close.ndim - 1) + (axis_name,)))
    rep = P(*((None,) * (close.ndim - 1)))

    def local(close_blk):
        Tb = close_blk.shape[-1]
        idx = jax.lax.axis_index(axis_name)
        gidx = jnp.arange(Tb) + idx * Tb

        # ONE one-bar halo exchange serves both the returns and the RSI
        # diff (collectives are latency-bound; XLA is not guaranteed to
        # CSE two identical ppermutes).
        prev = jnp.concatenate(
            [_from_left(close_blk, 1, axis_name), close_blk[..., :-1]],
            axis=-1)
        r = jnp.where(gidx == 0, 0.0,
                      close_blk / jnp.where(gidx == 0, 1.0, prev) - 1.0)
        # diff[0] = 0 globally (jnp.diff prepend=x0 semantics).
        diff = jnp.where(gidx == 0, 0.0, close_blk - prev)
        avg_gain = _ema_local(jnp.maximum(diff, 0.0), gidx, alpha, axis_name)
        avg_loss = _ema_local(jnp.maximum(-diff, 0.0), gidx, alpha,
                              axis_name)
        rsi = 100.0 - 100.0 / (1.0 + avg_gain / (avg_loss + 1e-12))

        valid = gidx >= period   # rolling.valid_mask(T, period + 1)
        pos = _band_positions_local(
            rsi - 50.0, jnp.broadcast_to(valid, rsi.shape),
            jnp.float32(band), jnp.float32(0.0), axis_name)
        return _pnl_metrics_local(pos, r, gidx, T, cost=cost,
                                  periods_per_year=periods_per_year,
                                  axis_name=axis_name)

    out_specs = Metrics(*(rep for _ in Metrics._fields))
    return shard_map(local, mesh=mesh, in_specs=spec,
                         out_specs=out_specs, check_vma=False)(close)


def sharded_pairs_backtest(mesh: Mesh, y_close, x_close, lookback: int,
                           z_entry: float, *, z_exit: float = 0.0,
                           cost: float = 0.0, periods_per_year: int = 252,
                           axis_name: str = TIME_AXIS,
                           t_real: int | None = None):
    """End-to-end rolling-OLS pairs backtest, TIME axis sharded.

    The two-legged long-context composition — every blockwise piece this
    module already has, assembled for the hardest single-pair strategy:
    distributed cumsums of the centered OLS moments (``lookback``-bar
    halos) give the rolling hedge ratio, the spread z-scores reuse the
    same windowed-sum primitive, the exactly-sharded band machine turns z
    into positions, and the shared PnL tail prices the *hedged* spread
    return ``(r_y - beta[t-1] r_x) / max(1 + |beta[t-1]|, 1)`` — the tail
    takes any per-bar return factor, so pairs need no new reduction code.
    Formulas mirror ``models.pairs.pair_backtest`` (series-centered
    moments, eps=1e-12, warmup spread = y, valid from ``2*lookback - 1``
    bars). Parity with the single-device computation is f32-tight except
    at knife-edge band entries: the blockwise cumsum rounds z ~1e-6
    differently, and a bar where ``|z - z_entry|`` is that small can
    resolve differently, moving a long history's metrics by ~1e-3
    relative per flipped bar (the same caveat class as the fused pairs
    kernel; the parity test bounds both the flip count and the
    non-flipped error).

    ``lookback`` is a static int with ``lookback <= block length`` (halo
    bound). Returns scalar-per-pair :class:`~..ops.metrics.Metrics`,
    replicated.
    """
    from ..ops.metrics import Metrics

    n_dev = mesh.shape[axis_name]
    T_pad = y_close.shape[-1]
    if T_pad % n_dev:
        raise ValueError(
            f"T={T_pad} not divisible by the {n_dev}-way {axis_name!r} axis")
    if lookback > T_pad // n_dev:
        raise ValueError(
            f"lookback={lookback} exceeds the {T_pad // n_dev}-bar block; "
            "the halo exchange needs the window to fit one neighbor block")
    T = _resolve_t_real(T_pad, t_real)
    halo_w = lookback
    eps = 1e-12
    w_f = jnp.float32(lookback)
    spec = P(*((None,) * (y_close.ndim - 1) + (axis_name,)))
    rep = P(*((None,) * (y_close.ndim - 1)))

    def local(y_blk, x_blk):
        Tb = y_blk.shape[-1]
        idx = jax.lax.axis_index(axis_name)
        gidx = jnp.arange(Tb) + idx * Tb
        # Both legs' returns through ONE one-bar halo exchange.
        r2 = _block_returns(jnp.stack([y_blk, x_blk]), gidx, axis_name)
        ry, rx = r2[0], r2[1]

        # Series means over the LIVE history (psum, gidx < T so right
        # padding can't shift them), the same f32 cancellation guard as
        # rolling.rolling_ols.
        my = (jax.lax.psum(
            jnp.sum(jnp.where(gidx < T, y_blk, 0.0), axis=-1), axis_name)
              / jnp.float32(T))[..., None]
        mx = (jax.lax.psum(
            jnp.sum(jnp.where(gidx < T, x_blk, 0.0), axis=-1), axis_name)
              / jnp.float32(T))[..., None]
        yc, xc = y_blk - my, x_blk - mx

        # All four OLS moment sums through ONE stacked _cumsum_ext
        # (collectives are latency-bound; one all_gather + one ppermute
        # serve the stack — same discipline as _windowed_zscore_local).
        cs, cs_ext = _cumsum_ext(jnp.stack([xc, yc, xc * xc, xc * yc]),
                                 halo_w, axis_name)
        s = _windowed_sum_blk(cs, cs_ext, gidx, lookback, halo_w)
        sx, sy, sxx, sxy = s[0], s[1], s[2], s[3]
        cov = sxy - sx * sy / w_f
        var = jnp.maximum(sxx - sx * sx / w_f, 0.0)
        beta = cov / (var + eps)
        alpha = (sy / w_f + my) - beta * (sx / w_f + mx)
        ok_w = gidx >= lookback - 1
        beta = jnp.where(ok_w, beta, 0.0)
        # Warmup spread is exactly y (rolling_ols fill=0.0): those bars
        # feed the z-score's series mean and early windowed sums.
        spread = jnp.where(ok_w, y_blk - (alpha + beta * x_blk), y_blk)

        z = _windowed_zscore_local(spread, gidx, lookback, halo_w, T,
                                   axis_name, eps=eps)
        valid = gidx >= 2 * lookback - 2
        z = jnp.where(valid, z, 0.0)

        pos = _band_positions_local(z, jnp.broadcast_to(valid, z.shape),
                                    jnp.float32(z_entry),
                                    jnp.float32(z_exit), axis_name)
        # ONE one-bar halo exchange serves both lagged states (pos for the
        # PnL tail, beta for the hedge) — same discipline as the returns.
        pb = jnp.stack([pos, beta])
        prev = jnp.concatenate(
            [_from_left(pb, 1, axis_name), pb[..., :-1]], axis=-1)
        prev_pos, prev_beta = prev[0], prev[1]
        gross = 1.0 + jnp.abs(prev_beta)
        hr = (ry - prev_beta * rx) / jnp.maximum(gross, 1.0)
        return _pnl_metrics_local(pos, hr, gidx, T, cost=cost,
                                  periods_per_year=periods_per_year,
                                  axis_name=axis_name, prev_pos=prev_pos)

    out_specs = Metrics(*(rep for _ in Metrics._fields))
    return shard_map(local, mesh=mesh, in_specs=(spec, spec),
                         out_specs=out_specs, check_vma=False)(
        y_close, x_close)


def _resolve_t_real(T_pad: int, t_real) -> int:
    """Semantic history length of a right-padded panel.

    The ``t_real`` contract shared by every ``sharded_*_backtest``: a
    caller whose history is not divisible by the mesh pads the time axis
    on the RIGHT with repeat-last values up to ``T_pad`` and passes the
    real length here. Pad bars then earn zero return, zero turnover, and
    zero weight in every mean/metric denominator (see
    :func:`_pnl_metrics_local`), so the padded result equals the
    unpadded one exactly — the same discipline as the fused kernels'
    per-ticker ``t_real`` (``ops.fused``)."""
    if t_real is None:
        return T_pad
    t = int(t_real)
    if not 0 < t <= T_pad:
        raise ValueError(
            f"t_real={t} must be in (0, {T_pad}] (the padded length)")
    return t


def _check_time_axis(T: int, n_dev: int, window: int, axis_name: str,
                     what: str):
    if window < 1:
        # A non-positive window would not crash: the windowed sums divide
        # by w and the halo slice x[..., -0:] takes the FULL block, so the
        # call would return silent NaN/garbage metrics instead of failing.
        raise ValueError(f"{what} must be >= 1, got {window}")
    if T % n_dev:
        raise ValueError(
            f"T={T} not divisible by the {n_dev}-way {axis_name!r} axis")
    if window > T // n_dev:
        raise ValueError(
            f"{what}={window} exceeds the {T // n_dev}-bar block; the halo "
            "exchange needs the window to fit one neighbor block")


def _donchian_metrics_local(latch_src, hi_src, lo_src, gidx, window: int,
                            T: int, *, cost: float, periods_per_year: int,
                            axis_name: str):
    """Shared blockwise body of both Donchian variants: ONE stacked
    ``window``-bar halo exchange serves the returns' lagged close and both
    prior-channel extrema (collectives are latency-bound — same
    one-collective discipline as the z-score/pairs paths). The prior
    channel at bar t reduces bars ``t-window .. t-1``; the breakout latch
    is a 3-state transition-map machine, so it composes across chips
    exactly like the band machine."""
    w = window
    stacked = (latch_src if hi_src is latch_src
               else jnp.stack([latch_src, hi_src, lo_src]))
    ext = jnp.concatenate([_from_left(stacked, w, axis_name), stacked],
                          axis=-1)
    if hi_src is latch_src:
        close_ext, hi_ext, lo_ext = ext, ext, ext
        close_blk = latch_src
    else:
        close_ext, hi_ext, lo_ext = ext[0], ext[1], ext[2]
        close_blk = latch_src
    Tb = close_blk.shape[-1]

    prev_close = jax.lax.slice_in_dim(close_ext, w - 1, w - 1 + Tb, axis=-1)
    r = jnp.where(gidx == 0, 0.0,
                  close_blk / jnp.where(gidx == 0, 1.0, prev_close) - 1.0)

    # hi_prev[t] = max(src[t-w .. t-1]): the w-window starting at local i
    # of the w-halo'd series. Warmup values are garbage on chip 0 (zero
    # halo) — masked by `valid` below, exactly like the unsharded fill.
    hi_prev = jax.lax.slice_in_dim(
        _reduce_window_last(hi_ext, w, "max"), 0, Tb, axis=-1)
    lo_prev = jax.lax.slice_in_dim(
        _reduce_window_last(lo_ext, w, "min"), 0, Tb, axis=-1)

    valid = gidx >= w            # rolling.valid_mask(T, w + 1)
    up = close_blk >= hi_prev
    down = close_blk <= lo_prev
    pos = _transition_positions_local(_latch_maps(up, down, valid),
                                      axis_name)
    return _pnl_metrics_local(pos, r, gidx, T, cost=cost,
                              periods_per_year=periods_per_year,
                              axis_name=axis_name)


def sharded_donchian_backtest(mesh: Mesh, close, window: int, *,
                              cost: float = 0.0, periods_per_year: int = 252,
                              axis_name: str = TIME_AXIS,
                              t_real: int | None = None):
    """End-to-end Donchian-channel breakout backtest, TIME axis sharded.

    The *rolling-extrema-state* long-context composition — the fourth and
    last state shape (after windowed-sum, EMA, and band-machine states):
    rolling max/min have no cumsum form, so the channel extrema come from
    a bounded halo instead of a distributed prefix sum — each bar's
    ``window``-bar channel reaches at most ``window`` bars into the left
    neighbor's block, so ONE stacked ``ppermute`` plus a local sliding
    ``reduce_window`` reproduces the trailing extrema exactly (extrema
    need no carry fixup at all: unlike a cumsum the reduction never spans
    more than ``window`` bars). The breakout latch (hold until the
    opposite channel is touched) is a {-1,0,+1} transition-map machine —
    ``models.donchian._latch``'s scan — so it composes across chips
    through the same 3-vector summary fold as the band machine
    (:func:`_transition_positions_local`). Semantics match
    ``models.donchian`` (channel at bar t uses bars ``t-window..t-1``,
    ties break long, warmup flat, valid from ``window`` bars).

    ``window`` is a static int with ``window <= block length`` (halo
    bound). Returns scalar-per-series :class:`~..ops.metrics.Metrics`,
    replicated. Matches the single-device computation to f32 tolerance.
    """
    from ..ops.metrics import Metrics

    n_dev = mesh.shape[axis_name]
    T_pad = close.shape[-1]
    _check_time_axis(T_pad, n_dev, window, axis_name, "window")
    T = _resolve_t_real(T_pad, t_real)
    spec = P(*((None,) * (close.ndim - 1) + (axis_name,)))
    rep = P(*((None,) * (close.ndim - 1)))

    def local(close_blk):
        Tb = close_blk.shape[-1]
        gidx = jnp.arange(Tb) + jax.lax.axis_index(axis_name) * Tb
        return _donchian_metrics_local(
            close_blk, close_blk, close_blk, gidx, window, T, cost=cost,
            periods_per_year=periods_per_year, axis_name=axis_name)

    out_specs = Metrics(*(rep for _ in Metrics._fields))
    return shard_map(local, mesh=mesh, in_specs=spec,
                         out_specs=out_specs, check_vma=False)(close)


def sharded_donchian_hl_backtest(mesh: Mesh, close, high, low, window: int,
                                 *, cost: float = 0.0,
                                 periods_per_year: int = 252,
                                 axis_name: str = TIME_AXIS,
                                 t_real: int | None = None):
    """Classic high/low-channel Donchian breakout, TIME axis sharded.

    Same composition as :func:`sharded_donchian_backtest` with the
    channels built from the HIGH/LOW columns (``models.donchian``'s
    ``donchian_hl``); the three series share ONE stacked halo exchange.
    """
    from ..ops.metrics import Metrics

    n_dev = mesh.shape[axis_name]
    T_pad = close.shape[-1]
    _check_time_axis(T_pad, n_dev, window, axis_name, "window")
    T = _resolve_t_real(T_pad, t_real)
    spec = P(*((None,) * (close.ndim - 1) + (axis_name,)))
    rep = P(*((None,) * (close.ndim - 1)))

    def local(close_blk, high_blk, low_blk):
        Tb = close_blk.shape[-1]
        gidx = jnp.arange(Tb) + jax.lax.axis_index(axis_name) * Tb
        return _donchian_metrics_local(
            close_blk, high_blk, low_blk, gidx, window, T, cost=cost,
            periods_per_year=periods_per_year, axis_name=axis_name)

    out_specs = Metrics(*(rep for _ in Metrics._fields))
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=out_specs, check_vma=False)(
        close, high, low)


def sharded_stochastic_backtest(mesh: Mesh, close, high, low, window: int,
                                band: float, *, cost: float = 0.0,
                                periods_per_year: int = 252,
                                axis_name: str = TIME_AXIS,
                                t_real: int | None = None):
    """End-to-end stochastic-%K mean-reversion backtest, TIME axis sharded.

    Rolling-extrema state feeding the band machine: the trailing
    ``window``-bar high/low channel comes from the bounded-halo sliding
    ``reduce_window`` (window ends AT bar t here — lag 0, vs the Donchian
    channel's lag 1), %K centers it, and the exactly-sharded band machine
    plus the shared PnL tail finish the composition. Semantics match
    ``models.stochastic`` (flat channel -> neutral 50, valid from
    ``window - 1`` bars, enter long below ``50 - band``, exit at 50).

    ``window`` is a static int with ``window <= block length``. Returns
    scalar-per-series :class:`~..ops.metrics.Metrics`, replicated.
    """
    from ..ops.metrics import Metrics

    eps = 1e-12
    n_dev = mesh.shape[axis_name]
    T_pad = close.shape[-1]
    _check_time_axis(T_pad, n_dev, window, axis_name, "window")
    T = _resolve_t_real(T_pad, t_real)
    halo = max(window - 1, 1)    # extrema need w-1 left bars; returns need 1
    spec = P(*((None,) * (close.ndim - 1) + (axis_name,)))
    rep = P(*((None,) * (close.ndim - 1)))

    def local(close_blk, high_blk, low_blk):
        Tb = close_blk.shape[-1]
        gidx = jnp.arange(Tb) + jax.lax.axis_index(axis_name) * Tb

        # ONE stacked halo exchange serves the lagged close and both
        # channel extrema.
        stacked = jnp.stack([close_blk, high_blk, low_blk])
        ext = jnp.concatenate([_from_left(stacked, halo, axis_name),
                               stacked], axis=-1)
        prev_close = jax.lax.slice_in_dim(ext[0], halo - 1, halo - 1 + Tb,
                                          axis=-1)
        r = jnp.where(gidx == 0, 0.0,
                      close_blk / jnp.where(gidx == 0, 1.0, prev_close)
                      - 1.0)

        # hh[t] = max(high[t-w+1 .. t]): w-window ending at local i, i.e.
        # starting at ext index i + halo - w + 1.
        start = halo - window + 1
        hh = jax.lax.slice_in_dim(
            _reduce_window_last(ext[1], window, "max"), start, start + Tb,
            axis=-1)
        ll = jax.lax.slice_in_dim(
            _reduce_window_last(ext[2], window, "min"), start, start + Tb,
            axis=-1)
        rng = hh - ll
        k_pct = jnp.where(rng > eps, 100.0 * (close_blk - ll) / (rng + eps),
                          50.0)

        valid = gidx >= window - 1   # rolling.valid_mask(T, window)
        pos = _band_positions_local(
            jnp.where(valid, k_pct - 50.0, 0.0),
            jnp.broadcast_to(valid, k_pct.shape), jnp.float32(band),
            jnp.float32(0.0), axis_name)
        return _pnl_metrics_local(pos, r, gidx, T, cost=cost,
                                  periods_per_year=periods_per_year,
                                  axis_name=axis_name)

    out_specs = Metrics(*(rep for _ in Metrics._fields))
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=out_specs, check_vma=False)(
        close, high, low)


def sharded_trix_backtest(mesh: Mesh, close, span: int, signal: int, *,
                          cost: float = 0.0, periods_per_year: int = 252,
                          axis_name: str = TIME_AXIS,
                          t_real: int | None = None):
    """End-to-end TRIX signal-line backtest, TIME axis sharded.

    Pure EMA-state composition (``models.trix`` semantics): the triple
    smoothing is three chained blockwise linear scans
    (:func:`_ema_local` — one ``(A, B)`` carry pair per chip each, no
    halo), the one-bar rate of change reuses the return halo exchange,
    and the signal line is a fourth blockwise EMA over the trix series.
    Like the sharded RSI path, only the one-bar halo constrains the block
    size — EMA state is O(1), so histories of any length shard.

    ``span``/``signal`` are static ints. Returns scalar-per-series
    :class:`~..ops.metrics.Metrics`, replicated. Matches the unsharded
    ``trix`` strategy backtest to f32 tolerance.
    """
    from ..ops.metrics import Metrics

    n_dev = mesh.shape[axis_name]
    T_pad = close.shape[-1]
    if T_pad % n_dev:
        raise ValueError(
            f"T={T_pad} not divisible by the {n_dev}-way {axis_name!r} axis")
    if span < 1 or signal < 1:
        raise ValueError(f"spans must be >= 1, got {span}, {signal}")
    T = _resolve_t_real(T_pad, t_real)
    a_span = jnp.float32(2.0 / (span + 1.0))
    a_sig = jnp.float32(2.0 / (signal + 1.0))
    spec = P(*((None,) * (close.ndim - 1) + (axis_name,)))
    rep = P(*((None,) * (close.ndim - 1)))

    def local(close_blk):
        Tb = close_blk.shape[-1]
        gidx = jnp.arange(Tb) + jax.lax.axis_index(axis_name) * Tb
        r = _block_returns(close_blk, gidx, axis_name)

        e3 = _ema_local(
            _ema_local(
                _ema_local(close_blk, gidx, a_span, axis_name),
                gidx, a_span, axis_name),
            gidx, a_span, axis_name)
        # One-bar rate of change: trix[0] = 0 globally (models.trix seeds
        # the lagged read with e3[0]).
        e3_prev = jnp.concatenate(
            [_from_left(e3, 1, axis_name), e3[..., :-1]], axis=-1)
        trix = jnp.where(gidx == 0, 0.0,
                         e3 / jnp.where(gidx == 0, 1.0, e3_prev) - 1.0)
        sig = _ema_local(trix, gidx, a_sig, axis_name)

        warm = 3 * span + signal - 2
        valid = gidx >= warm - 1   # rolling.valid_mask(T, warm)
        pos = jnp.where(valid, jnp.sign(trix - sig), 0.0)
        return _pnl_metrics_local(pos, r, gidx, T, cost=cost,
                                  periods_per_year=periods_per_year,
                                  axis_name=axis_name)

    out_specs = Metrics(*(rep for _ in Metrics._fields))
    return shard_map(local, mesh=mesh, in_specs=spec,
                         out_specs=out_specs, check_vma=False)(close)


def sharded_momentum_backtest(mesh: Mesh, close, lookback: int, *,
                              cost: float = 0.0, periods_per_year: int = 252,
                              axis_name: str = TIME_AXIS,
                              t_real: int | None = None):
    """End-to-end time-series momentum backtest, TIME axis sharded.

    The simplest windowed composition (``models.momentum`` semantics:
    ``sign(close[t] - close[t-lookback])``, valid from ``lookback`` bars):
    the lagged read is a pure bounded-halo exchange — no cumsum, no carry —
    so ONE stacked ``ppermute`` of the left neighbor's last ``lookback``
    bars serves both the one-bar return lag and the momentum lag.

    ``lookback`` is a static int with ``lookback <= block length`` (halo
    bound). Returns scalar-per-series :class:`~..ops.metrics.Metrics`,
    replicated. Matches the single-device computation to f32 tolerance.
    """
    from ..ops.metrics import Metrics

    n_dev = mesh.shape[axis_name]
    T_pad = close.shape[-1]
    _check_time_axis(T_pad, n_dev, lookback, axis_name, "lookback")
    T = _resolve_t_real(T_pad, t_real)
    halo = lookback
    spec = P(*((None,) * (close.ndim - 1) + (axis_name,)))
    rep = P(*((None,) * (close.ndim - 1)))

    def local(close_blk):
        Tb = close_blk.shape[-1]
        gidx = jnp.arange(Tb) + jax.lax.axis_index(axis_name) * Tb
        ext = jnp.concatenate([_from_left(close_blk, halo, axis_name),
                               close_blk], axis=-1)
        prev_close = jax.lax.slice_in_dim(ext, halo - 1, halo - 1 + Tb,
                                          axis=-1)
        r = jnp.where(gidx == 0, 0.0,
                      close_blk / jnp.where(gidx == 0, 1.0, prev_close)
                      - 1.0)
        # past[t] = close[t - lookback]; chip 0's zero halo is garbage in
        # the warmup region, masked by `valid` exactly like the unsharded
        # clipped-gather fill.
        past = jax.lax.slice_in_dim(ext, 0, Tb, axis=-1)
        valid = gidx >= lookback      # rolling.valid_mask(T, lookback + 1)
        pos = jnp.where(valid, jnp.sign(close_blk - past), 0.0)
        return _pnl_metrics_local(pos, r, gidx, T, cost=cost,
                                  periods_per_year=periods_per_year,
                                  axis_name=axis_name)

    out_specs = Metrics(*(rep for _ in Metrics._fields))
    return shard_map(local, mesh=mesh, in_specs=spec,
                         out_specs=out_specs, check_vma=False)(close)


def sharded_bollinger_touch_backtest(mesh: Mesh, close, window: int,
                                     k: float, *, cost: float = 0.0,
                                     periods_per_year: int = 252,
                                     axis_name: str = TIME_AXIS,
                                     t_real: int | None = None):
    """Path-free Bollinger band-touch backtest, TIME axis sharded.

    Same blockwise rolling z-score as :func:`sharded_bollinger_backtest`
    (distributed centered cumsums + ``window``-bar halo), but the exposure
    is memoryless — ``+1`` below the lower band, ``-1`` above the upper,
    flat inside (``models.bollinger._touch_positions``) — so no state
    machine composes across chips at all: the position is a local map of
    the z block.

    ``window`` is a static int with ``window <= block length``. Returns
    scalar-per-series :class:`~..ops.metrics.Metrics`, replicated.
    Matches the single-device computation to f32 tolerance.
    """
    from ..ops.metrics import Metrics

    n_dev = mesh.shape[axis_name]
    T_pad = close.shape[-1]
    _check_time_axis(T_pad, n_dev, window, axis_name, "window")
    T = _resolve_t_real(T_pad, t_real)
    halo_w = window
    eps = 1e-12
    k_f = jnp.float32(k)
    spec = P(*((None,) * (close.ndim - 1) + (axis_name,)))
    rep = P(*((None,) * (close.ndim - 1)))

    def local(close_blk):
        Tb = close_blk.shape[-1]
        gidx = jnp.arange(Tb) + jax.lax.axis_index(axis_name) * Tb
        r = _block_returns(close_blk, gidx, axis_name)
        z = _windowed_zscore_local(close_blk, gidx, window, halo_w, T,
                                   axis_name, eps=eps)
        valid = gidx >= window - 1
        z = jnp.where(valid, z, 0.0)
        pos = jnp.where(z < -k_f, 1.0, jnp.where(z > k_f, -1.0, 0.0))
        pos = jnp.where(valid, pos, 0.0)
        return _pnl_metrics_local(pos, r, gidx, T, cost=cost,
                                  periods_per_year=periods_per_year,
                                  axis_name=axis_name)

    out_specs = Metrics(*(rep for _ in Metrics._fields))
    return shard_map(local, mesh=mesh, in_specs=spec,
                         out_specs=out_specs, check_vma=False)(close)


def sharded_keltner_backtest(mesh: Mesh, close, high, low, window: int,
                             k: float, *, cost: float = 0.0,
                             periods_per_year: int = 252,
                             axis_name: str = TIME_AXIS,
                             t_real: int | None = None):
    """End-to-end Keltner-channel mean-reversion backtest, TIME axis sharded.

    A *mixed-state* composition (``models.keltner`` semantics): the EMA
    midline is a blockwise linear scan (one ``(A, B)`` carry pair per
    chip), the ATR is a windowed mean of the true range (distributed
    cumsum + ``window``-bar halo), and the ATR-normalized deviation feeds
    the exactly-sharded band machine. The true range's lagged close rides
    a one-bar halo (first global bar uses ``high - low`` via a
    ``close``-valued pad, matching the unsharded ``true_range``).

    ``window`` is a static int with ``window <= block length``. Returns
    scalar-per-series :class:`~..ops.metrics.Metrics`, replicated.
    Matches the single-device computation to f32 tolerance.
    """
    from ..ops.metrics import Metrics

    n_dev = mesh.shape[axis_name]
    T_pad = close.shape[-1]
    _check_time_axis(T_pad, n_dev, window, axis_name, "window")
    T = _resolve_t_real(T_pad, t_real)
    alpha = jnp.float32(2.0 / (window + 1.0))
    eps = 1e-12
    k_f = jnp.float32(k)
    spec = P(*((None,) * (close.ndim - 1) + (axis_name,)))
    rep = P(*((None,) * (close.ndim - 1)))

    def local(close_blk, high_blk, low_blk):
        Tb = close_blk.shape[-1]
        gidx = jnp.arange(Tb) + jax.lax.axis_index(axis_name) * Tb
        # ONE one-bar halo exchange serves the returns and the true
        # range's lagged close (the sharded-RSI discipline).
        prev_raw = jnp.concatenate(
            [_from_left(close_blk, 1, axis_name), close_blk[..., :-1]],
            axis=-1)
        r = jnp.where(gidx == 0, 0.0,
                      close_blk / jnp.where(gidx == 0, 1.0, prev_raw) - 1.0)
        # models.keltner.true_range pads the first bar's lagged close with
        # close[0] itself (|high - close[0]| etc. still <= high - low
        # bounds the max correctly only when close[0] is inside the bar —
        # we reproduce the reference formula, not a re-derivation).
        prev_c = jnp.where(gidx == 0, close_blk, prev_raw)
        tr = jnp.maximum(high_blk - low_blk,
                         jnp.maximum(jnp.abs(high_blk - prev_c),
                                     jnp.abs(low_blk - prev_c)))
        mid = _ema_local(close_blk, gidx, alpha, axis_name)
        cs, cs_ext = _cumsum_ext(tr, window, axis_name)
        atr = _windowed_sum_blk(cs, cs_ext, gidx, window,
                                window) / jnp.float32(window)
        dev = close_blk - mid
        valid = gidx >= window - 1    # rolling.valid_mask(T, window)
        # keltner_z: zero-ATR (or warmup-NaN in the unsharded path) -> 0.
        z = jnp.where(valid & (atr > eps), dev / (atr + eps), 0.0)
        pos = _band_positions_local(z, jnp.broadcast_to(valid, z.shape),
                                    k_f, jnp.float32(0.0), axis_name)
        return _pnl_metrics_local(pos, r, gidx, T, cost=cost,
                                  periods_per_year=periods_per_year,
                                  axis_name=axis_name)

    out_specs = Metrics(*(rep for _ in Metrics._fields))
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=out_specs, check_vma=False)(
        close, high, low)


def sharded_vwap_backtest(mesh: Mesh, close, volume, window: int, k: float,
                          *, cost: float = 0.0, periods_per_year: int = 252,
                          axis_name: str = TIME_AXIS,
                          t_real: int | None = None):
    """End-to-end VWAP-deviation mean-reversion backtest, TIME axis sharded.

    The volume-weighted composition (``models.vwap`` semantics): rolling
    VWAP is two windowed sums (price x volume and volume) riding ONE
    stacked distributed cumsum + halo, the close's deviation from it is
    z-scored with the same windowed machinery
    (:func:`_windowed_zscore_local` on the derived series), and the band
    machine + PnL tail finish as in Bollinger. Warmup and zero-volume
    windows fall back to ``vwap = close`` (deviation 0), exactly like the
    unsharded NaN-guarded path.

    ``window`` is a static int with ``window <= block length``. Returns
    scalar-per-series :class:`~..ops.metrics.Metrics`, replicated.
    Matches the single-device computation to f32 tolerance.
    """
    from ..ops.metrics import Metrics

    n_dev = mesh.shape[axis_name]
    T_pad = close.shape[-1]
    _check_time_axis(T_pad, n_dev, window, axis_name, "window")
    T = _resolve_t_real(T_pad, t_real)
    halo_w = window
    eps = 1e-12
    k_f = jnp.float32(k)
    spec = P(*((None,) * (close.ndim - 1) + (axis_name,)))
    rep = P(*((None,) * (close.ndim - 1)))

    def local(close_blk, vol_blk):
        Tb = close_blk.shape[-1]
        gidx = jnp.arange(Tb) + jax.lax.axis_index(axis_name) * Tb
        r = _block_returns(close_blk, gidx, axis_name)

        # Both VWAP sums through ONE stacked _cumsum_ext.
        cs, cs_ext = _cumsum_ext(
            jnp.stack([close_blk * vol_blk, vol_blk]), halo_w, axis_name)
        s = _windowed_sum_blk(cs, cs_ext, gidx, window, halo_w)
        pv, v = s[0], s[1]
        valid_w = gidx >= window - 1
        vwap = jnp.where(valid_w & (v > eps), pv / (v + eps), close_blk)
        dev = close_blk - vwap        # 0 through warmup, like the
                                      # unsharded NaN-window fallback
        z = _windowed_zscore_local(dev, gidx, window, halo_w, T,
                                   axis_name, eps=eps)
        valid = gidx >= 2 * window - 2   # rolling.valid_mask(T, 2w - 1)
        z = jnp.where(valid, z, 0.0)
        pos = _band_positions_local(z, jnp.broadcast_to(valid, z.shape),
                                    k_f, jnp.float32(0.0), axis_name)
        return _pnl_metrics_local(pos, r, gidx, T, cost=cost,
                                  periods_per_year=periods_per_year,
                                  axis_name=axis_name)

    out_specs = Metrics(*(rep for _ in Metrics._fields))
    return shard_map(local, mesh=mesh, in_specs=(spec, spec),
                         out_specs=out_specs, check_vma=False)(close, volume)


def sharded_macd_backtest(mesh: Mesh, close, fast: int, slow: int,
                          signal: int, *, cost: float = 0.0,
                          periods_per_year: int = 252,
                          axis_name: str = TIME_AXIS,
                          t_real: int | None = None):
    """End-to-end MACD signal-line backtest, TIME axis sharded.

    Pure EMA-chain composition (``models.macd`` semantics): the close is
    demeaned by its GLOBAL first bar (one ``psum`` broadcast — the f32
    error-budget trick of the unsharded model), the fast/slow EMAs and
    the signal-line EMA are three blockwise linear scans with one
    ``(A, B)`` carry pair per chip each, and the trade is
    ``sign(macd - signal_line)`` masked for the ``slow + signal - 1``
    warmup. EMA state is O(1), so only the one-bar return halo constrains
    the block size.

    ``fast``/``slow``/``signal`` are static ints. Returns
    scalar-per-series :class:`~..ops.metrics.Metrics`, replicated.
    Parity with the single-device model is flip-aware: the unsharded path
    evaluates its EMAs with the shift-doubling ladder while the blockwise
    path uses ``associative_scan`` + carry fixup, which rounds ~1e-7
    differently — enough to flip a knife-edge ``sign(macd - sig)``
    crossing (the TRIX caveat class; the parity test bounds flips).
    """
    from ..ops.metrics import Metrics

    n_dev = mesh.shape[axis_name]
    T_pad = close.shape[-1]
    if T_pad % n_dev:
        raise ValueError(
            f"T={T_pad} not divisible by the {n_dev}-way {axis_name!r} axis")
    if fast < 1 or slow < 1 or signal < 1:
        raise ValueError(
            f"spans must be >= 1, got {fast}, {slow}, {signal}")
    T = _resolve_t_real(T_pad, t_real)
    a_fast = jnp.float32(2.0 / (fast + 1.0))
    a_slow = jnp.float32(2.0 / (slow + 1.0))
    a_sig = jnp.float32(2.0 / (signal + 1.0))
    spec = P(*((None,) * (close.ndim - 1) + (axis_name,)))
    rep = P(*((None,) * (close.ndim - 1)))

    def local(close_blk):
        Tb = close_blk.shape[-1]
        gidx = jnp.arange(Tb) + jax.lax.axis_index(axis_name) * Tb
        r = _block_returns(close_blk, gidx, axis_name)

        # Demean by the global first bar (models.macd: x = close - close[0];
        # shift-invariant in exact arithmetic, ~100x less f32 rounding).
        c0 = jax.lax.psum(
            jnp.sum(jnp.where(gidx == 0, close_blk, 0.0), axis=-1),
            axis_name)[..., None]
        x = close_blk - c0
        macd = (_ema_local(x, gidx, a_fast, axis_name)
                - _ema_local(x, gidx, a_slow, axis_name))
        sig = _ema_local(macd, gidx, a_sig, axis_name)

        warm = slow + signal - 1
        valid = gidx >= warm - 1      # rolling.valid_mask(T, warm)
        pos = jnp.where(valid, jnp.sign(macd - sig), 0.0)
        return _pnl_metrics_local(pos, r, gidx, T, cost=cost,
                                  periods_per_year=periods_per_year,
                                  axis_name=axis_name)

    out_specs = Metrics(*(rep for _ in Metrics._fields))
    return shard_map(local, mesh=mesh, in_specs=spec,
                         out_specs=out_specs, check_vma=False)(close)


def sharded_obv_backtest(mesh: Mesh, close, volume, window: int, *,
                         cost: float = 0.0, periods_per_year: int = 252,
                         axis_name: str = TIME_AXIS,
                         t_real: int | None = None):
    """End-to-end OBV-trend backtest, TIME axis sharded.

    A *double-accumulation* composition (``models.obv`` semantics): the
    OBV series is a distributed cumsum of the signed volume steps (one
    block-offset ``all_gather``), and its rolling mean is a second
    distributed cumsum over the OBV values with a ``window``-bar halo for
    the lagged read (:func:`_cumsum_ext` + :func:`_windowed_sum_blk` —
    the SMA machinery applied to a derived series). The first-bar volume
    normalizer is one ``psum`` of the chip-0 contribution.

    ``window`` is a static int with ``window <= block length``. Returns
    scalar-per-series :class:`~..ops.metrics.Metrics`, replicated.
    Matches the unsharded ``obv_trend`` strategy backtest to f32
    tolerance.
    """
    from ..ops.metrics import Metrics

    n_dev = mesh.shape[axis_name]
    T_pad = close.shape[-1]
    if T_pad % n_dev:
        raise ValueError(
            f"T={T_pad} not divisible by the {n_dev}-way {axis_name!r} axis")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    if window > T_pad // n_dev:
        raise ValueError(
            f"window={window} exceeds the {T_pad // n_dev}-bar block; the "
            "halo exchange needs the window to fit one neighbor block")
    T = _resolve_t_real(T_pad, t_real)
    halo_w = window
    spec = P(*((None,) * (close.ndim - 1) + (axis_name,)))
    rep = P(*((None,) * (close.ndim - 1)))

    def local(close_blk, vol_blk):
        Tb = close_blk.shape[-1]
        gidx = jnp.arange(Tb) + jax.lax.axis_index(axis_name) * Tb

        # ONE one-bar halo exchange serves both the returns and the OBV
        # sign step (collectives are latency-bound; XLA is not guaranteed
        # to CSE two identical ppermutes — the sharded-RSI discipline).
        prev_close = jnp.concatenate(
            [_from_left(close_blk, 1, axis_name), close_blk[..., :-1]],
            axis=-1)
        r = jnp.where(gidx == 0, 0.0,
                      close_blk / jnp.where(gidx == 0, 1.0, prev_close)
                      - 1.0)

        # First-bar volume normalizer, broadcast from the global bar 0.
        v0 = jax.lax.psum(
            jnp.sum(jnp.where(gidx == 0, vol_blk, 0.0), axis=-1),
            axis_name)[..., None]
        v = vol_blk / jnp.where(v0 == 0.0, 1.0, v0)
        # diff[0] = 0 globally (sign(0) = 0).
        step = jnp.where(gidx == 0, 0.0,
                         jnp.sign(close_blk - prev_close)) * v

        # OBV = distributed cumsum of steps; its rolling mean = a second
        # distributed cumsum with a window halo (the double accumulation).
        obv = jnp.cumsum(step, axis=-1)
        obv = obv + _exclusive_block_offset(obv[..., -1],
                                            axis_name)[..., None]
        cs, cs_ext = _cumsum_ext(obv, halo_w, axis_name)
        sma = _windowed_sum_blk(cs, cs_ext, gidx, window,
                                halo_w) / jnp.float32(window)

        valid = gidx >= window - 1   # rolling.valid_mask(T, window)
        pos = jnp.where(valid, jnp.sign(obv - sma), 0.0)
        return _pnl_metrics_local(pos, r, gidx, T, cost=cost,
                                  periods_per_year=periods_per_year,
                                  axis_name=axis_name)

    out_specs = Metrics(*(rep for _ in Metrics._fields))
    return shard_map(local, mesh=mesh, in_specs=(spec, spec),
                         out_specs=out_specs, check_vma=False)(close, volume)
