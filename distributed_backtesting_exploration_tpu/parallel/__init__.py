"""Multi-chip / multi-host parallelism: meshes, shard_map sweeps, time sharding."""
