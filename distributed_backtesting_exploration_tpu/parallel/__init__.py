"""Multi-chip / multi-host parallelism: meshes, shard_map sweeps, time sharding.

- :mod:`.sweep` — the fused jit+vmap (ticker x param) kernel, the per-job unit
  of compute.
- :mod:`.sharding` — 1-D device mesh over a worker's chips; ticker-sharded
  SPMD sweeps via ``shard_map`` (no collectives in the hot loop).
- :mod:`.timeshard` — bar-time-axis sharding: distributed cumsum and linear
  scans (the sequence-parallelism analogue for backtests).
- :mod:`.walkforward` — walk-forward optimization: ``lax.scan`` over refit
  windows with the sweep kernel nested inside.
- :mod:`.portfolio` — portfolio-level composition: per-ticker param
  selection, weighted book aggregation (one ``psum`` across a sharded
  ticker axis), correlation diagnostics.
"""

from . import portfolio, sweep, sharding, timeshard, walkforward  # noqa: F401
