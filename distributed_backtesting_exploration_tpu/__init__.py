"""TPU-native distributed backtesting framework.

A brand-new framework with the capabilities of
``brendisurfs/Distributed-Backtesting-Exploration`` (the reference), re-designed
TPU-first:

- The reference's compute slot — a ``sleep(1s)`` stub per job
  (reference ``src/worker/process.rs:13-29``) — is here a fused ``jit``+``vmap``
  JAX backtest engine running indicator construction (rolling SMA/std/OLS) and
  the strategy-signal/PnL state machine over a (ticker x parameter-set) grid.
- The reference's distribution shell — a gRPC dispatcher handing out OHLC jobs
  sized by advertised core count with peer-liveness pruning (reference
  ``src/server/main.rs``) — is here a dispatcher with per-TPU-chip batching,
  job leases with re-queue, a journaled (crash-durable) queue, and a native C++
  runtime core (scheduler / bounded queues / journal / OHLC decoder).
- Multi-chip scaling is expressed with ``jax.sharding.Mesh`` + ``shard_map``
  and XLA collectives over ICI, not sockets; multi-host job-level data
  parallelism keeps the gRPC contract over DCN.

Import alias convention used throughout the docs and tests::

    import distributed_backtesting_exploration_tpu as dbx
"""

__version__ = "0.1.0"

from . import ops, models, parallel, utils  # noqa: F401
