"""Substrate autotuner: measure the live schedule cross-product per
(kernel family, shape-bucket), prune it with the bench op model as a
prior, persist the winner (DESIGN.md "Substrate autotuner & shared
compile cache"; the TVM discipline from PAPERS.md applied to this repo's
substrate knobs).

Search space (the same knobs an operator could hand-set):

- ``epilogue``: blocked carry-scan block sizes (``scan:8..scan:128``) vs
  the ``ladder`` verification substrate (ops/fused.py round 6);
- ``table_<fam>``: in-VMEM ``inline`` rebuild vs the XLA-built ``hbm``
  stream, for the five table families in ``fused._TABLE_FAMILIES``;
- ``lanes_cap``: the validated ``DBX_LANES_CAP`` ladder (0 = kernel
  default pick);
- ``page_bars``: page-count binning granularity for paged groups
  (model-scored only — re-paging a live pool per trial would cost more
  than it could ever win; the tuned value applies at the next pool
  construction).

The PRIOR is the per-cell-bar op model bench.py's roofline uses (VPU
ladder rounds + carry fixes, MXU selection matmuls, HBM table streams):
candidates are scored by the model first and only the top
``DBX_AUTOTUNE_TRIALS`` are measured live. ``DBX_AUTOTUNE`` picks the
mode: ``0``/unset = off (hardcoded defaults, zero new work — the shipped
default), ``model`` = pick the model's argmin with no measurement (free,
deterministic — what CPU-only rounds record), ``1``/``measure`` = measure
the pruned candidates on the caller-supplied harness. Every failure path
degrades to the defaults: tuning must never fail a job.
"""

from __future__ import annotations

import math
import os

from .. import obs
from .registry import ScheduleRegistry, entry_line

_TRIALS_DEFAULT = 4
_REPS_DEFAULT = 1

# Families whose position path runs the 3-state compose machine (the
# band/latch kernels — PR 3's second ladder). Everything else pays only
# the shared metrics tail.
_COMPOSE_FAMILIES = frozenset({
    "bollinger", "bollinger_touch", "rsi", "vwap_reversion", "keltner",
    "stochastic", "donchian", "donchian_hl", "pairs"})

_EPILOGUE_CANDIDATES = ("scan:8", "scan:32", "scan:128", "ladder")
_LANES_CANDIDATES = ("0", "256", "512")
_PAGE_BARS_CANDIDATES = ("256", "512", "1024")


def autotune_mode() -> str:
    """``DBX_AUTOTUNE`` resolution (lazy, host-side): ``"off"`` (default),
    ``"model"`` (cost-model argmin, no measurement) or ``"measure"``."""
    raw = os.environ.get("DBX_AUTOTUNE", "").strip().lower()
    if raw in ("", "0", "off"):
        return "off"
    if raw == "model":
        return "model"
    return "measure"


def autotune_trials() -> int:
    """Measured candidates per (family, bucket) — the prune width."""
    try:
        return max(int(os.environ.get("DBX_AUTOTUNE_TRIALS",
                                      _TRIALS_DEFAULT)), 1)
    except ValueError:
        return _TRIALS_DEFAULT


def _table_family(family: str) -> str | None:
    from ..ops import fused
    return fused._STRATEGY_TABLE_FAMILY.get(family)


def env_pinned_keys(family: str) -> frozenset:
    """Substrate keys the operator pinned by env for ``family`` — those
    axes are excluded from the search space (env beats tuned, so their
    candidates could only measure noise)."""
    from ..ops import fused
    pinned = set()
    if os.environ.get("DBX_EPILOGUE"):
        pinned.add("epilogue")
    if os.environ.get("DBX_LANES_CAP"):
        pinned.add("lanes_cap")
    if os.environ.get("DBX_PAGE_BARS"):
        pinned.add("page_bars")
    tf = _table_family(family)
    if tf is not None and os.environ.get(fused._TABLE_FAMILIES[tf][0]):
        pinned.add(f"table_{tf}")
    return frozenset(pinned)


def default_substrates(family: str) -> dict:
    """Today's hardcoded substrate defaults as a candidate tuple — the
    INCUMBENT. Always measured alongside the pruned candidates, so a
    measured winner can never be slower than the defaults it replaces
    (the prior prunes toward the model's optimum, which is chip-shaped;
    on a platform where the model is wrong — CPU interpret mode — the
    incumbent guard keeps the tune a no-op instead of a regression)."""
    from ..ops import fused
    out = {"epilogue": "scan", "lanes_cap": "0"}
    tf = _table_family(family)
    if tf is not None:
        out[f"table_{tf}"] = fused._TABLE_FAMILIES[tf][1]
    return out


def candidate_space(family: str, *, paged: bool = False) -> list[dict]:
    """The live substrate cross-product for ``family`` (epilogue x table
    x lanes [x page_bars]), in deterministic order."""
    tf = _table_family(family)
    tables = (None,) if tf is None else ("inline", "hbm")
    pages = _PAGE_BARS_CANDIDATES if paged else (None,)
    out = []
    for epi in _EPILOGUE_CANDIDATES:
        for tab in tables:
            for lanes in _LANES_CANDIDATES:
                for pb in pages:
                    c = {"epilogue": epi, "lanes_cap": lanes}
                    if tab is not None:
                        c[f"table_{tf}"] = tab
                    if pb is not None:
                        c["page_bars"] = pb
                    out.append(c)
    return out


def modeled_cost(family: str, substrates: dict, *, n_bars: int,
                 n_combos: int) -> float:
    """Relative modeled cost per cell-bar of one substrate tuple — the
    SAME accounting bench.py's roofline model uses (PR 3/5 numbers:
    metrics tail = 26 reduction/PnL ops + 2 ladders x 2 ops/round [+7
    carry fixes under scan]; band/latch compose = 9 ops/round [+2]; hbm
    tables stream 4*W bytes/cell-bar amortized over P lanes, inline
    rebuilds cost ~2 VPU ops/cell-bar; wider lane blocks amortize the
    per-cell fixed overhead). A PRIOR for pruning, not gospel — the
    measured trials rank the survivors."""
    from ..ops import fused

    T_pad = -(-max(int(n_bars), 8) // 8) * 8
    epi = substrates.get("epilogue", "scan")
    if epi == "ladder":
        rounds = max(math.ceil(math.log2(max(T_pad, 2))), 1)
        tail = 26 + 4 * rounds
        compose = 9 * rounds
    else:
        try:
            blk = fused._scan_block(T_pad, epi)
        except (ValueError, AttributeError):
            blk = 8
        rounds = max(math.ceil(math.log2(max(min(blk, T_pad), 2))), 1)
        tail = 26 + 4 * rounds + 7
        compose = 9 * rounds + 2
    vpu = 24.0 + tail   # ~24 signal/PnL ops per cell-bar outside the tail
    if family in _COMPOSE_FAMILIES:
        vpu += compose
    tf = _table_family(family)
    if tf is not None:
        w_pad = 8.0                     # representative distinct-window pad
        p_pad = -(-max(int(n_combos), 1) // 128) * 128
        if substrates.get(f"table_{tf}") == "hbm":
            # HBM stream (bytes -> VPU-op equivalents at the v5e byte/op
            # ratio the bench model uses) amortized over the param lanes.
            vpu += 4.0 * w_pad * 4 / p_pad
        else:
            vpu += 2.0                  # in-kernel scratch rebuild
    try:
        lanes = int(substrates.get("lanes_cap", "0") or 0)
    except ValueError:
        lanes = 0
    eff_lanes = lanes if lanes else 256
    vpu *= 1.0 + 16.0 / eff_lanes       # per-cell fixed overhead share
    pb = substrates.get("page_bars")
    if pb:
        try:
            vpu *= 1.0 + float(pb) / (2.0 * max(int(n_bars), 1))
        except ValueError:
            pass
    return vpu


class Autotuner:
    """First-contact tuner: consult the prior, measure the survivors,
    persist the winner in the schedule registry."""

    def __init__(self, schedule: ScheduleRegistry,
                 registry: "obs.Registry | None" = None):
        self.schedule = schedule
        self._obs = registry or obs.get_registry()
        self._c_trials: dict[str, obs.registry.Counter] = {}

    def _count_trials(self, family: str, n: int) -> None:
        c = self._c_trials.get(family)
        if c is None:
            # family is bounded: the fused strategy registry's key set.
            c = self._c_trials[family] = self._obs.counter(
                "dbx_autotune_trials_total",
                help="live autotune measurements run, by kernel family",
                family=family)
        c.inc(n)

    def tune(self, family: str, bucket: str, platform: str, *,
             n_bars: int, n_combos: int, measure=None,
             paged: bool = False) -> dict | None:
        """Tune one (family, bucket, platform) and persist the winner.

        ``measure(substrates) -> seconds`` runs the family's sweep under
        the candidate substrate tuple (the caller owns shapes and data);
        None or mode="model" picks the cost model's argmin without
        measuring. Returns the winning substrate dict, or None when the
        mode is off / everything failed — the caller then serves today's
        defaults (degradation ladder: tuning never fails a job)."""
        mode = autotune_mode()
        if mode == "off":
            return None
        cands = candidate_space(family, paged=paged)
        pinned = env_pinned_keys(family)
        if pinned:
            # Env knobs beat tuned schedules in every resolver, so a
            # pinned axis would make its candidates measure the SAME
            # substrate — the "winner" value would be timing noise, then
            # gossip fleet-wide as a measured entry. Drop pinned axes
            # from the search (and from the recorded schedule).
            cands = [{k: v for k, v in c.items() if k not in pinned}
                     for c in cands]
            seen: set = set()
            cands = [c for c in cands
                     if c and entry_line(c) not in seen
                     and not seen.add(entry_line(c))]
            if not cands:
                return None      # everything pinned: nothing to tune
        scored = sorted(
            cands,
            key=lambda c: (modeled_cost(family, c, n_bars=n_bars,
                                        n_combos=n_combos),
                           entry_line(c)))
        if mode == "model" or measure is None:
            winner, best_us, trials = scored[0], None, 0
        else:
            winner, best_us, trials = self._measure(
                family, self._pruned(family, scored, autotune_trials(),
                                     pinned=pinned),
                measure)
            if winner is None:
                return None
        if pinned:
            # The incumbent candidate carries every knob; pinned axes
            # must not be recorded as if they had been searched.
            winner = {k: v for k, v in winner.items() if k not in pinned}
            if not winner:
                return None
        self.schedule.record(family, bucket, platform, winner,
                             trials=trials, best_us=best_us)
        return winner

    @staticmethod
    def _pruned(family: str, scored: list[dict], n: int,
                pinned: frozenset = frozenset()) -> list[dict]:
        """The measured candidate set: the incumbent defaults first (the
        winner can never regress past them), then the model's best
        candidate PER EPILOGUE VALUE (diversity — a prior that is wrong
        for this platform must not prune the whole truth away), then the
        remaining model order up to ``n`` beyond the incumbent."""
        out: list[dict] = []
        seen: set[str] = set()

        def add(c: dict) -> None:
            k = entry_line(c)
            if k not in seen:
                seen.add(k)
                out.append(c)

        add({k: v for k, v in default_substrates(family).items()
             if k not in pinned})
        best_per: dict[str, dict] = {}
        for c in scored:
            best_per.setdefault(c.get("epilogue", ""), c)
        for c in best_per.values():
            add(c)
        for c in scored:
            if len(out) > max(n, len(best_per)):
                break
            add(c)
        return out[: max(n, len(best_per)) + 1]

    def _measure(self, family: str, cands: list[dict], measure):
        reps = _REPS_DEFAULT
        try:
            reps = max(int(os.environ.get("DBX_AUTOTUNE_REPS", reps)), 1)
        except ValueError:
            pass
        best, best_s, ran = None, float("inf"), 0
        for c in cands:
            try:
                s = min(float(measure(dict(c))) for _ in range(reps))
            except Exception:
                continue    # a failing candidate is just not the winner
            ran += 1
            if s < best_s:
                best, best_s = c, s
        self._count_trials(family, ran)
        if best is None:
            return None, None, 0
        return best, round(best_s * 1e6, 3), ran
