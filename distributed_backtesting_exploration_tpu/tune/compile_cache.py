"""Fleet-shared persistent XLA compile cache (runtime half of tune/).

Two cooperating layers:

1. **Local persistent cache** — :func:`configure` points JAX's persistent
   compilation cache at a directory and applies the best-effort threshold
   options (names have drifted across jax generations — kept in ONE place;
   tests/conftest.py imports this instead of carrying its own copy, and
   dispatcher/worker mains call it at startup). Every cache entry is keyed
   by jax's own HLO/config hash, so re-runs of unchanged kernels skip
   straight to execution.

2. **Fleet exchange** — the dispatcher hosts a byte-bounded
   :class:`CompileStore` of cache entries and two RPCs ride the PR-5
   content-addressing discipline: workers ``OfferCompiled`` entries their
   local compiles just wrote, and ``FetchCompiled`` the listing + any
   entries they lack, installing them into their local cache dir BEFORE
   jax looks — a cold worker's first sweep then hits the persistent cache
   and skips compilation entirely when any peer has compiled that kernel
   before. :class:`CacheSync` is the worker-side scanner/installer.

Wire keys are ``blake2b-128(file name | jax version | backend platform)``:
the file name already IS jax's content hash of (serialized HLO, compile
options — which fold the substrate tuple via the jit static args), and
folding the jax version + platform keeps entries from one generation or
chip type from ever being installed into another's cache. Payloads are
opaque bytes; a corrupt or irrelevant entry is at worst an unused file
jax ignores (its own integrity checks re-compile on mismatch) — the
degradation ladder never fails a job.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import threading

from .. import obs

_DEFAULT_STORE_MB = 256
# Entries larger than this never cross the wire (a single pathological
# executable must not evict the whole fleet store).
_MAX_ENTRY_BYTES = 64 * 1024 * 1024


def compile_store_max_bytes() -> int:
    """``DBX_COMPILE_CACHE_MB`` store bound (lazy read, default 256 MB)."""
    return int(float(os.environ.get("DBX_COMPILE_CACHE_MB",
                                    _DEFAULT_STORE_MB)) * 1024 * 1024)


def default_cache_dir() -> str:
    """The runtime cache directory: ``DBX_COMPILE_CACHE_DIR`` or a stable
    per-user tempdir path (stable so restarts re-hit their own entries)."""
    d = os.environ.get("DBX_COMPILE_CACHE_DIR")
    if d:
        return d
    return os.path.join(tempfile.gettempdir(), "dbx_jax_cache")


def configure(path: str | None = None, *,
              min_compile_time_s: float = 0.5,
              min_entry_bytes: int = 0) -> str | None:
    """Point JAX's persistent compilation cache at ``path`` (default
    :func:`default_cache_dir`). THE one implementation of the threshold
    best-effort (conftest + dispatcher + worker all route here). Returns
    the configured path, or None when jax itself is unusable — callers
    degrade to uncached compiles, never fail."""
    path = path or default_cache_dir()
    try:
        import jax
    except Exception:   # pragma: no cover - jax is baked into the image
        return None
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:
        return None
    # Threshold configs are best-effort — option names have drifted
    # across jax generations (the reason this lives in ONE module).
    for opt, val in (
            ("jax_persistent_cache_min_compile_time_secs",
             min_compile_time_s),
            ("jax_persistent_cache_min_entry_size_bytes",
             min_entry_bytes)):
        try:
            jax.config.update(opt, val)
        except Exception:  # pragma: no cover - older/newer jax
            pass
    # A mid-process dir switch (bench's second-worker A/B) must drop the
    # old backend-held cache handle; best-effort across jax generations.
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except Exception:
        pass
    return path


def attach(registry: "obs.Registry | None" = None) -> "CacheSync | None":
    """A :class:`CacheSync` on the jax cache dir ALREADY configured in
    this process (a test harness's or operator's choice is respected),
    configuring the default dir only when none is set. None when jax is
    unusable — the worker then simply runs uncached."""
    path = None
    try:
        import jax
        path = getattr(jax.config, "jax_compilation_cache_dir", None)
    except Exception:   # pragma: no cover - jax is baked into the image
        return None
    if not path:
        path = configure()
    if not path:
        return None
    return CacheSync(path, registry=registry)


def _runtime_tag() -> str:
    try:
        import jax
        version = jax.__version__
        platform = jax.default_backend()
    except Exception:   # pragma: no cover - jax is baked into the image
        version, platform = "nojax", "none"
    return f"{version}|{platform}"


def entry_key(name: str, runtime_tag: str | None = None) -> str:
    """Fleet wire key of one cache entry: blake2b-128 over the cache file
    name (jax's own hash of the serialized HLO + compile options, which
    already fold the substrate tuple through the jit static args) plus
    the jax version and backend platform — entries never travel across
    generations or chip types."""
    tag = _runtime_tag() if runtime_tag is None else runtime_tag
    return hashlib.blake2b(f"{name}|{tag}".encode(),
                           digest_size=16).hexdigest()


class CompileStore:
    """Dispatcher-side bounded LRU of fleet compile-cache entries.

    Values are ``(name, payload)`` — the worker needs the original file
    name to install under (jax looks entries up by name). Thread-safe:
    Offer/Fetch handlers run on the gRPC pool.
    """

    def __init__(self, max_bytes: int | None = None,
                 registry: "obs.Registry | None" = None):
        from ..rpc.panel_store import ByteLRU

        self._lock = threading.Lock()
        self._lru = ByteLRU(compile_store_max_bytes()
                            if max_bytes is None else int(max_bytes),
                            nbytes_of=lambda v: len(v[1]))
        reg = registry or obs.get_registry()
        self._c_offers = reg.counter(
            "dbx_compile_offers_total",
            help="compile-cache entries accepted from workers")
        self._c_fetch = {
            outcome: reg.counter(
                "dbx_compile_fetches_total",
                help="FetchCompiled entry requests served, by outcome",
                outcome=outcome)
            for outcome in ("hit", "gone")}

    def offer(self, key: str, name: str, payload: bytes) -> bool:
        if not key or not name or not payload \
                or len(payload) > _MAX_ENTRY_BYTES:
            return False
        with self._lock:
            if key in self._lru:
                return False
            self._lru.put(key, (name, payload))
        self._c_offers.inc()
        return True

    def get(self, key: str):
        """``(name, payload)`` or None (evicted/never offered)."""
        with self._lock:
            v = self._lru.get(key)
        self._c_fetch["hit" if v is not None else "gone"].inc()
        return v

    def keys(self) -> list[str]:
        with self._lock:
            return list(self._lru._entries.keys())

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._lru), "bytes": self._lru.bytes,
                    "evictions": self._lru.evictions,
                    "max_bytes": self._lru.max_bytes}


class CacheSync:
    """Worker-side cache-dir scanner / installer (control thread only).

    Accounting contract (the ``dbx_compile_cache_{hits,misses}_total``
    families):

    - ``hits{source="local"}``  — entries already on local disk when the
      sync attached (the persistent cache pre-warmed across restarts);
    - ``misses{source="local"}`` — new files appearing from THIS process's
      own compiles (each one is a compile wall actually paid locally);
    - ``hits{source="fleet"}``  — entries installed from a peer via the
      dispatcher (a compile wall skipped entirely);
    - ``misses{source="fleet"}`` — entries requested from the dispatcher
      that came back unservable (evicted or never offered).
    """

    def __init__(self, cache_dir: str | None = None,
                 registry: "obs.Registry | None" = None,
                 runtime_tag: str | None = None):
        self.cache_dir = cache_dir or default_cache_dir()
        self._tag = _runtime_tag() if runtime_tag is None else runtime_tag
        self._key_to_name: dict[str, str] = {}
        self._seen_names: set[str] = set()
        # Keys whose entries this worker REFUSED (foreign jax version /
        # platform): remembered so missing() stops re-requesting them —
        # a mixed-generation fleet must not re-download the foreign
        # entry set on every sync tick, forever.
        self._rejected_keys: set[str] = set()
        reg = registry or obs.get_registry()
        self._c = {
            (kind, source): reg.counter(
                f"dbx_compile_cache_{kind}_total",
                help=("persistent-compile-cache entries, by source "
                      "(local = this worker's own disk/compiles, fleet = "
                      "exchanged through the dispatcher)"),
                source=source)
            for kind in ("hits", "misses")
            for source in ("local", "fleet")}
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
        except OSError:
            pass
        # Pre-warmed entries (e.g. a restart onto its own cache dir):
        # local hits — compiles this process will never pay.
        for name, _ in self._scan():
            self._register(name)
            self._c[("hits", "local")].inc()

    def _scan(self):
        try:
            with os.scandir(self.cache_dir) as it:
                # Dot-files are never cache entries: our own interrupted
                # .dbx_fetch_* temps (and other writers' partials) must
                # not be counted as local compiles or offered under
                # names no peer's jax would ever look up.
                ents = [(e.name, e.stat().st_size) for e in it
                        if e.is_file() and not e.name.startswith(".")]
        except OSError:
            return []
        return sorted(ents)

    def _register(self, name: str) -> str:
        key = entry_key(name, self._tag)
        self._key_to_name[key] = name
        self._seen_names.add(name)
        return key

    def poll_new(self) -> list[tuple[str, str, bytes]]:
        """New cache files since the last poll — local compiles this
        process just paid for — as ``(key, name, payload)`` offers.
        Counted as local misses (the wall was actually spent here)."""
        out = []
        for name, size in self._scan():
            if name in self._seen_names or size > _MAX_ENTRY_BYTES:
                continue
            try:
                with open(os.path.join(self.cache_dir, name), "rb") as fh:
                    payload = fh.read()
            except OSError:
                continue
            key = self._register(name)
            self._c[("misses", "local")].inc()
            out.append((key, name, payload))
        return out

    def unmark(self, entries) -> None:
        """Forget ``(key, name, payload)`` offers whose RPC never reached
        the dispatcher, so the next poll re-offers them (the compile-leg
        twin of the schedule registry's ``remark_dirty``) — a transient
        dispatcher blip must not permanently drop a paid compile wall
        from fleet sharing."""
        for key, name, _payload in entries:
            self._seen_names.discard(name)
            self._key_to_name.pop(key, None)

    def missing(self, known_keys) -> list[str]:
        """The subset of a fleet listing this worker does not hold and
        has not previously refused (foreign runtime tag)."""
        return [k for k in known_keys
                if k and k not in self._key_to_name
                and k not in self._rejected_keys]

    def install(self, entries) -> int:
        """Write fetched ``(key, name, payload)`` entries into the local
        cache dir (atomic tmp+rename; jax picks them up by name on its
        next lookup). Returns entries installed — each one a compile
        skipped: ``hits{source="fleet"}``."""
        n = 0
        for key, name, payload in entries:
            if name in self._seen_names:
                continue
            if (not name or not payload or os.sep in name
                    or name != os.path.basename(name)
                    or name.startswith(".")
                    or key != entry_key(name, self._tag)):
                # Malformed, or a peer on another jax generation / chip
                # type: useless (and possibly harmful) here. Remember
                # the refusal so missing() never re-requests it.
                if len(self._rejected_keys) > 1 << 16:
                    self._rejected_keys.clear()
                self._rejected_keys.add(key)
                continue
            dest = os.path.join(self.cache_dir, name)
            try:
                fd, tmp = tempfile.mkstemp(dir=self.cache_dir,
                                           prefix=".dbx_fetch_")
                with os.fdopen(fd, "wb") as fh:
                    fh.write(payload)
                os.replace(tmp, dest)
            except OSError:
                continue
            self._register(name)
            self._c[("hits", "fleet")].inc()
            n += 1
        return n

    def count_fleet_misses(self, n: int) -> None:
        if n > 0:
            self._c[("misses", "fleet")].inc(n)
