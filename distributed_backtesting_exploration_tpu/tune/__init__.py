"""tune/: substrate autotuner + schedule registry + fleet compile cache.

The two halves of ROADMAP item 4 (DESIGN.md "Substrate autotuner & shared
compile cache"):

- :mod:`.registry` / :mod:`.autotune` — measure the substrate schedule
  cross-product once per (kernel family, shape-bucket, platform), persist
  winners in a journal-style registry (``DBX_SCHEDULE_DIR``), gossip them
  through the dispatcher so the Nth worker inherits the first worker's
  tuning (``JobsRequest.schedule_json`` up, ``StatsReply.schedule_json``
  down). Consumption is ops/fused.py's resolution chain: explicit arg >
  env > tuned schedule > hardcoded default.
- :mod:`.compile_cache` — JAX's persistent compilation cache as a
  first-class runtime module (one home for the version-drift best-effort
  conftest used to carry), plus the dispatcher-served entry exchange
  (``FetchCompiled``/``OfferCompiled``) that lets a cold worker skip a
  compile any peer already paid for.
"""

from .autotune import (Autotuner, autotune_mode, autotune_trials,
                       candidate_space, modeled_cost)
from .compile_cache import (CacheSync, CompileStore, attach, configure,
                            default_cache_dir, entry_key)
from .registry import (ScheduleRegistry, entry_line, schedule_dir,
                       shape_bucket)

__all__ = [
    "Autotuner", "CacheSync", "CompileStore", "ScheduleRegistry",
    "attach", "autotune_mode", "autotune_trials", "candidate_space",
    "configure", "default_cache_dir", "entry_key", "entry_line",
    "modeled_cost", "schedule_dir", "shape_bucket",
]
