"""Versioned, journal-style substrate schedule registry (the autotuner's
persistence half — DESIGN.md "Substrate autotuner & shared compile cache").

Every performance substrate in the fleet used to be a hand-set env knob
(``DBX_EPILOGUE`` scan block, per-family ``DBX_*_TABLE``, ``DBX_LANES_CAP``,
``DBX_PAGE_BARS``). The TVM discipline (PAPERS.md) is: measure the schedule
cross-product once per shape class, persist the winner, serve it everywhere.
This module is the "persist" and "everywhere" parts:

- an entry maps ``(kernel family, shape-bucket, backend platform)`` to a
  tuned substrate tuple (``{"epilogue": "scan:32", "table_sma": "inline",
  "lanes_cap": "256", ...}``) plus its measurement provenance
  (trial count, best wall);
- persistence is a JSONL *journal* under ``DBX_SCHEDULE_DIR`` (file
  ``schedule.v1.jsonl``): appends only, later entries win on replay, a
  corrupt line is skipped AND counted, never fatal. The serialization is
  canonical (sorted keys, fixed separators, no timestamps), so the same
  measurements always produce the same registry bytes — restart- and
  diff-stable by construction;
- ``to_json``/``merge_json`` are the fleet wire format: workers push
  newly-tuned entries up on ``JobsRequest.schedule_json``; the dispatcher
  merges them into its fleet registry and ships the union back on
  ``StatsReply.schedule_json`` — the Nth worker inherits the first
  worker's tuning without re-measuring. Merge conflicts resolve
  deterministically (more trials wins; ties by canonical line order), so
  every peer converges to the same registry regardless of arrival order.

The CONSUMPTION side lives in :mod:`..ops.fused` (the tuned-schedule
resolution layer: explicit arg > env > tuned schedule > hardcoded default)
and :mod:`..rpc.compute` (group-submit consultation). Nothing here ever
raises into a job: a missing/corrupt/unwritable registry degrades to
today's hardcoded defaults.
"""

from __future__ import annotations

import json
import os
import threading

from .. import obs

SCHEMA_VERSION = 1
_FILENAME = f"schedule.v{SCHEMA_VERSION}.jsonl"

# Bound on journal entries queued while the file is unopenable (kept for
# the next flush's retry; beyond this the oldest drop — an unwritable
# path already degrades to memory-only, the queue must stay bounded).
_MAX_PENDING_IO = 1024

# The substrate keys a schedule entry may carry. Unknown keys are dropped
# at record/merge time so a newer peer's extended schema cannot poison an
# older consumer's resolution chain (it simply will not see the new knob).
KNOWN_SUBSTRATES = frozenset(
    {"epilogue", "lanes_cap", "page_bars"}
    | {f"table_{fam}" for fam in ("sma", "boll", "mom", "don", "obv")})

# Shape buckets are CLAMPED power-of-two rails so the set of possible
# bucket strings is finite — bounded enough to ride a metric label
# (dbxlint obs-cardinality: raw dims would mint one series per shape).
_T_BUCKETS = (64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384, 32768,
              65536)
_P_BUCKETS = (128, 256, 512, 1024, 2048, 4096)


def _rail(v: int, rail: tuple) -> int:
    for r in rail:
        if v <= r:
            return r
    return rail[-1]


def shape_bucket(n_bars: int, n_combos: int) -> str:
    """Bounded shape-bucket label for ``(T, P)``: each dimension rounds up
    to a clamped power-of-two rail (``t64..t65536`` x ``p128..p4096`` —
    at most ``len(_T_BUCKETS) * len(_P_BUCKETS)`` distinct strings ever).
    Kernels compile and tune per padded shape class, not per exact shape,
    so this is also the right granularity for schedule reuse."""
    return (f"t{_rail(max(int(n_bars), 1), _T_BUCKETS)}"
            f"_p{_rail(max(int(n_combos), 1), _P_BUCKETS)}")


def schedule_dir() -> str | None:
    """``DBX_SCHEDULE_DIR`` (read lazily, never at import): the directory
    holding the schedule journal, or None = in-memory only."""
    return os.environ.get("DBX_SCHEDULE_DIR") or None


def entry_line(entry: dict) -> str:
    """THE canonical serialization of one registry entry — a pure function
    of its content (sorted keys, fixed separators, no timestamps), so
    identical measurements produce identical registry bytes everywhere.
    Both the journal file and the fleet wire format are built from it."""
    return json.dumps(entry, sort_keys=True, separators=(",", ":"))


def _valid_entry(e) -> bool:
    if not isinstance(e, dict) or e.get("v") != SCHEMA_VERSION:
        return False
    if not (isinstance(e.get("family"), str)
            and isinstance(e.get("bucket"), str)
            and isinstance(e.get("platform"), str)):
        return False
    subs = e.get("substrates")
    if not isinstance(subs, dict) or not subs:
        return False
    return all(isinstance(k, str) and isinstance(v, str)
               for k, v in subs.items())


class ScheduleRegistry:
    """Thread-safe tuned-schedule map with an append-only JSONL journal.

    ``path`` is the journal file (None = memory-only). All file IO is
    best-effort: an unreadable journal loads what it can (corrupt lines
    counted in ``corrupt_entries``), an unwritable one degrades to
    memory-only — tuning must never fail a job.
    """

    def __init__(self, path: str | None = None,
                 registry: "obs.Registry | None" = None,
                 scope: str = "local"):
        self._lock = threading.Lock()
        # Journal IO never runs under ``_lock`` (dbxlint lock-blocking:
        # a slow append — NFS, a full disk retry — would stall every
        # lookup() on the worker submit hot path and every gossip
        # merge). Mutations enqueue their entry on ``_pending_io``
        # under ``_lock``; ``_flush_io`` drains it to the file under
        # the dedicated leaf ``_io_lock`` — which both serializes
        # appends and preserves journal order == mutation order (the
        # queue is filled in ``_lock`` order), so replay's later-wins
        # semantics still reconstruct the in-memory state.
        self._io_lock = threading.Lock()
        self._pending_io: list[dict] = []
        self.path = path
        self._entries: dict[tuple, dict] = {}
        self._dirty: set[tuple] = set()
        self.corrupt_entries = 0
        self.io_errors = 0
        reg = registry or obs.get_registry()
        # gauge_fn: the entry count is read at scrape time, so every
        # surface (/metrics, /stats.json, GetStats obs_json) sees the
        # live registry size without a write hook per record(). ``scope``
        # ({"local", "fleet"} — bounded) keeps a worker's registry and an
        # in-process dispatcher's fleet registry on separate series.
        reg.gauge_fn("dbx_schedule_registry_entries", lambda: len(self),
                     help="tuned (family, shape-bucket, platform) entries "
                          "resident in the schedule registry",
                     scope=scope)
        self._c_corrupt = reg.counter(
            "dbx_schedule_corrupt_entries_total",
            help="schedule journal/wire entries skipped as corrupt")
        if path:
            self._load(path)

    @classmethod
    def open_default(cls, registry: "obs.Registry | None" = None,
                     scope: str = "local") -> "ScheduleRegistry":
        """Registry at ``DBX_SCHEDULE_DIR`` (journal created lazily on the
        first record), or memory-only when the knob is unset."""
        d = schedule_dir()
        path = os.path.join(d, _FILENAME) if d else None
        return cls(path, registry=registry, scope=scope)

    # -- journal -----------------------------------------------------------

    def _load(self, path: str) -> None:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                lines = fh.read().splitlines()
        except FileNotFoundError:
            return
        except OSError:
            self.io_errors += 1
            return
        entries: list[dict] = []
        bad = 0
        for line in lines:
            if not line.strip():
                continue
            try:
                e = json.loads(line)
            except ValueError:
                e = None
            if e is None or not _valid_entry(e):
                bad += 1
                continue
            # Journal replay: later entries win (append-only semantics).
            entries.append(self._scrub(e))
        if bad:
            self.corrupt_entries += bad
            self._c_corrupt.inc(bad)
        # ONE lock hold for the whole replay merge (__init__-only today,
        # but a future reload path racing a gossip merge must not
        # interleave: a per-line lock would let an older journal line
        # land AFTER — and silently overwrite — a fresher merged entry).
        with self._lock:
            for e in entries:
                self._entries[self._key(e)] = e

    def _open_journal(self):
        """Open the journal for appending, OUTSIDE every lock; None on
        failure (memory-only degradation, never a raise)."""
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            return open(self.path, "a", encoding="utf-8")
        except OSError:
            self.io_errors += 1
            return None

    def _flush_io(self) -> None:
        """Drain ``_pending_io`` to the journal (constructor docstring:
        called after ``_lock`` is RELEASED, never nested inside it). A
        concurrent flusher holding ``_io_lock`` will drain this
        thread's enqueued entries too — the queue swap under ``_lock``
        is the only moment both locks are held (io -> lock order,
        acquisition-cheap on both sides). The file handle lives for ONE
        flush (O_APPEND, writes serialized by ``_io_lock``): no fd
        outlives the call, matching the pre-round-12 per-append cost
        profile without its under-lock open."""
        if not self.path:
            return   # memory-only registry: nothing is ever enqueued
        with self._lock:
            if not self._pending_io:
                return
        fh = self._open_journal()
        if fh is None:
            # Transient open failure: keep the queue for the next
            # flush's retry — clearing here would drop entries OTHER
            # threads just enqueued whose own flush would succeed.
            # Bounded (oldest dropped) so a permanently unwritable
            # path cannot grow it without limit.
            with self._lock:
                if len(self._pending_io) > _MAX_PENDING_IO:
                    del self._pending_io[:-_MAX_PENDING_IO]
            return
        failed = 0
        try:
            with self._io_lock:
                while True:
                    with self._lock:
                        if not self._pending_io:
                            break
                        batch = self._pending_io[:]
                        self._pending_io.clear()
                    try:
                        for e in batch:
                            fh.write(entry_line(e) + "\n")
                        fh.flush()
                    except OSError:
                        failed += 1
        finally:
            fh.close()
        if failed:
            # Counted outside both locks (io_errors is a best-effort
            # diagnostic, never guarded state): degrade, don't raise.
            self.io_errors += failed

    # -- core map ----------------------------------------------------------

    @staticmethod
    def _key(e: dict) -> tuple:
        return (e["family"], e["bucket"], e["platform"])

    @staticmethod
    def _scrub(e: dict) -> dict:
        subs = {k: v for k, v in e["substrates"].items()
                if k in KNOWN_SUBSTRATES}
        return {"v": SCHEMA_VERSION, "family": e["family"],
                "bucket": e["bucket"], "platform": e["platform"],
                "substrates": subs,
                "trials": int(e.get("trials", 0)),
                "best_us": (float(e["best_us"])
                            if e.get("best_us") is not None else None)}

    def lookup(self, family: str, bucket: str, platform: str
               ) -> dict | None:
        """The tuned substrate dict for the key, or None (copy — callers
        may not mutate registry state)."""
        with self._lock:
            e = self._entries.get((family, bucket, platform))
            return dict(e["substrates"]) if e else None

    def record(self, family: str, bucket: str, platform: str,
               substrates: dict, *, trials: int = 0,
               best_us: float | None = None) -> bool:
        """Persist a tuned winner (journal append + memory). Returns False
        when an identical entry is already resident (no journal growth on
        re-tuning the same answer)."""
        e = self._scrub({"family": family, "bucket": bucket,
                         "platform": platform,
                         "substrates": {k: str(v)
                                        for k, v in substrates.items()},
                         "trials": trials, "best_us": best_us})
        if not _valid_entry(e):
            return False
        with self._lock:
            key = self._key(e)
            if self._entries.get(key) == e:
                return False
            self._entries[key] = e
            self._dirty.add(key)
            if self.path:
                self._pending_io.append(e)
        self._flush_io()
        return True

    def entries(self) -> list[dict]:
        """Every resident entry in canonical (sorted-line) order."""
        with self._lock:
            out = [dict(e, substrates=dict(e["substrates"]))
                   for e in self._entries.values()]
        return sorted(out, key=entry_line)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- fleet exchange ----------------------------------------------------

    def to_json(self) -> str:
        """Canonical wire form of the whole registry (deterministic: the
        same entries serialize to the same bytes on every peer)."""
        return "[" + ",".join(entry_line(e) for e in self.entries()) + "]"

    def take_dirty_json(self) -> str:
        """Entries recorded/adopted since the last take, as wire JSON —
        empty string when clean (the worker's JobsRequest push: a clean
        poll adds zero wire bytes)."""
        with self._lock:
            if not self._dirty:
                return ""
            dirty = [self._entries[k] for k in self._dirty
                     if k in self._entries]
            self._dirty.clear()
        return "[" + ",".join(entry_line(e)
                              for e in sorted(dirty, key=entry_line)) + "]"

    def remark_dirty(self, payload: str) -> None:
        """Re-mark previously-taken wire entries as dirty (the push-retry
        path: a poll that drained ``take_dirty_json`` but never reached
        the dispatcher must not lose its entries from the gossip)."""
        try:
            items = json.loads(payload)
        except ValueError:
            return
        if not isinstance(items, list):
            return
        with self._lock:
            for e in items:
                if _valid_entry(e):
                    key = self._key(e)
                    if key in self._entries:
                        self._dirty.add(key)

    def merge_json(self, payload: str, *, mark_dirty: bool = False) -> int:
        """Merge a peer's wire JSON; returns entries adopted. Malformed
        payloads/entries are skipped and counted — a hostile or
        version-skewed peer can at worst teach nothing."""
        if not payload:
            return 0
        try:
            items = json.loads(payload)
        except ValueError:
            items = None
        if not isinstance(items, list):
            self.corrupt_entries += 1
            self._c_corrupt.inc()
            return 0
        adopted = 0
        for e in items:
            if not _valid_entry(e):
                self.corrupt_entries += 1
                self._c_corrupt.inc()
                continue
            if self._adopt(self._scrub(e), mark_dirty=mark_dirty):
                adopted += 1
        return adopted

    def _adopt(self, e: dict, *, mark_dirty: bool) -> bool:
        """Deterministic conflict resolution: an incoming entry replaces
        the resident one iff it measured MORE trials, or ties and sorts
        earlier in canonical line order — every peer applying the same
        rule converges to the same registry regardless of gossip order."""
        with self._lock:
            key = self._key(e)
            cur = self._entries.get(key)
            if cur is not None:
                if cur == e:
                    return False
                if e["trials"] < cur["trials"]:
                    return False
                if (e["trials"] == cur["trials"]
                        and entry_line(e) >= entry_line(cur)):
                    return False
            self._entries[key] = e
            if mark_dirty:
                self._dirty.add(key)
            if self.path:
                self._pending_io.append(e)
        self._flush_io()
        return True
