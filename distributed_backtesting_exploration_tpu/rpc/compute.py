"""Worker compute backends: the slot the reference filled with a sleep.

The reference's worker pushes each job batch to an OS thread that sleeps one
second per job (reference ``src/worker/process.rs:13-29``, acknowledged as a
stub in reference ``README.md:84``). Here the same seam — a backend consuming
job batches and yielding completions — is filled by the fused JAX sweep
kernel; fake backends preserve the seam for control-plane tests exactly as
the stub's isolation suggested (SURVEY.md §4).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Iterable, NamedTuple, Protocol

import numpy as np

from . import backtesting_pb2 as pb
from . import wire
from .. import obs
from ..obs import flight as obs_flight
from ..parallel._shardmap_compat import shard_map
from ..utils import data as data_mod

log = logging.getLogger("dbx.compute")

_DEFAULT_CACHE_MB = 256


def cache_max_bytes() -> int:
    """Worker panel-cache budget (per level), read lazily — import-time
    env capture would pin the knob before tests/operators can set it."""
    return int(float(os.environ.get("DBX_PANEL_CACHE_MB",
                                    _DEFAULT_CACHE_MB)) * 1024 * 1024)


class PanelCache:
    """Two-level digest-keyed panel cache (dispatch by digest, worker side).

    The dispatcher content-addresses every panel (``JobSpec.panel_digest``)
    and, once a worker generation has received the bytes, ships
    digest-only jobs. This cache is what makes that hit cheap end to end:

    - **host level**: decoded :class:`~..utils.data.OHLCV` panels — a hit
      skips the wire decode entirely;
    - **device level**: the panel's stacked ``(5, T)`` field block already
      resident on the accelerator — a hit additionally skips the
      host->device transfer (group stacking then runs device-side);
    - **page level** (:attr:`pages`, ragged paged batching): field data as
      fixed-size T-pages in one device pool keyed by page CONTENT — an
      append-extended panel reuses all of its base's full pages and
      overlapping histories share pages across digests, where the block
      level would duplicate the whole ``(5, T)`` history per digest.

    The first two levels are LRU-bounded by approximate bytes
    (``DBX_PANEL_CACHE_MB``, default 256 per level); the page pool by
    ``DBX_PAGE_POOL_MB``. Eviction is not an error: the worker recovers
    a digest-only miss through the dispatcher's ``FetchPayload`` RPC, and
    a pool-rejected group falls back to the dense stack path.
    Thread-safe — the worker's control thread probes/fills the host level
    while the compute thread serves from all levels.
    """

    def __init__(self, max_bytes: int | None = None,
                 registry: "obs.Registry | None" = None):
        from .panel_store import ByteLRU

        self.max_bytes = (cache_max_bytes() if max_bytes is None
                          else int(max_bytes))
        self._lock = threading.Lock()
        self._pages = None
        # Both levels ride the ONE eviction/accounting implementation the
        # dispatcher's blob store uses (panel_store.ByteLRU); only the
        # pricing differs (decoded array nbytes vs caller-supplied device
        # block size).
        self._series = ByteLRU(self.max_bytes, self._nbytes)
        self._device = ByteLRU(self.max_bytes)   # put() passes nbytes
        reg = registry or obs.get_registry()
        self._reg = reg
        self._c_hits = {
            lvl: reg.counter("dbx_panel_cache_hits_total",
                             help="panel-cache hits by level "
                                  "(host=decode skipped, device=h2d "
                                  "skipped too)", level=lvl)
            for lvl in ("host", "device")}
        self._c_misses = {
            lvl: reg.counter("dbx_panel_cache_misses_total",
                             help="panel-cache misses by level",
                             level=lvl)
            for lvl in ("host", "device")}
        self._g_bytes = reg.gauge(
            "dbx_panel_cache_bytes",
            help="approximate bytes resident in the worker panel cache "
                 "(host + device levels)")

    @staticmethod
    def _nbytes(arrays) -> int:
        return int(sum(getattr(a, "nbytes", 0) for a in arrays))

    def _publish_bytes(self) -> None:
        self._g_bytes.set(self._series.bytes + self._device.bytes)

    def contains_series(self, digest: str) -> bool:
        """Non-counting probe (the control thread's pre-dispatch check —
        a probe must not inflate the hit rate the compute path reports)."""
        with self._lock:
            return digest in self._series

    def get_series(self, digest: str):
        with self._lock:
            s = self._series.get(digest)
        if s is not None:
            self._c_hits["host"].inc()
        else:
            self._c_misses["host"].inc()
        return s

    def put_series(self, digest: str, series) -> None:
        with self._lock:
            self._series.put(digest, series)
            self._publish_bytes()

    def get_device(self, digest: str):
        with self._lock:
            d = self._device.get(digest)
        if d is not None:
            self._c_hits["device"].inc()
        else:
            self._c_misses["device"].inc()
        return d

    def put_device(self, digest: str, block, nbytes: int) -> None:
        """Cache a device-resident field block. ``nbytes`` is passed in
        (not read off the array): a just-launched device_put's .nbytes is
        known host-side without forcing a sync."""
        with self._lock:
            self._device.put(digest, block, nbytes)
            self._publish_bytes()

    @property
    def pages(self):
        """Third cache level: the device page pool (ragged paged
        batching), created lazily so workers that never take the paged
        route (mesh workers, pre-digest dispatchers, DBX_PAGED=0) do not
        allocate it."""
        with self._lock:
            if self._pages is None:
                from .page_pool import PagePool

                self._pages = PagePool(registry=self._reg)
            return self._pages

    def stats(self) -> dict:
        with self._lock:
            out = {"host_panels": len(self._series),
                   "host_bytes": self._series.bytes,
                   "device_panels": len(self._device),
                   "device_bytes": self._device.bytes,
                   "max_bytes": self.max_bytes}
            pages = self._pages
        if pages is not None:
            out["page_pool"] = pages.stats()
        return out

    def top_digests(self, k: int = 8) -> list[dict]:
        """The top-``k`` resident panels by byte size across the host +
        device levels — the fleet telemetry frame's digest SKETCH
        (12-hex prefixes + byte sizes, never the full key list: a
        thousand-panel cache must not ride every poll)."""
        sizes: dict[str, int] = {}
        with self._lock:
            for key, nb in self._series.sizes() + self._device.sizes():
                sizes[key] = sizes.get(key, 0) + int(nb)
        top = sorted(sizes.items(), key=lambda kv: (-kv[1], kv[0]))[:k]
        return [{"d": str(d)[:12], "b": nb} for d, nb in top]


class Completion:
    """One finished job: id + packed DBXM metrics + compute seconds.

    ``trace_id`` echoes the job's dispatcher-minted trace (JobSpec.trace_id)
    so the report leg and the CompleteItem wire echo stay stitchable;
    empty for jobs enqueued by a pre-tracing dispatcher."""

    __slots__ = ("job_id", "metrics", "elapsed_s", "trace_id")

    def __init__(self, job_id: str, metrics: bytes, elapsed_s: float,
                 trace_id: str = ""):
        self.job_id = job_id
        self.metrics = metrics
        self.elapsed_s = elapsed_s
        self.trace_id = trace_id


class _ScenarioJob:
    """Per-scenario identity inside a coalesced spec-batch job: collect()
    and the obs span helpers read id/grid/trace ATTRIBUTES off whatever
    object rides the pending entry, so a K-spec batch completes as K
    ordinary per-scenario results without K JobSpec protos ever existing
    worker-side. ``grid`` aliases the carrier JobSpec's shared grid map
    (every batch member swept the same grid by construction)."""

    __slots__ = ("id", "grid", "trace_id", "parent_span_id")

    def __init__(self, job_id: str, grid, trace_id: str,
                 parent_span_id: str):
        self.id = job_id
        self.grid = grid
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id


class ComputeBackend(Protocol):
    def process(self, jobs: Iterable[pb.JobSpec]) -> list[Completion]:
        """Run a job batch to completion (synchronous, CPU/TPU-bound)."""
        ...

    @property
    def chips(self) -> int:
        """Device count to advertise to the dispatcher."""
        ...

    # Backends may additionally expose a two-phase pipeline:
    #   submit(jobs) -> opaque handle   (dispatch work, return immediately)
    #   collect(handle) -> [Completion] (block for results)
    # The worker runs submit and collect on separate threads of a bounded
    # pipeline (DBX_PIPELINE, round 14) when both methods exist — the
    # decode -> H2D -> compute double-buffering SURVEY.md §2.3 (PP row)
    # prescribes against the reference's serial loop (reference
    # src/worker/process.rs:21-25) — and calls the optional
    #   prefetch(jobs) -> int  (stage inputs early; best-effort)
    # hook from its CONTROL thread for batches still queued behind the
    # pipeline (DBX_PREFETCH).


def _stack_field_ragged(series_list, t_max: int,
                        field: str = "close") -> np.ndarray:
    """Single-column ragged stack with repeat-last padding to ``t_max`` bars.

    Repeat-last padding is load-bearing: pad bars earn exactly zero return
    and hold the final position, so the kernels' reductions over the padded
    width equal the unpadded ones (see ops.fused). Shared by the
    single-asset and pairs submit paths so the discipline cannot diverge.
    (For non-close columns — high/low channels, volume — the repeated last
    value changes nothing either: pad-bar positions never reach a metric.)
    """
    out = np.empty((len(series_list), t_max), np.float32)
    for i, s in enumerate(series_list):
        a = np.asarray(getattr(s, field), np.float32)
        out[i, :a.shape[0]] = a
        out[i, a.shape[0]:] = a[-1]
    return out


class _FusedSpec(NamedTuple):
    """One fused-kernel routing row (see ``_FUSED_STRATEGIES``)."""

    axes: set               # required grid axes, exactly
    window_axes: tuple      # axes whose values must be integral bar counts
    run: Callable           # (*field_arrays, grid, cost, ppy, t_real) -> Metrics
    table_axes: tuple | None = None   # axes sizing the selection table
    fields: tuple = ("close",)        # OHLCV columns the kernel consumes


class _TimeshardSpec(NamedTuple):
    """One time-sharded (long-context) routing row.

    Maps a strategy to its ``parallel.timeshard`` composed backtest: the
    positional parameter order of the sharded function, the OHLCV columns
    it consumes, and whether its signal head needs a window-sized halo
    (EMA-state families carry O(1) state, so their windows are not bounded
    by the per-chip block length)."""

    params: tuple           # positional param axes, in the fn's order
    fields: tuple           # OHLCV columns the backtest consumes
    fn_name: str            # attribute in parallel.timeshard
    halo_bound: bool = True  # window must fit one per-chip block


def _start_result_copy(m, *, donate: bool = True):
    """Stack the 9 metric fields on device and begin the async d2h copy.

    ``donate=False`` opts a caller out of the TPU buffer donation — the
    streaming-append path must, because ``recurrent.finalize``'s outputs
    may alias buffers the stored carry checkpoint still owns."""
    stacked = _stack_metrics(*m, donate=donate)
    try:
        stacked.copy_to_host_async()
    except AttributeError:
        pass   # non-jax array (already host-resident)
    return stacked


_STACK_METRICS_CACHE: dict = {}


def _stack_metrics(*fields, donate: bool = True):
    """Stack 9 metric fields into one device array under jit (one transfer).

    On TPU the inputs are DONATED: the per-field sweep outputs hand
    their buffers to the stacked block, so a deep pipeline holds one
    result block per in-flight batch instead of block + 9 donors — the
    donated-buffer half of the round-14 async-collect contract. CPU/GPU
    skip donation (XLA there may not consume it and jax warns per call).
    """
    import jax

    key = "fn"
    donate = donate and jax.default_backend() == "tpu"
    if donate:
        key = "fn_donate"
    fn = _STACK_METRICS_CACHE.get(key)
    if fn is None:
        import jax.numpy as jnp

        fn = _STACK_METRICS_CACHE[key] = jax.jit(
            lambda *fs: jnp.stack(fs),
            donate_argnums=tuple(range(9)) if donate else ())
    return fn(*fields)


_TOPK_FN_CACHE: dict = {}


def _topk_reduce(m, metric: str, k: int):
    """On-device top-k: ``(N, P)`` Metrics -> ``((N, k) idx, (N, k) Metrics)``.

    Rows are ranked by ``metric`` in the metric's own direction
    (``metric_sign``), NaN rows last. Runs under jit on whatever sharding
    the sweep produced (the param axis is unsharded in every backend path,
    so ``top_k``/``take_along_axis`` stay chip-local) — the reduction is
    the "move scalars, not matrices" half of the north star's per-chip
    batching story (``JobSpec.top_k``).
    """
    import jax

    from ..ops.metrics import Metrics, metric_sign

    key = (metric, int(k))
    fn = _TOPK_FN_CACHE.get(key)
    if fn is None:
        import jax.numpy as jnp

        sign = float(metric_sign(metric))
        pos = Metrics._fields.index(metric)

        def f(*fields):
            score = fields[pos] * sign
            score = jnp.where(jnp.isnan(score), -jnp.inf, score)
            _, idx = jax.lax.top_k(score, k)
            return idx, [jnp.take_along_axis(f_, idx, axis=1)
                         for f_ in fields]

        fn = _TOPK_FN_CACHE[key] = jax.jit(f)
    idx, sel = fn(*m)
    try:
        idx.copy_to_host_async()
    except AttributeError:
        pass
    return idx, Metrics(*sel)


class JaxSweepBackend:
    """The real engine: decode OHLCV bytes, run the fused sweep, pack metrics.

    Jobs in a batch that share (strategy, grid, n_bars) are stacked into one
    (tickers x params) device call — the per-chip batching the north star
    prescribes — instead of being looped one by one. The submit/collect
    split lets the worker overlap batch N+1's decode/H2D/compute with batch
    N's result transfer (SURVEY.md §2.3 PP row; the reference's serial loop
    at src/worker/process.rs:21-25 is the anti-pattern).
    """

    def __init__(self, *, param_chunk: int | None = None,
                 use_fused: bool | None = None,
                 use_mesh: bool | None = None):
        import jax  # deferred: workers decide platform via env/config

        self._jax = jax
        self.param_chunk = param_chunk
        # local_devices, not devices: under jax.distributed a process sees
        # every host's chips in jax.devices(), but a WORKER is one process
        # on one host — it can only feed (and should only advertise) its
        # own chips. Cross-host scale-out is the dispatcher's job.
        self._devices = jax.local_devices()
        # The fused Pallas kernel is compiled-TPU only; its interpret mode
        # is far slower than the generic XLA path on CPU.
        if use_fused is None:
            use_fused = jax.default_backend() == "tpu"
        self.use_fused = use_fused
        # Multi-chip workers shard every job group's ticker axis over a 1-D
        # mesh of the local chips (advertising N chips while computing on
        # one would leave N-1 idle). Defaults on for real multi-chip TPU
        # hosts; tests opt in on the virtual CPU mesh.
        if use_mesh is None:
            use_mesh = (len(self._devices) > 1
                        and jax.default_backend() == "tpu")
        self._mesh = None
        self._mesh_fns: dict = {}
        self._time_mesh_cache = None
        if use_mesh and len(self._devices) > 1:
            from ..parallel import sharding as sharding_mod

            self._mesh = sharding_mod.make_mesh(self._devices)
        # Observability (DESIGN.md "Observability"): per-phase attribution
        # of the decode -> submit -> device-drain pipeline, kernel wall
        # keyed by route:strategy (the live counterpart of bench.py's
        # roofline stages), and the jit compile-vs-execute split (first
        # call on a signature = compile-inclusive "cold").
        reg = obs.get_registry()
        self._obs = reg
        self._h_decode = reg.histogram(
            "dbx_compute_decode_seconds",
            help="OHLCV wire decode wall per job group")
        self._c_decode_bytes = reg.counter(
            "dbx_compute_decode_bytes_total",
            help="OHLCV payload bytes decoded")
        self._h_collect = reg.histogram(
            "dbx_compute_collect_seconds",
            help="device drain + d2h wait per pending group")
        self._c_d2h_bytes = reg.counter(
            "dbx_compute_d2h_bytes_total",
            help="result bytes copied device->host")
        self._c_backtests = reg.counter(
            "dbx_backtests_total", help="(ticker x param) combos computed")
        self._bt_rate = obs.StepTimer(reg.gauge(
            "dbx_compute_backtests_per_sec",
            help="combos/s since backend start"))
        self._h_jit = {
            phase: reg.histogram(
                "dbx_jit_call_seconds",
                help="mesh-fn dispatch wall: cold includes trace+compile, "
                     "warm is async launch only", phase=phase)
            for phase in ("cold", "warm")}
        self._kern_h: dict = {}    # (strategy, route, cold) -> Histogram
        self._seen_cold: set = set()
        # Live fused-kernel substrate defaults (epilogue / table / lanes
        # cap): an info-style gauge whose LABELS carry the values, so
        # /metrics, /stats.json, GetStats obs_json and `obs.dump` all show
        # per-worker which substrate is serving without reading logs
        # (DESIGN.md "Roofline accounting"). Resolved once here — the same
        # env validation the first sweep would hit, surfaced at backend
        # construction instead of mid-batch.
        from ..ops import fused as fused_ops

        self._fused_ops = fused_ops
        # Ragged paged panel batching (round 10): fused groups assemble
        # from the device page pool (PanelCache.pages) through per-job
        # page tables instead of dense per-length stacks. Meshless fused
        # workers only — the mesh path needs explicit shardings on its
        # device_put (same boundary as the device block cache).
        # DBX_PAGED=0 is the kill switch.
        self.use_paged = (self.use_fused and self._mesh is None
                          and fused_ops.paged_enabled())
        # Padding-waste observability: bars materialized ONLY to batch
        # (dense = repeat-last stacks padded to the group/bucket max;
        # paged = in-page pad of newly uploaded partial tail pages —
        # bounded by one page per ticker).
        _pad_help = ("panel pad bars materialized for batching, by "
                     "execution path (dense = stacks padded to the group "
                     "max; paged = in-page pad of uploaded tail pages)")
        self._c_pad_bars = {
            "dense": reg.counter("dbx_pad_bars_total", help=_pad_help,
                                 path="dense"),
            "paged": reg.counter("dbx_pad_bars_total", help=_pad_help,
                                 path="paged")}
        reg.gauge("dbx_fused_substrate_info",
                  help="constant 1; labels carry the live fused-kernel "
                       "substrate defaults (epilogue/table/lanes)",
                  **fused_ops.substrate_defaults()).set(1)
        # (strategy, epilogue, table) -> Counter: which substrate served
        # each fused job group (the per-group twin of the info gauge).
        self._substrate_counters: dict = {}
        # jit caches per input SHAPE, not just per program key: a cached
        # mesh fn hit with a new (rows, bars) signature recompiles for
        # seconds and must not be attributed as "warm" async launch.
        self._seen_shapes: set = set()
        # Dispatch by digest (worker half): decoded-panel + device-block
        # cache keyed by JobSpec.panel_digest, and the FetchPayload hook
        # the Worker installs (compute-thread recovery for the
        # evicted-between-poll-and-decode race).
        self.panel_cache = PanelCache(registry=reg)
        self.payload_fetcher: Callable[[str], bytes] | None = None
        # Streaming appends (JobSpec.append_*): digest-keyed carry
        # checkpoints so an appended ΔT-bar slice advances a finished
        # sweep in O(ΔT) instead of repricing T bars (streaming/).
        from ..streaming import CarryStore

        self.carry_store = CarryStore(registry=reg)
        self._c_append = {
            outcome: reg.counter(
                "dbx_worker_append_total",
                help="streaming append jobs served, by outcome "
                     "(carry_hit=O(ΔT) advance, full_reprice=checkpoint "
                     "miss fallback)", outcome=outcome)
            for outcome in ("carry_hit", "full_reprice")}
        # Scenario megakernel route accounting: which path served each
        # scenario job. `fused` = in-trace regeneration inside the sweep
        # launch (panel never in HBM); `materialized` = a concrete panel
        # was generated first — dispatcher-side (old capability / kill
        # switch / digestless base) or the worker's own in-process
        # fallback when the fused leg fails.
        self._c_scenario_route = {
            mode: reg.counter(
                "dbx_scenario_route_total",
                help="scenario jobs served, by route (fused=in-trace "
                     "regeneration, materialized=concrete panel)",
                mode=mode)
            for mode in ("materialized", "fused")}
        # Substrate autotuner (tune/, round 11): the schedule registry is
        # consulted per fused group submit — explicit arg > env > tuned
        # schedule > hardcoded default, so every existing override keeps
        # its exact semantics — and, under DBX_AUTOTUNE, first contact
        # with a (family, shape-bucket) measures the substrate
        # cross-product and persists the winner. The worker control loop
        # gossips new entries up (JobsRequest.schedule_json) and adopts
        # the merged fleet registry from GetStats, so the Nth worker
        # inherits the first worker's tuning without re-measuring.
        from .. import tune as tune_mod

        self._tune = tune_mod
        self.schedule_registry = tune_mod.ScheduleRegistry.open_default(
            registry=reg)
        self._autotuner = tune_mod.Autotuner(self.schedule_registry,
                                             registry=reg)
        self._platform = jax.default_backend()
        # First-contact memo: a (family, bucket) whose tune attempt found
        # no winner must not re-pay the measurement on every group.
        self._tuned_attempted: set = set()
        self._tuned_info_seen: set = set()
        # Construction-time tuned defaults: knobs that bind before any
        # group submit (the page pool's page size) apply through the
        # process-wide tuned default layer when the restored registry
        # holds a page_bars winner for this platform (deterministic pick:
        # most common value, ties to the smallest).
        pb_counts: dict = {}
        for e in self.schedule_registry.entries():
            if e["platform"] != self._platform:
                continue
            v = e["substrates"].get("page_bars")
            if v:
                pb_counts[v] = pb_counts.get(v, 0) + 1
        if pb_counts:
            pb_pick = sorted(pb_counts.items(),
                             key=lambda kv: (-kv[1], kv[0]))[0][0]
            fused_ops.set_tuned_defaults({"page_bars": pb_pick})

    def _evict_mesh_fn(self) -> None:
        """FIFO-evict the oldest compiled mesh fn AND its shape-signature
        memory: eviction discards the jit cache, so the rebuilt fn's first
        call recompiles and must count as "cold" again."""
        evicted = next(iter(self._mesh_fns))
        del self._mesh_fns[evicted]
        self._seen_shapes = {sk for sk in self._seen_shapes
                             if sk[0] != evicted}

    def _observe_submit(self, strategy: str, route: str, t0: float,
                        cold_key=None, group=None, bars=None,
                        combos=None) -> None:
        """Record a group's submit-side wall (group start -> kernels
        launched, decode included) into
        ``dbx_kernel_submit_seconds{kernel=route:strategy}``. ``cold_key``
        marks the first submission of a compile signature as
        phase="compile" (the jit compile-vs-execute split at group grain).

        With ``group`` given, the same interval is also emitted as a
        ``worker.compile`` / ``worker.execute`` span joined to every job's
        trace — the timeline analyzer's compile-vs-execute stage split
        (the decode span nests inside this interval and wins attribution
        for its sub-range). ``bars``/``combos`` ride the span as shape
        attrs so the cost-model drift plane (obs/costmodel.py) can score
        the measured wall against the op model's prediction."""
        dt = time.perf_counter() - t0
        cold = False
        if cold_key is not None:
            cold = cold_key not in self._seen_cold
            if cold:
                if len(self._seen_cold) > 4096:   # long-lived worker bound
                    self._seen_cold.clear()
                self._seen_cold.add(cold_key)
        hk = (strategy, route, cold)
        h = self._kern_h.get(hk)
        if h is None:
            h = self._kern_h[hk] = self._obs.histogram(
                "dbx_kernel_submit_seconds",
                help="per-group submit wall (decode + H2D + launch) by "
                     "route:strategy",
                kernel=f"{route}:{strategy}",
                phase="compile" if cold else "execute")
        h.observe(dt)
        if group is not None:
            pairs = obs.job_trace_pairs(group)
            if pairs:
                shape = {}
                if bars is not None:
                    shape["bars"] = int(bars)
                if combos is not None:
                    shape["combos"] = int(combos)
                obs.emit_span("worker.compile" if cold else "worker.execute",
                              time.time() - dt, dt, pairs=pairs,
                              kernel=f"{route}:{strategy}",
                              jobs=len(group), **shape)

    def _observe_substrates(self, strategy: str) -> None:
        """Count a fused group against the substrate set that served it
        (``dbx_fused_substrate_total{kernel,epilogue,table}``)."""
        subs = self._fused_ops.route_substrates(strategy)
        key = (strategy, subs["epilogue"], subs["table"])
        c = self._substrate_counters.get(key)
        if c is None:
            c = self._substrate_counters[key] = self._obs.counter(
                "dbx_fused_substrate_total",
                help="fused job groups served, by kernel and "
                     "epilogue/table substrate",
                kernel=strategy, **subs)
        c.inc()

    @property
    def accepts_scenario_batch(self) -> bool:
        """Capability the worker advertises on JobsRequest: this backend
        can regenerate scenario panels in-trace inside the fused sweep
        launch. Read per poll so flipping the ``DBX_SCENARIO_FUSED`` kill
        switch stops NEW spec batches immediately (already-leased
        batches still drain through the in-process materialized
        fallback)."""
        return self._fused_ops.scenario_fused_enabled()

    @property
    def chips(self) -> int:
        # Honest capacity advertising: a meshless backend computes every
        # group on ONE device, so a multi-chip host claiming all of them
        # would take leases it cannot parallelize; the mesh path advertises
        # the real fan-out.
        return len(self._devices) if self._mesh is not None else 1

    def telemetry(self) -> dict:
        """Capability flags + cache residency for the fleet telemetry
        frame (obs/fleet.py): counts and byte totals per cache level
        plus a bounded top-K digest sketch — the placement-scorer's
        future input (ROADMAP item 3: carry hits, page residency and a
        warm compile cache are exactly the stage costs it ranks)."""
        return {
            "caps": {"backend": "jax", "chips": self.chips,
                     "platform": self._platform,
                     "fused": bool(self.use_fused),
                     "mesh": self._mesh is not None,
                     "paged": bool(self.use_paged)},
            "caches": {
                "panel": self.panel_cache.stats(),
                "panel_topk": self.panel_cache.top_digests(),
                "carry": self.carry_store.stats(),
                "schedule_entries": len(
                    self.schedule_registry.entries()),
            },
        }

    # Per-cell VMEM budget of the fused kernel: its (T_pad, W_pad) SMA-table
    # block plus ~8 (T_pad, 128) working tiles must fit in ~16 MB.
    _FUSED_MAX_BARS = 8192
    _FUSED_MAX_WINDOWS = 128

    # Fused Pallas kernels per strategy, described by _FusedSpec rows.
    # "Table axes" are the ones whose distinct values size the kernel's
    # selection table (defaults to the integral window axes); MACD's signal
    # spans are per-lane decays, not a table dimension, so they must not
    # count toward the window cap. "Fields" are the OHLCV columns the kernel
    # consumes — only those reach the device. Eligibility and dispatch share
    # this table so they cannot drift.
    @staticmethod
    def _run_fused_sma(close, grid, cost, ppy, t_real):
        from ..ops import fused
        return fused.fused_sma_sweep(
            close, np.asarray(grid["fast"]), np.asarray(grid["slow"]),
            t_real=t_real, cost=cost, periods_per_year=ppy)

    @staticmethod
    def _run_fused_bollinger(close, grid, cost, ppy, t_real):
        from ..ops import fused
        return fused.fused_bollinger_sweep(
            close, np.asarray(grid["window"]), np.asarray(grid["k"]),
            t_real=t_real, cost=cost, periods_per_year=ppy)

    @staticmethod
    def _run_fused_bollinger_touch(close, grid, cost, ppy, t_real):
        from ..ops import fused
        return fused.fused_bollinger_touch_sweep(
            close, np.asarray(grid["window"]), np.asarray(grid["k"]),
            t_real=t_real, cost=cost, periods_per_year=ppy)

    @staticmethod
    def _run_fused_momentum(close, grid, cost, ppy, t_real):
        from ..ops import fused
        return fused.fused_momentum_sweep(
            close, np.asarray(grid["lookback"]), t_real=t_real, cost=cost,
            periods_per_year=ppy)

    @staticmethod
    def _run_fused_donchian(close, grid, cost, ppy, t_real):
        from ..ops import fused
        return fused.fused_donchian_sweep(
            close, np.asarray(grid["window"]), t_real=t_real, cost=cost,
            periods_per_year=ppy)

    @staticmethod
    def _run_fused_rsi(close, grid, cost, ppy, t_real):
        from ..ops import fused
        return fused.fused_rsi_sweep(
            close, np.asarray(grid["period"]), np.asarray(grid["band"]),
            t_real=t_real, cost=cost, periods_per_year=ppy)

    @staticmethod
    def _run_fused_macd(close, grid, cost, ppy, t_real):
        from ..ops import fused
        return fused.fused_macd_sweep(
            close, np.asarray(grid["fast"]), np.asarray(grid["slow"]),
            np.asarray(grid["signal"]), t_real=t_real, cost=cost,
            periods_per_year=ppy)

    @staticmethod
    def _run_fused_trix(close, grid, cost, ppy, t_real):
        from ..ops import fused
        return fused.fused_trix_sweep(
            close, np.asarray(grid["span"]), np.asarray(grid["signal"]),
            t_real=t_real, cost=cost, periods_per_year=ppy)

    @staticmethod
    def _run_fused_donchian_hl(close, high, low, grid, cost, ppy, t_real):
        from ..ops import fused
        return fused.fused_donchian_hl_sweep(
            close, high, low, np.asarray(grid["window"]), t_real=t_real,
            cost=cost, periods_per_year=ppy)

    @staticmethod
    def _run_fused_stochastic(close, high, low, grid, cost, ppy, t_real):
        from ..ops import fused
        return fused.fused_stochastic_sweep(
            close, high, low, np.asarray(grid["window"]),
            np.asarray(grid["band"]), t_real=t_real, cost=cost,
            periods_per_year=ppy)

    @staticmethod
    def _run_fused_keltner(close, high, low, grid, cost, ppy, t_real):
        from ..ops import fused
        return fused.fused_keltner_sweep(
            close, high, low, np.asarray(grid["window"]),
            np.asarray(grid["k"]), t_real=t_real, cost=cost,
            periods_per_year=ppy)

    @staticmethod
    def _run_fused_obv(close, volume, grid, cost, ppy, t_real):
        from ..ops import fused
        return fused.fused_obv_sweep(
            close, volume, np.asarray(grid["window"]), t_real=t_real,
            cost=cost, periods_per_year=ppy)

    @staticmethod
    def _run_fused_vwap(close, volume, grid, cost, ppy, t_real):
        from ..ops import fused
        return fused.fused_vwap_sweep(
            close, volume, np.asarray(grid["window"]),
            np.asarray(grid["k"]), t_real=t_real, cost=cost,
            periods_per_year=ppy)

    _FUSED_STRATEGIES = {
        "sma_crossover": _FusedSpec({"fast", "slow"}, ("fast", "slow"),
                                    _run_fused_sma),
        "bollinger": _FusedSpec({"window", "k"}, ("window",),
                                _run_fused_bollinger),
        "bollinger_touch": _FusedSpec({"window", "k"}, ("window",),
                                      _run_fused_bollinger_touch),
        "momentum": _FusedSpec({"lookback"}, ("lookback",),
                               _run_fused_momentum),
        "donchian": _FusedSpec({"window"}, ("window",), _run_fused_donchian),
        "donchian_hl": _FusedSpec({"window"}, ("window",),
                                  _run_fused_donchian_hl,
                                  fields=("close", "high", "low")),
        "rsi": _FusedSpec({"period", "band"}, ("period",), _run_fused_rsi),
        "stochastic": _FusedSpec({"window", "band"}, ("window",),
                                 _run_fused_stochastic,
                                 fields=("close", "high", "low")),
        "keltner": _FusedSpec({"window", "k"}, ("window",),
                              _run_fused_keltner,
                              fields=("close", "high", "low")),
        "macd": _FusedSpec({"fast", "slow", "signal"},
                           ("fast", "slow", "signal"), _run_fused_macd,
                           table_axes=("fast", "slow")),
        "trix": _FusedSpec({"span", "signal"}, ("span", "signal"),
                           _run_fused_trix, table_axes=("span",)),
        "vwap_reversion": _FusedSpec({"window", "k"}, ("window",),
                                     _run_fused_vwap,
                                     fields=("close", "volume")),
        "obv_trend": _FusedSpec({"window"}, ("window",), _run_fused_obv,
                                fields=("close", "volume")),
    }

    # Time-sharded long-context backtests (parallel.timeshard): the route
    # for jobs whose bar count exceeds the fused kernels' VMEM cap on a
    # meshed worker whose ticker axis cannot fill the chips. Each strategy
    # maps to its composed sharded backtest; parameters are per-combo
    # statics (halo sizes and EMA decays bake into the compiled program),
    # so a grid sweeps as one jitted program with one sub-backtest per
    # combo. Fields/axes mirror _FUSED_STRATEGIES so routing cannot drift.
    _TIMESHARD_STRATEGIES = {
        "sma_crossover": _TimeshardSpec(("fast", "slow"), ("close",),
                                        "sharded_sma_backtest"),
        "bollinger": _TimeshardSpec(("window", "k"), ("close",),
                                    "sharded_bollinger_backtest"),
        "bollinger_touch": _TimeshardSpec(("window", "k"), ("close",),
                                          "sharded_bollinger_touch_backtest"),
        "momentum": _TimeshardSpec(("lookback",), ("close",),
                                   "sharded_momentum_backtest"),
        "donchian": _TimeshardSpec(("window",), ("close",),
                                   "sharded_donchian_backtest"),
        "donchian_hl": _TimeshardSpec(("window",), ("close", "high", "low"),
                                      "sharded_donchian_hl_backtest"),
        "rsi": _TimeshardSpec(("period", "band"), ("close",),
                              "sharded_rsi_backtest", halo_bound=False),
        "stochastic": _TimeshardSpec(("window", "band"),
                                     ("close", "high", "low"),
                                     "sharded_stochastic_backtest"),
        "keltner": _TimeshardSpec(("window", "k"), ("close", "high", "low"),
                                  "sharded_keltner_backtest"),
        "macd": _TimeshardSpec(("fast", "slow", "signal"), ("close",),
                               "sharded_macd_backtest", halo_bound=False),
        "trix": _TimeshardSpec(("span", "signal"), ("close",),
                               "sharded_trix_backtest", halo_bound=False),
        "vwap_reversion": _TimeshardSpec(("window", "k"),
                                         ("close", "volume"),
                                         "sharded_vwap_backtest"),
        "obv_trend": _TimeshardSpec(("window",), ("close", "volume"),
                                    "sharded_obv_backtest"),
    }

    # Every grid combo compiles its own sub-backtest (windows are static
    # halo sizes); cap the per-group program count so a huge grid cannot
    # spend minutes in XLA before its first result.
    _TIMESHARD_MAX_COMBOS = 128

    # Walk-forward routes to the fused-train two-phase split only when the
    # grid is large enough for the train sweep to dominate; below this the
    # generic single-program walk_forward measured faster (bench.py:
    # 11.5M/s generic vs 5.5M/s fused at P=400 on a v5e chip).
    _WF_FUSED_MIN_COMBOS = 512

    def _timeshard_window_reason(self, wins, n_combos: int, t_min: int, *,
                                 halo_bound: bool = True,
                                 what: str = "window") -> str | None:
        return _timeshard_window_reason(
            wins, n_combos, t_min, self._mesh.devices.size,
            halo_bound=halo_bound, what=what)

    def _timeshard_reason(self, job, axes, lengths) -> str | None:
        """None when a long-context group can route to the time-sharded
        backtests; otherwise why it stays on the generic path."""
        return timeshard_route_reason(job.strategy, axes, lengths,
                                      self._mesh.devices.size)

    def _time_mesh(self):
        """1-D mesh over the SAME local chips with the TIME axis name
        (the worker's ticker mesh re-labeled for bar-axis sharding)."""
        if self._time_mesh_cache is None:
            from jax.sharding import Mesh

            from ..parallel import timeshard

            self._time_mesh_cache = Mesh(
                self._mesh.devices, (timeshard.TIME_AXIS,))
        return self._time_mesh_cache

    def _submit_timeshard_groups(self, group, series, lengths, t0, axes):
        """Long-context jobs: shard the BAR axis over the local chip mesh.

        The submit path for groups whose history exceeds the fused VMEM
        cap but whose ticker count cannot fill the mesh — instead of
        demoting to a single device's generic path, each grid combo runs
        the composed blockwise backtest from ``parallel.timeshard``
        (distributed cumsums / EMA carries / transition-map folds over
        ICI), so one history longer than any chip's memory uses every
        chip. Histories pad right with repeat-last values to a mesh
        multiple and pass their real length (``t_real``) so pad bars are
        dead in every metric. Returns one pending entry per length
        subgroup (ragged groups cannot share one padded panel).
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..ops.metrics import Metrics
        from ..parallel import timeshard

        job0 = group[0]
        fam = self._TIMESHARD_STRATEGIES[job0.strategy]
        fn = getattr(timeshard, fam.fn_name)
        tmesh = self._time_mesh()
        n_dev = tmesh.devices.size
        cost = float(job0.cost)
        ppy = int(job0.periods_per_year or 252)
        # DBXM column order IS product_grid order — the shared helper
        # keeps this path and the slice worker on one contract.
        combos = timeshard_combos(job0.strategy, axes)

        subgroups: dict[int, list[int]] = {}
        for i, t in enumerate(lengths):
            subgroups.setdefault(int(t), []).append(i)

        pending = []
        for t, idxs in sorted(subgroups.items()):
            T_pad = -(-t // n_dev) * n_dev
            sub_jobs = [group[i] for i in idxs]
            arrays = [_stack_field_ragged([series[i] for i in idxs], T_pad,
                                          f)
                      for f in fam.fields]
            sharded = [jax.device_put(
                a, NamedSharding(tmesh, P(None, timeshard.TIME_AXIS)))
                for a in arrays]
            t_real = None if t == T_pad else t
            key = (("timeshard",) + self._group_key(job0, axes)
                   + (t, T_pad))
            run = self._mesh_fns.get(key)
            if run is None:
                def run(*arrs, _tr=t_real):
                    ms = [fn(tmesh, *arrs, *cmb, cost=cost,
                             periods_per_year=ppy,
                             axis_name=timeshard.TIME_AXIS, t_real=_tr)
                          for cmb in combos]
                    return Metrics(*(jnp.stack(cols, axis=-1)
                                     for cols in zip(*ms)))

                run = jax.jit(run)
                if len(self._mesh_fns) >= self._MESH_FN_CAP:
                    self._evict_mesh_fn()
                self._mesh_fns[key] = run
            m = run(*sharded)
            pending.append(self._finish_group(sub_jobs, m, t0,
                                              len(sub_jobs), job0))
        return pending

    @classmethod
    def _fused_eligible(cls, job, grid, lengths) -> bool:
        """True when the job routes to a fused Pallas kernel."""
        return cls._fused_demotion_reason(job, grid, lengths) is None

    @classmethod
    def _fused_demotion_reason(cls, job, grid, lengths) -> str | None:
        """None when the job is fused-eligible; otherwise the cap that
        demotes it to the ~6x-slower generic path.

        Jobs whose strategy has a ``_FUSED_STRATEGIES`` entry, with integral
        window grids and a VMEM-sized working set, route to Pallas. Mixed
        history lengths are fine: the kernels take per-ticker real lengths
        (round 3 — a ragged fleet used to silently drop to the generic
        path). A strategy with no fused kernel at all returns a reason too,
        but submit() only LOGS demotions of fused-capable strategies — the
        rest are ordinary routing, not a demotion.
        """
        import numpy as np

        spec = cls._FUSED_STRATEGIES.get(job.strategy)
        if spec is None:
            return f"strategy {job.strategy!r} has no fused kernel"
        if set(grid) != spec.axes:
            return (f"grid axes {sorted(grid)} do not match the fused "
                    f"contract {sorted(spec.axes)}")
        wins = np.concatenate([grid[a] for a in spec.window_axes])
        if wins.size == 0:
            return "empty window grid"   # route to generic, don't crash
        if not np.allclose(wins, np.round(wins)):
            return ("non-integral window values in axes "
                    f"{list(spec.window_axes)}")
        tbl = np.concatenate(
            [grid[a] for a in (spec.table_axes or spec.window_axes)])
        n_tbl = int(np.unique(np.round(tbl)).size)
        if n_tbl > cls._FUSED_MAX_WINDOWS:
            return (f"{n_tbl} distinct table windows exceed the kernel cap "
                    f"of {cls._FUSED_MAX_WINDOWS}")
        if job.strategy in ("donchian", "donchian_hl", "stochastic"):
            # The generic channel paths poison windows beyond their static
            # view bound (MAX_WINDOW) to NaN; the fused kernels have no
            # such bound, so larger windows would silently diverge from the
            # semantics-defining path — keep them generic.
            from ..models import donchian as donchian_mod
            from ..models import stochastic as stoch_mod

            bound = (stoch_mod.MAX_WINDOW if job.strategy == "stochastic"
                     else donchian_mod.MAX_WINDOW)
            if float(wins.max()) > bound:
                return (f"max window {int(wins.max())} exceeds the channel "
                        f"view bound {bound}")
        t_max = int(max(lengths))
        if t_max > cls._FUSED_MAX_BARS:
            return (f"{t_max} bars exceed the kernel VMEM cap of "
                    f"{cls._FUSED_MAX_BARS}")
        return None

    def _mesh_call(self, key, runner, row_arrays, t_real):
        """Run ``runner(*blocks, t_real_block)`` with ticker rows sharded
        over the worker's chip mesh.

        The (ticker x param) sweep is embarrassingly parallel, so the SPMD
        program has no collectives: each chip runs the fused kernel on its
        row block and the metrics stay row-sharded until the stacked result
        copy. Rows pad to a mesh multiple by repeating the last row (the pad
        rows are real compute but land beyond ``len(group)`` in collect, so
        they are never reported). The jit(shard_map) wrapper is cached per
        (strategy, grid, cost) key — rebuilding it per batch would retrace
        every poll.
        """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel import sharding as sharding_mod

        mesh = self._mesh
        axis = mesh.axis_names[0]
        n_pad = sharding_mod.pad_tickers(row_arrays[0].shape[0],
                                         mesh.devices.size)

        row = NamedSharding(mesh, P(axis, None))
        args = [self._jax.device_put(
                    sharding_mod.pad_rows(np.asarray(a, np.float32), n_pad),
                    row)
                for a in row_arrays]
        ragged = t_real is not None
        if ragged:
            args.append(self._jax.device_put(
                sharding_mod.pad_rows(
                    np.asarray(t_real, np.int32).reshape(-1, 1), n_pad),
                row))

        # Every env-resolved kernel substrate must be part of the cache
        # key: the fused runners read DBX_LANES_CAP / DBX_EPILOGUE /
        # DBX_*_TABLE (host-side, via their resolve helpers) while this
        # outer jit(shard_map) traces, so without them an in-process
        # substrate change would silently reuse the stale compiled
        # program on the mesh path — the same cache-key bug class the
        # single-device path fixed by threading each knob as a jit static
        # (dbxlint trace-time-env).
        from ..ops.fused import substrate_defaults

        key = key + (ragged,) + tuple(sorted(substrate_defaults().items()))
        fn = self._mesh_fns.get(key)
        if fn is None:
            from ..ops.metrics import Metrics

            def local(*blks):
                if ragged:
                    *data, tr_blk = blks
                    return runner(*data, tr_blk[:, 0])
                return runner(*blks, None)

            fn = jax.jit(shard_map(
                local, mesh=mesh,
                in_specs=tuple(P(axis, None) for _ in args),
                out_specs=Metrics(*(P(axis, None)
                                    for _ in Metrics._fields)),
                check_vma=False))
            if len(self._mesh_fns) >= self._MESH_FN_CAP:
                # FIFO eviction: a long-lived worker cycling through many
                # distinct grids must not grow compiled executables forever
                # (an evicted entry simply recompiles on next use).
                self._evict_mesh_fn()
            self._mesh_fns[key] = fn
        shape_key = (key, tuple(a.shape for a in args))
        cold = shape_key not in self._seen_shapes
        if cold:
            if len(self._seen_shapes) > 4096:
                self._seen_shapes.clear()
            self._seen_shapes.add(shape_key)
        t_call = time.perf_counter()
        out = fn(*args)
        # Cold dispatch blocks on trace+compile (first call of this
        # program x shape signature); warm is the async launch.
        self._h_jit["cold" if cold else "warm"].observe(
            time.perf_counter() - t_call)
        return out

    _MESH_FN_CAP = 32

    @staticmethod
    def _group_key(job, axes) -> tuple:
        """Cache key capturing everything a mesh runner closes over.

        Hashes the per-parameter AXES (small, as the submit grouping key
        does), not the materialized cartesian product — the product is a
        deterministic function of the axes."""
        return (job.strategy,
                tuple(sorted((k, np.asarray(v).tobytes())
                             for k, v in axes.items())),
                float(job.cost), int(job.periods_per_year or 252))

    def _length_bucket(self, job, grid) -> int:
        """Power-of-two length bucket for the submit grouping key — or 0
        (no bucketing) when the paged path will serve the job: the page
        tables make mixed-length groups first-class (one launch per
        page-count class, pad bounded by one page per ticker), so
        splitting them by length would only multiply launches.

        The collapse is gated on actually being paged-SERVABLE, not just
        paged-capable: the job must carry a digest (page keys memoize per
        digest; a digestless job would drag its whole merged group onto
        the dense fallback) and its GRID must pass the length-independent
        fused gates (axes/integrality/table caps — checked with a 1-bar
        length so only the VMEM bar cap, which the submit-time cap split
        handles, is deferred). Jobs that fail any of this keep the
        power-of-two bucket, so a merged group can only miss the paged
        route through a pool rejection — and that path re-splits by this
        same bucket before stacking densely."""
        if self._paged_servable(job, grid):
            return 0
        return (len(job.ohlcv) or job.panel_bytes_len).bit_length()

    def _paged_servable(self, job, grid) -> bool:
        """THE paged-eligibility predicate — grouping
        (:meth:`_length_bucket`) and :meth:`prefetch` share it, so the
        page warm-up can never drift from what the submit path will
        actually serve paged. Length-independent (the VMEM bar cap is
        the caller's concern: submit splits over-cap groups, prefetch
        gates on ``n_bars`` directly)."""
        return (self.use_paged and job.wf_train == 0
                and not job.best_returns and job.strategy != "pairs"
                and bool(job.panel_digest)
                and job.strategy in self._FUSED_STRATEGIES
                and self._fused_demotion_reason(job, grid, (1,)) is None)

    @staticmethod
    def _topk_request_ok(group) -> bool:
        """Validate a group's ``top_k``/``rank_metric`` request up front.

        An unknown rank metric is validated-bad (complete empty + loud
        error, no compute); walk-forward jobs ignore ``top_k`` entirely —
        their payload is already one stitched OOS row (backtesting.proto
        JobSpec.top_k).
        """
        import logging

        from ..ops.metrics import Metrics

        job0 = group[0]
        if job0.top_k <= 0 or job0.wf_train > 0:
            return True
        metric = job0.rank_metric or "sharpe"
        if metric in Metrics._fields:
            return True
        logging.getLogger("dbx.compute").error(
            "jobs %s request top-k by unknown metric %r (known: %s); "
            "completing with empty metrics", [j.id for j in group], metric,
            ", ".join(Metrics._fields))
        return False

    def prefetch(self, jobs) -> int:
        """Control-thread batch warm-up (the worker's ``DBX_PREFETCH``
        leg, round 14): decode payload bytes into the host panel cache
        and pre-stage paged groups' device pages while the compute
        pipeline runs earlier batches.

        Strictly an overlap optimization — every warmed path re-resolves
        through the same caches on the compute thread, so a skipped or
        failed prefetch costs nothing but the overlap. Append jobs are
        left alone (their delta-splice path must not materialize the
        full panel early) and a zero-budget cache
        (``DBX_PANEL_CACHE_MB=0``) skips the decode it could not retain.
        Returns the number of panels decoded (the worker's prefetch span
        is emitted only when real work happened).
        """
        cache = self.panel_cache
        if cache.max_bytes <= 0:
            return 0
        warmed = 0
        decoded: dict = {}
        paged_groups: dict[str, tuple[list, list]] = {}
        for job in jobs:
            if job.append_parent_digest:
                continue
            for digest, raw in ((job.panel_digest, job.ohlcv),
                                (job.panel_digest2, job.ohlcv2)):
                if (not digest or not raw or digest in decoded
                        or cache.contains_series(digest)):
                    continue
                try:
                    s = data_mod.from_wire_bytes(raw)
                except Exception:
                    log.exception(
                        "prefetch decode failed for digest %s; the "
                        "compute thread will decode (and error) inline",
                        digest[:16])
                    continue
                cache.put_series(digest, s)
                decoded[digest] = s
                warmed += 1
            # Page-pool warm-up: upload the pool-missing pages of paged-
            # servable jobs now, so the submit-side prepare finds them
            # resident (pages_new == 0 -> the h2d-skip fast path). Only
            # panels decoded in THIS call join — a digest-only job whose
            # panel is already host-cached had its pages prepared when
            # that panel first crossed the paged submit path. Gated on
            # the SHARED servability predicate: warming pages the submit
            # path will demote to dense would waste H2D and evict pages
            # live groups are about to gather.
            s = decoded.get(job.panel_digest)
            if (s is not None and s.n_bars <= self._FUSED_MAX_BARS
                    and self._fused_ops.paged_supported(job.strategy)):
                try:
                    grid = wire.grid_from_proto(job.grid)
                except Exception:
                    continue
                if not self._paged_servable(job, grid):
                    continue
                digests, series = paged_groups.setdefault(job.strategy,
                                                          ([], []))
                if job.panel_digest not in digests:
                    digests.append(job.panel_digest)
                    series.append(s)
        for strategy, (digests, series) in paged_groups.items():
            try:
                # A pool rejection (None) is fine — the submit path will
                # take the dense fallback exactly as without prefetch.
                self.panel_cache.pages.prepare(
                    digests, series, self._fused_ops.paged_fields(strategy))
            except Exception:
                log.exception("page-pool prefetch failed for %s; submit "
                              "will prepare inline", strategy)
        return warmed

    def _resolve_series(self, job, *, leg2: bool = False):
        """One leg's decoded panel: host cache -> inline bytes ->
        FetchPayload (the second chance for a panel evicted between the
        control thread's pre-dispatch probe and this decode). Returns
        ``(series, cache_hit)``. An unresolvable digest raises — the
        worker loop logs it and leaves the lease to requeue the batch
        (by then the dispatcher has forgotten the delivery, so the
        re-dispatch ships full bytes): miss -> fetch -> full job, never a
        failed job."""
        digest = job.panel_digest2 if leg2 else job.panel_digest
        raw = job.ohlcv2 if leg2 else job.ohlcv
        if digest:
            s = self.panel_cache.get_series(digest)
            if s is not None:
                return s, True
        if not raw and digest and self.payload_fetcher is not None:
            # The recovery RPC gets its OWN span: it can run inside the
            # decode window (compute-thread race leg), and a 30s network
            # stall must read as transport in timeline attribution, not
            # as decode work (obs.timeline maps worker.payload_fetch ->
            # transport, innermost-wins over the enclosing decode span).
            t0_wall, t0 = time.time(), time.perf_counter()
            raw = self.payload_fetcher(digest)
            obs.emit_span("worker.payload_fetch", t0_wall,
                          time.perf_counter() - t0,
                          pairs=obs.job_trace_pairs([job]),
                          digest=digest, ok=bool(raw))
        if not raw:
            raise ValueError(
                f"job {job.id}: digest-only payload "
                f"{digest[:16] if digest else '?'} is in no cache and not "
                "fetchable; leaving the lease to requeue it")
        s = data_mod.from_wire_bytes(raw)
        if digest:
            self.panel_cache.put_series(digest, s)
        return s, False

    def _resolve_append_series(self, job):
        """Extended panel for an append job: digest cache -> splice (the
        cached BASE panel + ``JobSpec.append_delta`` — the delta-only
        dispatch fast path, no full panel on the wire) -> inline bytes ->
        FetchPayload. Returns ``(series, cache_hit)``."""
        digest = job.panel_digest
        if (digest and not job.ohlcv and job.append_delta
                and job.append_parent_digest
                and not self.panel_cache.contains_series(digest)):
            base = self.panel_cache.get_series(job.append_parent_digest)
            if base is not None and base.n_bars == int(job.append_base_len):
                delta = data_mod.from_wire_bytes(job.append_delta)
                s = data_mod.OHLCV(*(
                    np.concatenate([np.asarray(b), np.asarray(d)])
                    for b, d in zip(base, delta)))
                self.panel_cache.put_series(digest, s)
                return s, True
        return self._resolve_series(job)

    def _submit_append_job(self, job):
        """One streaming append job: advance the base panel's carry
        checkpoint by the appended slice (O(ΔT)); a missing/stale
        checkpoint falls back to a full scan-form rebuild over the
        extended panel (degraded, never a failed job). Either way the
        NEW checkpoint is stored under the extended panel's digest, so
        the next append in the chain hits."""
        from ..parallel import sweep as sweep_mod
        from ..streaming import recurrent

        t0 = time.perf_counter()
        t0_wall = time.time()
        trace_pairs = obs.job_trace_pairs([job])
        if (not recurrent.supports_strategy(job.strategy)
                or job.strategy == "pairs"):
            # Validated-bad, the malformed-pairs discipline: the AppendBars
            # wire carries ONE panel, so two-legged strategies (and any
            # family without a streaming spec) complete loudly empty
            # instead of requeue-looping through leases.
            log.error("append job %s: strategy %r is not streamable over "
                      "AppendBars; completing with empty metrics", job.id,
                      job.strategy)
            return ([job], None, t0, 0, None)
        axes = wire.grid_from_proto(job.grid)
        grid = {k: np.asarray(v)
                for k, v in sweep_mod.product_grid(**axes).items()}
        cost = float(job.cost)
        ppy = int(job.periods_per_year or 252)
        skey = recurrent.stream_key(job.strategy, grid, cost, ppy)
        series, _ = self._resolve_append_series(job)
        fields = {
            f: np.asarray(getattr(series, f), np.float32)[None, :]
            for f in recurrent.stream_fields(job.strategy)}
        base_len = int(job.append_base_len)
        hit = False
        try:
            carry = (self.carry_store.get((job.panel_digest, skey))
                     if job.panel_digest else None)
            if carry is not None and carry.n_bars == series.n_bars:
                # Retried delivery of an already-advanced append: serve
                # the stored checkpoint, don't advance twice.
                hit = True
            else:
                carry = None
                if 0 < base_len < series.n_bars:
                    base_carry = self.carry_store.get(
                        (job.append_parent_digest, skey))
                    if (base_carry is not None
                            and base_carry.n_bars == base_len):
                        carry = recurrent.append_step(
                            base_carry,
                            {f: v[:, base_len:]
                             for f, v in fields.items()})
                        hit = True
                if carry is None:
                    carry = recurrent.build_carry(
                        job.strategy, fields, grid, cost=cost,
                        periods_per_year=ppy)
        except (ValueError, KeyError) as e:
            # Validated-bad (a grid the family cannot price, an empty
            # axis, ...): complete loudly empty — requeue-looping through
            # leases would never fix a malformed spec.
            log.error("append job %s: %s; completing with empty metrics",
                      job.id, e)
            return ([job], None, t0, 0, None)
        if job.panel_digest:
            self.carry_store.put((job.panel_digest, skey), carry)
        m = recurrent.finalize(carry)
        self._c_append["carry_hit" if hit else "full_reprice"].inc()
        # The append span carries the hit flag: obs.timeline charges hit
        # windows to the `carry_hit` pseudo-stage (the streaming twin of
        # panel_cache_hit), full reprices stay execute.
        obs.emit_span("worker.append", t0_wall,
                      time.perf_counter() - t0, pairs=trace_pairs,
                      job=job.id, carry_hit=hit, bars=series.n_bars,
                      delta_bars=series.n_bars - base_len)
        # Histogram only (no group=): an execute envelope span over the
        # SAME interval would tie worker.append at equal priority in
        # timeline attribution, and the tie-break (later t0) is clock
        # jitter — a served O(ΔT) append must never read as phantom
        # execute work.
        self._observe_submit(job.strategy, "append", t0)
        # donate=False: finalize's outputs may alias buffers the stored
        # carry still owns — donating them would invalidate the
        # checkpoint the next append in the chain advances.
        return ([job], _start_result_copy(m, donate=False), t0, 1, None)

    def _submit_scenario_group(self, job):
        """One coalesced scenario spec-batch job (JobSpec.scenario_batch):
        a single launch regenerates each of the K scenario panels
        IN-TRACE inside the fused sweep — the synthetic panels never
        exist in HBM (``lax.map`` over specs holds one scenario's working
        set at a time, so device bytes are O(1) in K; only the BASE panel
        rides the payload/digest legs). Every spec completes under its
        own queued job id, so queue semantics are per-scenario exactly as
        if the K jobs had dispatched materialized.

        Degradation: any fused-leg failure (unsupported family drifting
        past the dispatcher gate, kill switch flipped mid-lease, a trace
        error) drops to :meth:`_submit_scenario_materialized` — the
        in-process twin of the dispatcher's materialized path — and
        fires a flight-recorder anomaly so the demotion is capturable.
        An unresolvable BASE raises, leaving the lease to requeue (by
        then the dispatcher re-ships bytes): degraded, never a failed
        job."""
        from ..parallel import sweep as sweep_mod
        from ..scenarios import synth

        t0 = time.perf_counter()
        specs = list(job.scenario_batch)
        # Base resolution rides the ordinary digest machinery: host
        # cache -> inline bytes -> FetchPayload; raises on miss.
        series, _ = self._resolve_series(job)
        pseudo = [_ScenarioJob(s.id, job.grid, s.trace_id,
                               job.parent_span_id) for s in specs]
        try:
            if not self._fused_ops.scenario_fused_enabled():
                raise ValueError("DBX_SCENARIO_FUSED=0")
            base = {f: np.asarray(getattr(series, f), np.float32)
                    for f in ("open", "high", "low", "close", "volume")}
            n_bars = int(specs[0].n_bars) or series.n_bars
            block = max(int(specs[0].block), 1)
            regimes = max(int(specs[0].regimes), 1)
            axes = wire.grid_from_proto(job.grid)
            grid = {k: np.asarray(v, np.float32) for k, v
                    in sweep_mod.product_grid(**axes).items()}
            # ScenarioSpec.seed carries the EFFECTIVE 64-bit seed for
            # batch members (dispatcher-derived from host-precision
            # params) — the worker only splits it into the int31 words
            # the generator's key derivation folds.
            words = [synth.seed_words(int(s.seed)) for s in specs]
            m = self._fused_ops.fused_scenario_sweep(
                job.strategy, base,
                np.asarray([w[0] for w in words], np.int32),
                np.asarray([w[1] for w in words], np.int32),
                np.asarray([s.vol_scale for s in specs], np.float32),
                np.asarray([s.shock for s in specs], np.float32),
                grid, n_bars=n_bars, block=block, regimes=regimes,
                cost=float(job.cost),
                periods_per_year=int(job.periods_per_year or 252))
        except Exception as e:     # noqa: BLE001 — demote, never fail
            log.warning(
                "scenario batch %s (%s, %d specs): fused leg failed "
                "(%s); falling back to in-process materialization",
                job.id, job.strategy, len(specs), e)
            obs_flight.trigger(
                "scenario_fused_fail", subject=job.id,
                strategy=job.strategy, specs=len(specs), error=str(e))
            return self._submit_scenario_materialized(job, specs,
                                                      series, t0)
        self._c_scenario_route["fused"].inc(len(specs))
        P = sweep_mod.grid_size(grid) if grid else 1
        self._observe_submit(
            job.strategy, "scenario", t0,
            cold_key=("scenario", job.strategy, n_bars, block, regimes,
                      P, len(specs)),
            group=pseudo, bars=n_bars, combos=len(specs) * P)
        return [(pseudo, _start_result_copy(m), t0, len(specs), None)]

    def _submit_scenario_materialized(self, job, specs, series, t0):
        """The worker-side materialized rung of the scenario degradation
        ladder: host-generate each spec's panel (the same
        ``synth.generate`` program the dispatcher's materialized path
        runs, under the same effective seed — bit-identical bytes) and
        resubmit the batch as K ordinary inline-payload jobs through the
        normal routing. A spec whose generation raises is validated-bad
        and completes loudly empty (a malformed spec requeue-loops
        forever; the dispatcher's own materialized path would have
        failed it the same way)."""
        from ..scenarios import synth

        self._c_scenario_route["materialized"].inc(len(specs))
        expanded, bad = [], []
        for s in specs:
            # generate() reads only the shape/scale fields off params —
            # the effective seed arrives pre-derived in s.seed, so the
            # sequence-number field is irrelevant here.
            params = synth.ScenarioParams(
                n_bars=int(s.n_bars), block=int(s.block),
                regimes=int(s.regimes), vol_scale=float(s.vol_scale),
                shock=float(s.shock))
            try:
                panel = synth.generate(series, params, int(s.seed))
            except (ValueError, ZeroDivisionError) as e:
                log.error("scenario job %s: generation failed (%s); "
                          "completing with empty metrics", s.id, e)
                bad.append(s)
                continue
            spec = pb.JobSpec()
            spec.CopyFrom(job)
            del spec.scenario_batch[:]
            spec.ClearField("scenario")
            spec.id = s.id
            spec.trace_id = s.trace_id
            spec.ohlcv = data_mod.to_wire_bytes(panel)
            spec.panel_digest = ""
            spec.panel_bytes_len = 0
            expanded.append(spec)
        pending = []
        if bad:
            pending.append(
                ([_ScenarioJob(s.id, job.grid, s.trace_id,
                               job.parent_span_id) for s in bad],
                 None, t0, 0, None))
        if expanded:
            pending.extend(self.submit(expanded))
        return pending

    def _decode_group(self, group):
        """Cache-aware group decode (leg 1 — the pairs path drives
        :meth:`_resolve_series` per leg itself) under the traced
        ``worker.decode`` span. The span's ``cache_hit`` attr is True
        when EVERY panel came from the digest cache (decode skipped) —
        obs.timeline charges such windows to the ``panel_cache_hit``
        pseudo-stage instead of mis-reading a span-less gap as
        transport."""
        pairs = obs.job_trace_pairs(group)
        t0_wall = time.time()
        t_dec = time.perf_counter()
        series = []
        hits = 0
        for j in group:
            s, hit = self._resolve_series(j)
            series.append(s)
            hits += 1 if hit else 0
        dur = time.perf_counter() - t_dec
        self._h_decode.observe(dur)
        self._c_decode_bytes.inc(sum(len(j.ohlcv) for j in group))
        obs.emit_span("worker.decode", t0_wall, dur, pairs=pairs,
                      jobs=len(group), cache_hit=hits == len(group),
                      cache_hits=hits)
        return series, hits

    def _uniform_field_arrays(self, group, series, fields):
        """Per-field ``(n, T)`` arrays for a uniform-length group, plus an
        ``h2d_cache_hit`` flag. With content digests on every job and no
        mesh, each panel is cached on DEVICE as its ``(5, T)`` field block
        keyed by digest: a hit builds the group stack device-side — no
        host->device copy at all; a miss uploads once and primes the
        cache. Digestless jobs (hand-built specs, pre-dedupe dispatchers)
        and mesh workers (whose arrays must device_put with an explicit
        sharding) keep the host ``np.stack`` path."""
        digests = [j.panel_digest for j in group]
        if self._mesh is not None or not all(digests):
            return [np.stack([np.asarray(getattr(s, f)) for s in series])
                    for f in fields], False
        import jax.numpy as jnp

        rows, all_hit = [], True
        for d, s in zip(digests, series):
            blk = self.panel_cache.get_device(d)
            if blk is None:
                all_hit = False
                host = np.stack([np.asarray(f, np.float32) for f in s])
                blk = self._jax.device_put(host)
                self.panel_cache.put_device(d, blk, host.nbytes)
            rows.append(blk)
        idx = [data_mod.OHLCV._fields.index(f) for f in fields]
        return [jnp.stack([r[i] for r in rows]) for i in idx], all_hit

    def _finish_group(self, jobs, m, t0, n_real, job0, *,
                      h2d_hit: bool = False):
        """Shared tail of every sweep submit path: optional on-device top-k
        reduction, then the stacked async result copy. ``h2d_hit`` rides
        the pending entry so collect's d2h span can report that the
        submit-side panel upload was served from the device cache."""
        topk = None
        if job0.top_k > 0 and job0.wf_train == 0:
            metric = job0.rank_metric or "sharpe"
            # Grid size, not m.shape: reading a device array's shape is
            # free, but np.asarray would sync the pipeline here.
            P = wire.grid_n_combos(job0.grid)
            idx, m = _topk_reduce(m, metric, min(int(job0.top_k), P))
            topk = (idx, metric)
        return (jobs, _start_result_copy(m), t0, n_real, topk, h2d_hit)

    def submit(self, jobs) -> list:
        """Dispatch a batch: decode, transfer, launch kernels, start the
        device->host result copy — all without blocking on the device.

        Returns an opaque handle for :meth:`collect`. The 9 metric fields
        are stacked into ONE device array and fetched with a single async
        transfer: nine per-field ``np.asarray`` round-trips measured ~1.9 s
        per 100-job group on a remote-proxy chip vs ~1.3 s for the stacked
        copy, and ``copy_to_host_async`` lets the next batch's decode/H2D/
        compute proceed while this one's results stream back.
        """
        import jax.numpy as jnp

        from ..models import base as models_base
        from ..parallel import sweep as sweep_mod

        jobs = list(jobs)
        # Streaming append jobs peel off first: each advances (or
        # rebuilds) its own carry checkpoint — O(ΔT) work per job, no
        # batching needed or wanted (the carry is per-panel state).
        stream_pending = [self._submit_append_job(j) for j in jobs
                          if j.append_parent_digest]
        jobs = [j for j in jobs if not j.append_parent_digest]
        # Scenario spec batches peel next: each carrier JobSpec is ONE
        # fused generator x sweep launch that completes its K coalesced
        # scenario records individually (megakernel route).
        for j in [j for j in jobs if j.scenario_batch]:
            stream_pending.extend(self._submit_scenario_group(j))
        jobs = [j for j in jobs if not j.scenario_batch]
        # Route accounting for the materialized rung: scenario jobs that
        # arrive as ordinary concrete panels (old worker capability,
        # DBX_SCENARIO_FUSED=0, digestless base, unsupported family).
        n_mat = sum(1 for j in jobs if j.scenario.base_digest)
        if n_mat:
            self._c_scenario_route["materialized"].inc(n_mat)
        # Group stackable jobs: same strategy, grid, cost (and walk-forward
        # windowing). Mixed history lengths stack fine — both the fused
        # kernels (per-ticker t_real) and the generic path (pad_and_stack +
        # bar_mask) handle ragged groups — but lengths are bucketed by
        # power of two (on the wire byte length, which is linear in bars)
        # so co-batching never pads a job more than ~2x, and one oversized
        # job cannot push a whole group over the fused VMEM cap onto the
        # generic path.
        groups: dict[tuple, list[pb.JobSpec]] = {}
        for job in jobs:
            grid = wire.grid_from_proto(job.grid)
            key = (job.strategy,
                   tuple(sorted((k, v.tobytes()) for k, v in grid.items())),
                   # Digest-only dispatches ship no bytes; the stamped
                   # panel_bytes_len keeps them in the same length bucket
                   # as their full-payload twins. With the paged path
                   # live the bucket collapses to 0 — mixed lengths fuse.
                   self._length_bucket(job, grid),
                   (len(job.ohlcv2)
                    or job.panel_bytes_len2).bit_length(),   # 0 single-asset
                   job.cost, job.periods_per_year,
                   job.wf_train, job.wf_test, job.wf_metric,
                   job.top_k, job.rank_metric, job.best_returns)
            groups.setdefault(key, []).append(job)

        pending = stream_pending
        for group in groups.values():
            t0 = time.perf_counter()
            if not self._topk_request_ok(group):
                # Validated-bad, like a malformed pairs leg: complete with
                # empty blocks instead of requeue-looping through leases.
                pending.append((list(group), None, t0, 0, None))
                continue
            if group[0].best_returns and (group[0].strategy == "pairs"
                                          or group[0].wf_train > 0):
                # Validated-bad, like a bad top-k request: the DBXP contract
                # is single-asset full-history sweeps (the dispatcher CLI
                # enforces this; a hand-built spec gets a loud empty).
                log.error(
                    "jobs %s: best_returns is not supported for %s jobs; "
                    "completing empty", [j.id for j in group],
                    "pairs" if group[0].strategy == "pairs"
                    else "walk-forward")
                pending.append((list(group), None, t0, 0, None))
                continue
            if group[0].strategy == "pairs":
                pending.append(self._submit_pairs_group(group, t0))
                self._observe_submit(
                    "pairs", "pairs_wf" if group[0].wf_train > 0
                    else "pairs", t0, group=group)
                continue
            # The decode span adopts the GROUP's traces (a batch can hold
            # several groups; the batch-level context set by the worker
            # loop would attribute one group's decode to every job); a
            # digest-cache hit skips the decode and the span says so
            # (`cache_hit` attr).
            series, _ = self._decode_group(group)
            lengths = [s.n_bars for s in series]
            if group[0].wf_train > 0:
                pending.append(self._submit_walkforward_group(
                    group, series, lengths, t0))
                self._observe_submit(group[0].strategy, "walkforward", t0,
                                     group=group)
                continue
            if group[0].best_returns:
                pending.append(self._submit_best_returns_group(
                    group, series, lengths, t0))
                self._observe_submit(group[0].strategy, "best_returns", t0,
                                     group=group)
                continue
            # JobSpec.grid carries per-parameter AXES; the cartesian product
            # is materialized worker-side (backtesting.proto JobSpec.grid).
            axes = wire.grid_from_proto(group[0].grid)
            grid = sweep_mod.product_grid(**axes)
            strategy = models_base.get_strategy(group[0].strategy)
            ppy = group[0].periods_per_year or 252
            demotion = (self._fused_demotion_reason(group[0], axes, lengths)
                        if self.use_fused else None)
            fused_ok = self.use_fused and demotion is None
            t_max_g = int(max(lengths))
            if (not fused_ok and self._mesh is not None
                    and t_max_g > self._FUSED_MAX_BARS
                    and len(group) < self._mesh.devices.size):
                # Long-context route: a history too long for the fused
                # VMEM cap, on a meshed worker whose ticker axis cannot
                # fill the chips, shards its BAR axis instead of demoting
                # to one device's generic path.
                ts_reason = self._timeshard_reason(group[0], axes, lengths)
                if ts_reason is None:
                    log.info(
                        "jobs %s (%s) routed to the time-sharded "
                        "long-context path (%d bars over %d chips)",
                        [j.id for j in group], group[0].strategy, t_max_g,
                        self._mesh.devices.size)
                    pending.extend(self._submit_timeshard_groups(
                        group, series, lengths, t0, axes))
                    self._observe_submit(
                        group[0].strategy, "timeshard", t0,
                        cold_key=("timeshard", len(group), t_max_g)
                        + self._group_key(group[0], axes), group=group,
                        bars=t_max_g, combos=sweep_mod.grid_size(grid)
                        if grid else 1)
                    continue
                # The group-level gate uses min(lengths) for the halo
                # bound, so ONE short job in a ragged group would drag
                # every genuinely long job off the route. Re-gate per
                # job: the submit path already shards per length
                # subgroup, so a partial route is natural. The per-job
                # gate keeps the LONG-CONTEXT condition too — a short
                # job that merely shares the group must stay on the
                # (faster) single-device/fused path, not be dragged onto
                # distributed cumsums for a panel that fits one chip.
                ok_idx = [i for i, t in enumerate(lengths)
                          if int(t) > self._FUSED_MAX_BARS
                          and timeshard_route_reason(
                              group[0].strategy, axes, [int(t)],
                              self._mesh.devices.size) is None]
                if ok_idx:
                    log.info(
                        "jobs %s (%s) route time-sharded individually; "
                        "%s stay generic (%s)",
                        [group[i].id for i in ok_idx], group[0].strategy,
                        [group[i].id for i in range(len(group))
                         if i not in set(ok_idx)], ts_reason)
                    pending.extend(self._submit_timeshard_groups(
                        [group[i] for i in ok_idx],
                        [series[i] for i in ok_idx],
                        [int(lengths[i]) for i in ok_idx], t0, axes))
                    self._observe_submit(
                        group[0].strategy, "timeshard", t0,
                        cold_key=("timeshard", len(ok_idx),
                                  max(int(lengths[i]) for i in ok_idx))
                        + self._group_key(group[0], axes),
                        group=[group[i] for i in ok_idx],
                        bars=max(int(lengths[i]) for i in ok_idx),
                        combos=sweep_mod.grid_size(grid) if grid else 1)
                    rest = [i for i in range(len(group))
                            if i not in set(ok_idx)]
                    if not rest:
                        continue
                    # The remainder restarts the clock: its route
                    # observation (and completion elapsed) must not
                    # re-attribute the timeshard subset's submit wall.
                    t0 = time.perf_counter()
                    group = [group[i] for i in rest]
                    series = [series[i] for i in rest]
                    lengths = [int(lengths[i]) for i in rest]
                    # The remainder is a different (shorter) panel:
                    # re-evaluate the fused gate for it.
                    demotion = (self._fused_demotion_reason(
                        group[0], axes, lengths) if self.use_fused
                        else None)
                    fused_ok = self.use_fused and demotion is None
                    t_max_g = int(max(lengths))
                else:
                    log.warning(
                        "jobs %s (%s) are long-context (%d bars) but not "
                        "time-shardable (%s); falling through to the "
                        "generic path", [j.id for j in group],
                        group[0].strategy, t_max_g, ts_reason)
            if fused_ok:
                pending.extend(self._submit_fused_group(
                    group, series, lengths, axes, grid, t0))
                continue
            if (self.use_paged and demotion is not None
                    and group[0].strategy in self._FUSED_STRATEGIES
                    and t_max_g > self._FUSED_MAX_BARS):
                # Over-cap ragged groups route through paging FIRST: the
                # paged group key no longer buckets by length, so one
                # oversized panel would otherwise demote every under-cap
                # member of its merged group to the generic path. Split:
                # the under-cap subset keeps the fused (paged) route,
                # only the genuinely-long remainder stays demoted.
                ok_idx = [i for i, t in enumerate(lengths)
                          if int(t) <= self._FUSED_MAX_BARS]
                if ok_idx and len(ok_idx) < len(group) \
                        and self._fused_demotion_reason(
                            group[0], axes,
                            [int(lengths[i]) for i in ok_idx]) is None:
                    log.info(
                        "jobs %s (%s) route paged-fused under the VMEM "
                        "bar cap; %s stay demoted (%s)",
                        [group[i].id for i in ok_idx], group[0].strategy,
                        [group[i].id for i in range(len(group))
                         if i not in set(ok_idx)], demotion)
                    pending.extend(self._submit_fused_group(
                        [group[i] for i in ok_idx],
                        [series[i] for i in ok_idx],
                        [int(lengths[i]) for i in ok_idx], axes, grid, t0))
                    rest = [i for i in range(len(group))
                            if i not in set(ok_idx)]
                    # The remainder restarts the clock (the timeshard
                    # split's discipline): its route observation must not
                    # re-attribute the fused subset's submit wall.
                    t0 = time.perf_counter()
                    group = [group[i] for i in rest]
                    series = [series[i] for i in rest]
                    lengths = [int(lengths[i]) for i in rest]
                    t_max_g = int(max(lengths))
            if (demotion is not None
                    and group[0].strategy in self._FUSED_STRATEGIES):
                # A fleet silently dropping to the ~6x-slower generic
                # path is a throughput bug nobody can see; name the cap.
                log.warning(
                    "jobs %s (%s) demoted to the generic path: %s",
                    [j.id for j in group], group[0].strategy, demotion)
            if len(set(int(t) for t in lengths)) > 1:
                # The generic stack pads every series to the group max —
                # the padding-waste counter must see this path too.
                self._c_pad_bars["dense"].inc(
                    int(sum(t_max_g - int(t) for t in lengths)))
            batch, _, mask = data_mod.pad_and_stack(series)
            # One chunk-eligibility rule for both branches: the mesh and
            # single-device backends must agree on memory bounding.
            P = sweep_mod.grid_size(grid) if grid else 1
            chunk = (self.param_chunk
                     if self.param_chunk and P % self.param_chunk == 0
                     else None)
            if self._mesh is not None:
                # The generic path's multi-chip story already exists in
                # the library: device_put_sweep + sharded_sweep (tickers
                # over the mesh, grid replicated). The two memory valves
                # compose: the mesh divides the ticker axis, param_chunk
                # still bounds the param axis's live set per chip.
                from ..parallel import sharding as sharding_mod

                sh_panel, sh_grid, sh_mask, _ = (
                    sharding_mod.device_put_sweep(
                        self._mesh, batch,
                        {k: jnp.asarray(v) for k, v in grid.items()},
                        bar_mask=mask))
                m = sharding_mod.sharded_sweep(
                    self._mesh, sh_panel, strategy, sh_grid,
                    cost=group[0].cost, bar_mask=sh_mask,
                    periods_per_year=ppy, param_chunk=chunk)
            else:
                panel = type(batch)(*(jnp.asarray(f) for f in batch))
                kwargs = dict(cost=group[0].cost,
                              bar_mask=jnp.asarray(mask),
                              periods_per_year=ppy)
                if chunk:
                    m = sweep_mod.chunked_sweep(
                        panel, strategy, grid, param_chunk=chunk,
                        **kwargs)
                else:
                    m = sweep_mod.jit_sweep(panel, strategy, grid,
                                            **kwargs)
            route = ("generic"
                     + ("_mesh" if self._mesh is not None else ""))
            # Shape in the cold key: jit compiles per (rows, bars), so a
            # new group size IS a compile, not an execute.
            self._observe_submit(
                group[0].strategy, route, t0,
                cold_key=(route, len(group), t_max_g)
                + self._group_key(group[0], axes), group=group,
                bars=t_max_g, combos=P)
            pending.append(self._finish_group(group, m, t0, len(group),
                                              group[0]))
        return pending

    def _try_paged_submit(self, group, series, lengths, grid):
        """Paged fused submit: resolve the group against the device page
        pool (uploading only pool-missing pages) and sweep it through
        the page tables — one launch per page-count class, mixed lengths
        welcome. Returns ``(metrics, pool_warm)`` where ``pool_warm``
        means every page was already device-resident (no upload — the
        paged analogue of the device-block h2d cache hit), or None when
        the pool rejects the group (working set over the pool bound) —
        the caller falls back to the dense stacks, degraded never
        failed. Fields come from the paged registry itself
        (`fused.paged_fields`) so the tables can never be prepared for a
        different column set than `fused_paged_sweep` validates
        against."""
        prep = self.panel_cache.pages.prepare(
            [j.panel_digest for j in group], series,
            self._fused_ops.paged_fields(group[0].strategy))
        if prep is None:
            return None
        pool_arr, tables, info = prep
        if info["pad_bars_new"]:
            self._c_pad_bars["paged"].inc(info["pad_bars_new"])
        job0 = group[0]
        m = self._fused_ops.fused_paged_sweep(
            job0.strategy, pool_arr, tables,
            np.asarray(lengths, np.int32), grid,
            cost=float(job0.cost),
            periods_per_year=int(job0.periods_per_year or 252))
        return m, info["pages_new"] == 0

    def _tuned_schedule_for(self, job0, lengths, grid) -> dict | None:
        """Registry consultation at group-submit time (tune/ round 11):
        the tuned substrate schedule for this group's (family,
        shape-bucket, platform) — running a first-contact autotune under
        ``DBX_AUTOTUNE`` — or None (hardcoded defaults). NEVER raises:
        a broken registry or failed tune degrades to today's routing."""
        try:
            n_bars = int(max(lengths))
            n_combos = max((int(np.asarray(v).shape[0])
                            for v in grid.values()), default=1)
            bucket = self._tune.shape_bucket(n_bars, n_combos)
            family = job0.strategy
            sched = self.schedule_registry.lookup(family, bucket,
                                                  self._platform)
            mode = self._tune.autotune_mode()
            if (sched is None and mode != "off"
                    and (family, bucket) not in self._tuned_attempted):
                self._tuned_attempted.add((family, bucket))
                # page_bars joins the search space only under the model
                # prior: it binds at pool construction, so a live
                # measurement through the dense wrapper could not tell
                # the candidates apart anyway.
                sched = self._autotuner.tune(
                    family, bucket, self._platform, n_bars=n_bars,
                    n_combos=n_combos,
                    measure=(None if mode == "model"
                             else self._autotune_measure(job0, grid)),
                    paged=(mode == "model" and self.use_paged
                           and self._fused_ops.paged_supported(family)))
            if sched:
                self._publish_tuned_info(family, bucket, sched)
            return sched
        except Exception:
            log.exception("tuned-schedule consultation failed; serving "
                          "hardcoded substrate defaults")
            return None

    def _autotune_measure(self, job0, grid):
        """The live measurement harness handed to the autotuner: one
        representative single-ticker sweep of this group's family/grid
        under the candidate substrate tuple (warm run timed — compile
        excluded, it is what the fleet compile cache amortizes)."""
        spec = self._FUSED_STRATEGIES[job0.strategy]
        series, _hit = self._resolve_series(job0)
        arrays = [np.asarray(getattr(series, f), np.float32)[None, :]
                  for f in spec.fields]
        cost, ppy = job0.cost, job0.periods_per_year or 252
        jax = self._jax

        def measure(substrates: dict) -> float:
            with self._fused_ops.tuned_schedule(substrates):
                jax.block_until_ready(
                    spec.run(*arrays, grid, cost, ppy, None))
                t0 = time.perf_counter()
                jax.block_until_ready(
                    spec.run(*arrays, grid, cost, ppy, None))
                return time.perf_counter() - t0
        return measure

    def _publish_tuned_info(self, family: str, bucket: str,
                            sched: dict) -> None:
        """``dbx_tuned_substrate_info`` — the tuned twin of
        ``dbx_fused_substrate_info``: constant 1, labels carry which
        tuned substrates route this (family, shape-bucket). Fixed label
        keys ("default" = knob left on hardcoded routing); family and
        bucket are bounded (strategy registry x clamped pow2 rails)."""
        key = (family, bucket, tuple(sorted(sched.items())))
        if key in self._tuned_info_seen:
            return
        self._tuned_info_seen.add(key)
        table = next((v for k, v in sorted(sched.items())
                      if k.startswith("table_")), "default")
        self._obs.gauge(
            "dbx_tuned_substrate_info",
            help="constant 1; labels carry the tuned substrate schedule "
                 "serving this (kernel family, shape-bucket) — the "
                 "tuned-vs-default twin of dbx_fused_substrate_info",
            kernel=family, bucket=bucket,
            epilogue=sched.get("epilogue", "default"),
            table=table,
            lanes_cap=sched.get("lanes_cap", "default"),
            page_bars=sched.get("page_bars", "default")).set(1)

    def _submit_fused_group(self, group, series, lengths, axes, grid, t0,
                            *, allow_paged: bool = True):
        """Tuned-schedule activation around one fused group submit: the
        registry's winner for this (family, shape-bucket) routes every
        substrate resolver the wrappers call inside — below explicit
        args and env knobs, above hardcoded defaults — and folds into
        the jit cache keys exactly like an env knob flip (the wrappers'
        static args and the mesh path's substrate_defaults() key both
        resolve through the same chain)."""
        sched = self._tuned_schedule_for(group[0], lengths, grid)
        if not sched:
            return self._submit_fused_group_routed(
                group, series, lengths, axes, grid, t0,
                allow_paged=allow_paged)
        with self._fused_ops.tuned_schedule(sched):
            return self._submit_fused_group_routed(
                group, series, lengths, axes, grid, t0,
                allow_paged=allow_paged)

    def _submit_fused_group_routed(self, group, series, lengths, axes,
                                   grid, t0, *, allow_paged: bool = True):
        """Fused submit of one (possibly mixed-length) group.

        Paged route first (digest-keyed device pages + page tables —
        round 10); dense stacks as the fallback for digestless jobs,
        mesh workers, pool rejections and ``DBX_PAGED=0``. Repeat-last
        padding + per-ticker lengths either way: pad bars earn zero
        return and hold the final position, and all metric reductions
        use each ticker's real length. Only the columns the kernel
        consumes (spec.fields) reach the device. Returns a LIST of
        pending entries for :meth:`collect` — one normally; several when
        a pool-rejected merged mixed-length group re-splits by the
        power-of-two length bucket so the dense fallback keeps the
        pre-paging ~2x pad bound instead of padding every ticker to the
        merged group's max.
        """
        from ..parallel import sweep as sweep_mod
        job0 = group[0]
        spec = self._FUSED_STRATEGIES[job0.strategy]
        ppy = job0.periods_per_year or 252
        cost = job0.cost
        h2d_hit = False
        m = None
        paged = False
        ragged = len(set(int(x) for x in lengths)) > 1
        if (allow_paged and self.use_paged
                and all(j.panel_digest for j in group)
                and self._fused_ops.paged_supported(job0.strategy)):
            paged_out = self._try_paged_submit(group, series, lengths,
                                               grid)
            paged = paged_out is not None
            if paged:
                # A fully pool-warm group skipped every upload: collect's
                # d2h span reports it exactly like a device-block h2d hit.
                m, h2d_hit = paged_out
            if m is None and ragged:
                buckets: dict[int, list[int]] = {}
                for i, j in enumerate(group):
                    b = (len(j.ohlcv) or j.panel_bytes_len).bit_length()
                    buckets.setdefault(b, []).append(i)
                if len(buckets) > 1:
                    log.warning(
                        "jobs %s (%s): page pool rejected the merged "
                        "group; re-splitting into %d dense length "
                        "buckets", [j.id for j in group], job0.strategy,
                        len(buckets))
                    out = []
                    sub_t0 = t0
                    for _, idx in sorted(buckets.items()):
                        out.extend(self._submit_fused_group(
                            [group[i] for i in idx],
                            [series[i] for i in idx],
                            [lengths[i] for i in idx], axes, grid,
                            sub_t0, allow_paged=False))
                        # Later buckets restart the clock (the split
                        # disciplines' rule: one subset's submit wall
                        # must not re-attribute to the next).
                        sub_t0 = time.perf_counter()
                    return out
        self._observe_substrates(job0.strategy)
        if m is None:
            if not ragged:
                arrays, h2d_hit = self._uniform_field_arrays(
                    group, series, spec.fields)
                t_real = None
            else:
                # Column-wise stack (pad_and_stack would also pad the
                # unused fields — wasted memcpy on the hot path).
                t_max = int(max(lengths))
                arrays = [_stack_field_ragged(series, t_max, f)
                          for f in spec.fields]
                t_real = np.asarray(lengths, np.int32)
                self._c_pad_bars["dense"].inc(
                    int(sum(t_max - int(t) for t in lengths)))
            if self._mesh is not None:
                run = spec.run

                def runner(*a, run=run, grid=grid, cost=cost, ppy=ppy):
                    return run(*a[:-1], grid, cost, ppy, a[-1])

                m = self._mesh_call(
                    ("fused",) + self._group_key(job0, axes),
                    runner, arrays, t_real)
            else:
                m = spec.run(*arrays, grid, cost, ppy, t_real)
        # paged implies mesh is None, so the suffix is vacuous there.
        route = (("paged" if paged else "fused")
                 + ("_mesh" if self._mesh is not None else ""))
        # Shape in the cold key: jit compiles per (rows, bars), so a new
        # group size IS a compile, not an execute.
        self._observe_submit(
            job0.strategy, route, t0,
            cold_key=(route, len(group), int(max(lengths)))
            + self._group_key(job0, axes), group=group,
            bars=int(max(lengths)),
            combos=sweep_mod.grid_size(grid) if grid else 1)
        return [self._finish_group(group, m, t0, len(group), job0,
                                   h2d_hit=h2d_hit)]

    def _submit_best_returns_group(self, group, series, lengths, t0):
        """Fleet-portfolio jobs (proto ``JobSpec.best_returns``): sweep the
        grid, pick each job's best combo by ``rank_metric`` (NaN-last,
        direction-aware — ``sweep.best_params``'s discipline), re-price the
        winner, and ship a DBXP block: grid index + 9 metric values + the
        per-bar net-return series. Sweep -> selection -> repricing run in
        ONE jitted trace per group (the ``sweep_and_compose`` discipline:
        the (n, P) intermediates never leave the device); the three result
        arrays start async d2h copies so the next batch overlaps.

        Uses the generic sweep path (the repricing needs positions, which
        the fused kernels do not materialize); selection is identical
        either way.
        """
        import jax.numpy as jnp

        from ..ops.metrics import Metrics

        job0 = group[0]
        axes = wire.grid_from_proto(job0.grid)
        metric = job0.rank_metric or "sharpe"
        if metric not in Metrics._fields:
            # Validated-bad, the _topk_request_ok discipline: a hand-built
            # spec naming an unknown metric completes empty with a loud
            # error instead of crashing the worker inside the trace.
            log.error("jobs %s: unknown best_returns rank metric %r; "
                      "completing empty", [j.id for j in group], metric)
            return (list(group), None, t0, 0, None)
        batch, _, mask = data_mod.pad_and_stack(series)
        panel_arrays = [np.asarray(f) for f in batch]
        fn = self._best_returns_fn(job0, axes, metric)
        m_best, idx, returns = fn(
            type(batch)(*(jnp.asarray(a) for a in panel_arrays)),
            jnp.asarray(mask))
        stacked = _start_result_copy(m_best)
        for arr in (idx, returns):
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass
        return (list(group), stacked, t0, len(group),
                {"kind": "returns", "idx": idx, "returns": returns,
                 "metric": metric, "lens": lengths})

    def _best_returns_fn(self, job0, axes, metric: str):
        """Build (and cache) the one-trace sweep->select->reprice function
        for a (strategy, grid, cost, ppy, metric) signature."""
        import jax
        import jax.numpy as jnp

        from ..models import base as models_base
        from ..ops import pnl as pnl_mod
        from ..ops.metrics import Metrics
        from ..parallel import sweep as sweep_mod

        key = (("best_returns",) + self._group_key(job0, axes) + (metric,))
        fn = self._mesh_fns.get(key)   # shared FIFO-evicted compile cache
        if fn is not None:
            return fn

        strategy = models_base.get_strategy(job0.strategy)
        cost = job0.cost
        ppy = job0.periods_per_year or 252
        grid = {k: jnp.asarray(v)
                for k, v in sweep_mod.product_grid(**axes).items()}

        @jax.jit
        def f(panel, bar_mask):
            m = sweep_mod.run_sweep(panel, strategy, grid, cost=cost,
                                    bar_mask=bar_mask,
                                    periods_per_year=ppy)
            # Selection delegates to THE shared implementation
            # (sweep.best_params: NaN-last, direction-aware) so this path
            # can never drift from walk-forward/portfolio selection.
            _, chosen, idx = sweep_mod.best_params(
                getattr(m, metric), grid, metric=metric, return_index=True)
            idx = idx.astype(jnp.int32)                          # (n,)

            def per_ticker(o1, mask1, p1):
                pos = strategy.positions(o1, p1)
                # run_sweep's padding discipline: HOLD the last valid
                # position through padded bars (zero return, zero
                # turnover on repeat-last closes).
                last_idx = jnp.maximum(
                    jnp.sum(mask1.astype(jnp.int32), axis=-1) - 1, 0)
                pos_last = jnp.take(pos, last_idx, axis=-1)
                return jnp.where(mask1, pos, pos_last)

            pos = jax.vmap(per_ticker)(panel, bar_mask, chosen)
            res = pnl_mod.backtest_prefix(panel.close, pos, cost=cost)
            m_best = Metrics(*(jnp.take_along_axis(f_, idx[:, None], axis=1)
                               for f_ in m))                     # (n, 1)
            return m_best, idx, res.returns

        if len(self._mesh_fns) >= self._MESH_FN_CAP:
            self._evict_mesh_fn()
        self._mesh_fns[key] = f
        return f

    def _submit_walkforward_group(self, group, series, lengths, t0):
        """Walk-forward jobs (proto ``JobSpec.wf_*``): per refit window,
        train-span sweep -> per-ticker argmax by ``wf_metric`` ->
        out-of-sample repricing on the next ``wf_test`` bars; the DBXM
        result is ONE stitched OOS metrics row per job, not a per-combo
        matrix. Jobs too short for a single train+test window complete
        with an empty block and a loud error. Uniform groups shard over
        the chip mesh (the refit scan's carries are per-ticker, so rows
        are independent); ragged groups refit per job single-device."""
        import logging

        import jax.numpy as jnp

        from ..models import base as models_base
        from ..ops.metrics import Metrics
        from ..parallel import sweep as sweep_mod, walkforward

        log = logging.getLogger("dbx.compute")
        job0 = group[0]
        need = job0.wf_train + job0.wf_test
        metric = job0.wf_metric or "sharpe"
        if metric not in Metrics._fields:
            # Validated-bad, like a malformed pairs leg: raising here would
            # requeue the group through lease expiry forever.
            log.error("walk-forward jobs %s request unknown selection "
                      "metric %r (known: %s); completing with empty metrics",
                      [j.id for j in group], metric,
                      ", ".join(Metrics._fields))
            return (list(group), None, t0, 0, None)
        good, bad = [], []
        for j, s, n_bars in zip(group, series, lengths):
            if job0.wf_test <= 0 or n_bars < need:
                log.error(
                    "walk-forward job %s needs wf_test > 0 and >= %d bars "
                    "(train %d + test %d), has %d; completing with empty "
                    "metrics", j.id, need, job0.wf_train, job0.wf_test,
                    n_bars)
                bad.append(j)
            else:
                good.append((j, s))
        if not good:
            return (bad, None, t0, 0, None)

        axes = wire.grid_from_proto(job0.grid)
        grid = sweep_mod.product_grid(
            **{k: jnp.asarray(v) for k, v in axes.items()})
        strategy = models_base.get_strategy(job0.strategy)
        kwargs = dict(train=job0.wf_train, test=job0.wf_test,
                      metric=metric, cost=job0.cost,
                      periods_per_year=job0.periods_per_year or 252)
        uniform = len({s.n_bars for _, s in good}) == 1
        panel_cls = type(good[0][1])
        if uniform:
            arrays = [np.stack([np.asarray(getattr(s, f)) for _, s in good])
                      for f in good[0][1]._fields]
        # Fused-train route (VERDICT r4 item 4): when the grid is large
        # enough that the per-window train sweep dominates, run phase 1 on
        # the fused Pallas kernel — walk_forward_fused's two-phase split
        # (one stacked train sweep for ALL refit windows, then re-price
        # only each ticker's chosen param). The generic single-program
        # walk_forward wins below the threshold (bench: 11.5M/s generic vs
        # 5.5M/s fused at P=400), so routing is by grid size, with the
        # same fused eligibility table and rounding-twin caveats as the
        # plain sweep path (train span plays the role of the bar count).
        fused_wf = (self.use_fused and uniform
                    and sweep_mod.grid_size(grid) >=
                    self._WF_FUSED_MIN_COMBOS
                    and self._fused_demotion_reason(
                        job0, axes, [job0.wf_train]) is None)
        if fused_wf:
            spec = self._FUSED_STRATEGIES[job0.strategy]
            prod_np = {k: np.asarray(v)
                       for k, v in sweep_mod.product_grid(**axes).items()}
            cost = job0.cost
            ppy = kwargs["periods_per_year"]

            def train_fn(*fs):
                return spec.run(*fs, prod_np, cost, ppy, None)

            log.info("walk-forward jobs %s (%s, P=%d) using the "
                     "fused-train route", [j.id for j, _ in good],
                     job0.strategy, sweep_mod.grid_size(grid))
            self._observe_substrates(job0.strategy)
            if self._mesh is not None:
                def runner(*blks):
                    r = walkforward.walk_forward_fused(
                        panel_cls(*blks[:-1]), strategy, dict(grid),
                        train_fn, fields=spec.fields, **kwargs)
                    return Metrics(*(f[:, None] for f in r.oos_metrics))

                m = self._mesh_call(
                    ("wf-fused",) + self._group_key(job0, axes)
                    + (job0.wf_train, job0.wf_test, metric),
                    runner, arrays, None)
                return ([j for j, _ in good] + bad, _start_result_copy(m),
                        t0, len(good), None)
            panel = panel_cls(*(jnp.asarray(a) for a in arrays))
            m = walkforward.walk_forward_fused(
                panel, strategy, dict(grid), train_fn, fields=spec.fields,
                **kwargs).oos_metrics
            m = Metrics(*(f[:, None] for f in m))   # one OOS row per job
            return ([j for j, _ in good] + bad, _start_result_copy(m), t0,
                    len(good), None)
        if uniform and self._mesh is not None:
            # The per-window refit is row-parallel (per-ticker scan +
            # argmax, no cross-row interaction), so walk-forward groups
            # shard over the chip mesh like any sweep. The runner returns
            # (rows, 1) metric columns so the row-sharded out_specs fit.
            def runner(*blks):
                r = walkforward.walk_forward(
                    panel_cls(*blks[:-1]), strategy, dict(grid), **kwargs)
                return Metrics(*(f[:, None] for f in r.oos_metrics))

            m = self._mesh_call(
                ("wf",) + self._group_key(job0, axes)
                + (job0.wf_train, job0.wf_test, metric),
                runner, arrays, None)
            return ([j for j, _ in good] + bad, _start_result_copy(m), t0,
                    len(good), None)
        if uniform:
            panel = panel_cls(*(jnp.asarray(a) for a in arrays))
            m = walkforward.walk_forward(panel, strategy, dict(grid),
                                         **kwargs).oos_metrics
        else:
            # Window starts are global bar indices: ragged histories can't
            # share one scan, so they refit per job (grouping buckets
            # lengths by power of two, keeping this rare and bounded).
            rows = [walkforward.walk_forward(
                type(s)(*(jnp.asarray(np.asarray(f))[None, :] for f in s)),
                strategy, dict(grid), **kwargs).oos_metrics
                for _, s in good]
            m = Metrics(*(jnp.concatenate(f, axis=0) for f in zip(*rows)))
        m = Metrics(*(f[:, None] for f in m))   # one OOS row per job
        return ([j for j, _ in good] + bad, _start_result_copy(m), t0,
                len(good), None)

    def _submit_pairs_group(self, group, t0):
        """Two-legged jobs: stack both legs, run the pairs sweep.

        The fused pairs kernel takes per-pair ragged lengths; on CPU the
        generic path has no mask support, so ragged groups fall back to a
        per-job loop (grouping already buckets lengths by power of two, so
        this is rare and bounded).
        """
        import logging

        import jax.numpy as jnp

        from ..models import pairs as pairs_mod
        from ..parallel import sweep as sweep_mod

        log = logging.getLogger("dbx.compute")
        # Per-job validation at decode time: a malformed pair (missing
        # second leg, or legs of different lengths — padding one leg would
        # fabricate bars the PnL treats as real) is completed with an EMPTY
        # metric block and a loud error rather than poisoning the whole
        # co-batched group or looping forever through lease requeues.
        job0 = group[0]
        wf = job0.wf_train > 0
        if wf:
            # Validate the walk-forward request once for the group (the
            # same gates as the single-asset path).
            metric = job0.wf_metric or "sharpe"
            from ..ops.metrics import Metrics

            if job0.wf_test <= 0 or metric not in Metrics._fields:
                log.error(
                    "pairs walk-forward jobs %s need wf_test > 0 and a "
                    "known metric (got test=%d, metric=%r); completing "
                    "with empty metrics", [j.id for j in group],
                    job0.wf_test, metric)
                return (list(group), None, t0, 0, None)
        good, bad = [], []
        trace_pairs = obs.job_trace_pairs(group)
        t0_wall = time.time()
        t_dec = time.perf_counter()
        hits = 0
        for j in group:
            if not j.ohlcv2 and not j.panel_digest2:
                log.error("pairs job %s has no second leg (ohlcv2); "
                          "completing with empty metrics", j.id)
                bad.append(j)
                continue
            y, hit_y = self._resolve_series(j)
            x, hit_x = self._resolve_series(j, leg2=True)
            if y.n_bars != x.n_bars:
                log.error("pairs job %s legs differ in length (%d vs "
                          "%d); completing with empty metrics", j.id,
                          y.n_bars, x.n_bars)
                bad.append(j)
                continue
            if wf and y.n_bars < job0.wf_train + job0.wf_test:
                log.error(
                    "pairs walk-forward job %s needs >= %d bars "
                    "(train %d + test %d), has %d; completing with "
                    "empty metrics",
                    j.id, job0.wf_train + job0.wf_test, job0.wf_train,
                    job0.wf_test, y.n_bars)
                bad.append(j)
                continue
            hits += 1 if (hit_y and hit_x) else 0
            good.append((j, y, x))
        dur = time.perf_counter() - t_dec
        self._h_decode.observe(dur)
        obs.emit_span("worker.decode", t0_wall, dur, pairs=trace_pairs,
                      jobs=len(group),
                      cache_hit=bool(good) and hits == len(good),
                      cache_hits=hits)
        self._c_decode_bytes.inc(
            sum(len(j.ohlcv) + len(j.ohlcv2) for j in group))
        if not good:
            return (bad, None, t0, 0, None)
        group = [j for j, _, _ in good]
        ys = [y for _, y, _ in good]
        xs = [x for _, _, x in good]
        axes = wire.grid_from_proto(group[0].grid)
        grid = sweep_mod.product_grid(**axes)
        ppy = group[0].periods_per_year or 252
        cost = group[0].cost
        lens = np.asarray([y.n_bars for y in ys], np.int32)
        t_max = int(lens.max())
        y_close = _stack_field_ragged(ys, t_max)
        x_close = _stack_field_ragged(xs, t_max)
        uniform = len(set(int(v) for v in lens)) == 1
        if wf:
            # Walk-forward pairs (JobSpec.wf_* + strategy "pairs"): one
            # stitched OOS metrics row per job, like the single-asset path.
            # Window starts are global bar indices, so ragged groups refit
            # per job (grouping buckets lengths by power of two — rare).
            from ..ops.metrics import Metrics
            from ..parallel import walkforward

            kwargs = dict(train=job0.wf_train, test=job0.wf_test,
                          metric=job0.wf_metric or "sharpe", cost=cost,
                          periods_per_year=ppy)
            if uniform and self._mesh is not None:
                # Row-parallel exactly like the single-asset wf path: the
                # per-window refit has no cross-pair interaction, so
                # uniform groups shard over the chip mesh.
                def runner(yb, xb, tr):
                    r = walkforward.walk_forward_pairs(yb, xb, dict(grid),
                                                       **kwargs)
                    return Metrics(*(f[:, None] for f in r.oos_metrics))

                m = self._mesh_call(
                    ("pairs-wf",) + self._group_key(job0, axes)
                    + (job0.wf_train, job0.wf_test, kwargs["metric"]),
                    runner, [y_close, x_close], None)
                return self._finish_group(list(group) + bad, m, t0,
                                          len(group), job0)
            if uniform:
                m = walkforward.walk_forward_pairs(
                    jnp.asarray(y_close), jnp.asarray(x_close), dict(grid),
                    **kwargs).oos_metrics
            else:
                rows = [walkforward.walk_forward_pairs(
                    jnp.asarray(y_close[i:i + 1, :int(lens[i])]),
                    jnp.asarray(x_close[i:i + 1, :int(lens[i])]),
                    dict(grid), **kwargs).oos_metrics
                    for i in range(len(group))]
                m = Metrics(*(jnp.concatenate(f, axis=0)
                              for f in zip(*rows)))
            m = Metrics(*(f[:, None] for f in m))   # one OOS row per job
            return self._finish_group(list(group) + bad, m, t0,
                                      len(group), job0)
        lb = np.asarray(grid.get("lookback", np.empty(0)))
        n_lb = int(np.unique(np.round(lb)).size)
        demotion = None
        if lb.size == 0:
            demotion = "no 'lookback' axis in grid"
        elif not np.allclose(lb, np.round(lb)):
            demotion = "non-integral lookback values"
        elif n_lb > self._FUSED_MAX_WINDOWS:
            demotion = (f"{n_lb} distinct lookbacks exceed the kernel cap "
                        f"of {self._FUSED_MAX_WINDOWS}")
        elif t_max > self._FUSED_MAX_BARS:
            demotion = (f"{t_max} bars exceed the kernel VMEM cap of "
                        f"{self._FUSED_MAX_BARS}")
        if ((not self.use_fused or demotion is not None)
                and self._mesh is not None and uniform
                and t_max > self._FUSED_MAX_BARS
                and len(group) < self._mesh.devices.size):
            # Long-context pairs: shard the bar axis over the chips (the
            # single-asset _submit_timeshard_groups discipline; ragged
            # groups keep the per-job generic loop — they cannot share
            # one padded panel). Grid gates are the SHARED helper.
            ts_reason = ("no 'lookback' axis in grid" if lb.size == 0
                         else self._timeshard_window_reason(
                             lb, int(np.asarray(grid["lookback"]).size),
                             t_max, what="lookback"))
            if ts_reason is None:
                log.info(
                    "jobs %s (pairs) routed to the time-sharded "
                    "long-context path (%d bars over %d chips)",
                    [j.id for j in group], t_max,
                    self._mesh.devices.size)
                return self._submit_pairs_timeshard(
                    group, bad, ys, xs, t_max, t0, axes, grid)
            log.warning(
                "jobs %s (pairs) are long-context (%d bars) but not "
                "time-shardable (%s); falling through to the generic "
                "path", [j.id for j in group], t_max, ts_reason)
        if self.use_fused and demotion is not None:
            log.warning("jobs %s (pairs) demoted to the generic path: %s",
                        [j.id for j in group], demotion)
        if self.use_fused and demotion is None:
            from ..ops import fused

            self._observe_substrates("pairs")
            plb = np.asarray(grid["lookback"])
            pze = np.asarray(grid["z_entry"])
            pzx = (np.asarray(grid["z_exit"]) if "z_exit" in grid else 0.0)
            t_real = None if uniform else lens
            if self._mesh is not None:
                def runner(yb, xb, tr):
                    return fused.fused_pairs_sweep(
                        yb, xb, plb, pze, z_exit=pzx, t_real=tr, cost=cost,
                        periods_per_year=ppy)

                m = self._mesh_call(
                    ("pairs-fused",) + self._group_key(group[0], axes),
                    runner, [y_close, x_close], t_real)
            else:
                m = fused.fused_pairs_sweep(
                    y_close, x_close, plb, pze, z_exit=pzx, t_real=t_real,
                    cost=cost, periods_per_year=ppy)
        elif uniform:
            if self._mesh is not None:
                def runner(yb, xb, tr):
                    return pairs_mod.run_pairs_sweep(
                        yb, xb, dict(grid), cost=cost, periods_per_year=ppy)

                m = self._mesh_call(
                    ("pairs-generic",) + self._group_key(group[0], axes),
                    runner, [y_close, x_close], None)
            else:
                m = pairs_mod.run_pairs_sweep(
                    jnp.asarray(y_close), jnp.asarray(x_close), dict(grid),
                    cost=cost, periods_per_year=ppy)
        else:
            rows = [pairs_mod.run_pairs_sweep(
                jnp.asarray(y_close[i:i + 1, :int(lens[i])]),
                jnp.asarray(x_close[i:i + 1, :int(lens[i])]), dict(grid),
                cost=cost, periods_per_year=ppy)
                for i in range(len(group))]
            m = type(rows[0])(*(jnp.concatenate(f, axis=0)
                                for f in zip(*rows)))
        return self._finish_group(list(group) + bad, m, t0, len(group),
                                  group[0])

    def _submit_pairs_timeshard(self, group, bad, ys, xs, t, t0,
                                axes, grid):
        """Uniform long-context pairs group: both legs' bar axes sharded
        over the chip mesh via ``timeshard.sharded_pairs_backtest``, one
        sub-backtest per grid combo (the ``_submit_timeshard_groups``
        discipline applied to the two-legged panel). Legs re-stack
        through ``_stack_field_ragged`` so the repeat-last padding (the
        t_real dead-bar contract) stays the one shared implementation."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..ops.metrics import Metrics
        from ..parallel import timeshard

        job0 = group[0]
        tmesh = self._time_mesh()
        n_dev = tmesh.devices.size
        T_pad = -(-t // n_dev) * n_dev
        cost = float(job0.cost)
        ppy = int(job0.periods_per_year or 252)
        lbs = np.asarray(grid["lookback"])
        zes = np.asarray(grid["z_entry"])
        zxs = (np.asarray(grid["z_exit"]) if "z_exit" in grid
               else np.zeros_like(zes))
        combos = tuple(
            (int(round(float(lbs[i]))), float(zes[i]), float(zxs[i]))
            for i in range(lbs.size))

        sharding = NamedSharding(tmesh, P(None, timeshard.TIME_AXIS))
        y = jax.device_put(_stack_field_ragged(ys, T_pad), sharding)
        x = jax.device_put(_stack_field_ragged(xs, T_pad), sharding)
        t_real = None if t == T_pad else t
        key = (("timeshard-pairs",) + self._group_key(job0, axes)
               + (t, T_pad))
        run = self._mesh_fns.get(key)
        if run is None:
            def run(yb, xb, _tr=t_real):
                ms = [timeshard.sharded_pairs_backtest(
                          tmesh, yb, xb, lkb, ze, z_exit=zx, cost=cost,
                          periods_per_year=ppy,
                          axis_name=timeshard.TIME_AXIS, t_real=_tr)
                      for (lkb, ze, zx) in combos]
                return Metrics(*(jnp.stack(cols, axis=-1)
                                 for cols in zip(*ms)))

            run = jax.jit(run)
            if len(self._mesh_fns) >= self._MESH_FN_CAP:
                self._evict_mesh_fn()
            self._mesh_fns[key] = run
        return self._finish_group(list(group) + bad, run(y, x), t0,
                                  len(group), job0)

    def collect(self, pending) -> list[Completion]:
        """Block for a submitted batch's results and pack completions."""
        from ..ops.metrics import Metrics

        out: list[Completion] = []
        for entry in pending:
            # Entries are 5-tuples from the legacy paths and 6-tuples from
            # _finish_group (the trailing h2d_hit flag).
            group, stacked, t0, n_real, extra = entry[:5]
            h2d_hit = bool(entry[5]) if len(entry) > 5 else False
            t_wait = time.perf_counter()
            if stacked is None:
                host = None
            else:
                # The blocking device drain, traced per group: the d2h
                # stage of each job's timeline (the worker.collect span
                # above it covers the whole pending entry). cache_hit here
                # reports that the SUBMIT-side panel upload was served
                # from the device digest cache (no h2d for this group's
                # panels); the drain itself is real work either way.
                with obs.trace_context(obs.job_trace_pairs(group)), \
                        obs.span("worker.d2h", jobs=len(group),
                                 cache_hit=h2d_hit):
                    host = np.asarray(stacked)
            if host is not None:
                # The blocking d2h drain: everything after here is host-side
                # packing. Combo credit counts only real jobs (mesh pad rows
                # are compute, not results) and is derived from each job's
                # GRID, not the result shape — a top-k/best_returns group
                # ships k (or 1) rows but computed the full grid, and the
                # dispatcher's backtests_per_sec credits grid combos too
                # (the two gauges must agree).
                self._h_collect.observe(time.perf_counter() - t_wait)
                self._c_d2h_bytes.inc(host.nbytes)
                n_rows = min(host.shape[1], n_real)
                combos = sum(wire.grid_n_combos(job.grid)
                             for job in group[:n_rows])
                self._c_backtests.inc(combos)
                self._bt_rate.add(combos)
            idx_host = ret_host = lens = None
            mode = None
            if isinstance(extra, dict):          # best_returns (DBXP) group
                mode = extra["kind"]
                idx_host = np.asarray(extra["idx"])
                ret_host = np.asarray(extra["returns"])
                lens = extra["lens"]
            elif extra is not None:              # top-k (DBXS) group
                mode = "topk"
                idx_host = np.asarray(extra[0])
            elapsed = time.perf_counter() - t0
            per_job = elapsed / max(len(group), 1)
            # n_real (the jobs actually computed), NOT host.shape[1]: the
            # mesh path pads rows to a chip multiple, and a pad row must
            # never be reported as a validated-bad job's "result".
            n_rows = 0 if host is None else min(host.shape[1], n_real)
            for i, job in enumerate(group):
                if i < n_rows:
                    row = Metrics(*(host[k, i] for k in range(9)))
                    if mode == "topk":
                        blob = wire.topk_to_bytes(idx_host[i], row, extra[1])
                    elif mode == "returns":
                        # Trim to the job's real history: padded bars earn
                        # exactly zero (repeat-last close + held position)
                        # but belong to the group, not the job.
                        blob = wire.best_returns_to_bytes(
                            int(idx_host[i]), row,
                            ret_host[i, :int(lens[i])], extra["metric"])
                    else:
                        blob = wire.metrics_to_bytes(row)
                else:
                    blob = b""   # validated-bad job: complete, no result
                out.append(Completion(job.id, blob, per_job,
                                      trace_id=job.trace_id))
        return out

    def process(self, jobs) -> list[Completion]:
        return self.collect(self.submit(jobs))


def _timeshard_window_reason(wins, n_combos: int, t_min: int, n_dev: int, *,
                             halo_bound: bool = True,
                             what: str = "window") -> str | None:
    """Shared grid gates of EVERY time-sharded route (single-asset,
    pairs, and the slice worker — one implementation so they cannot
    drift): per-combo compile cap, integral windows >= 1, and the
    halo-fits-one-per-chip-block bound."""
    wins = np.asarray(wins, np.float64)
    if n_combos == 0 or wins.size == 0:
        return "empty grid"
    if n_combos > JaxSweepBackend._TIMESHARD_MAX_COMBOS:
        return (f"{n_combos} grid combos exceed the per-combo compile "
                f"cap of {JaxSweepBackend._TIMESHARD_MAX_COMBOS}")
    if not np.allclose(wins, np.round(wins)):
        return f"non-integral {what} values"
    if wins.min() < 1:
        return f"{what} values below 1"
    if halo_bound:
        block = -(-int(t_min) // n_dev)
        if int(wins.max()) > block:
            return (f"max {what} {int(wins.max())} exceeds the "
                    f"{block}-bar per-chip block; the halo exchange "
                    "needs the window to fit one neighbor block")
    return None


def timeshard_route_reason(strategy: str, axes, lengths,
                           n_dev: int) -> str | None:
    """None when a long-context single-asset group can route to the
    time-sharded backtests over an ``n_dev``-chip time axis; otherwise
    why it stays on the generic path. Shared by the single-host backend
    (``JaxSweepBackend._timeshard_reason``) and the slice worker."""
    from ..parallel import sweep as sweep_mod

    fam = JaxSweepBackend._TIMESHARD_STRATEGIES.get(strategy)
    if fam is None:
        return f"strategy {strategy!r} has no time-sharded backtest"
    if set(axes) != set(fam.params):
        return (f"grid axes {sorted(axes)} do not match the "
                f"time-sharded contract {sorted(fam.params)}")
    prod = sweep_mod.product_grid(**axes)
    n_combos = int(np.asarray(next(iter(prod.values()))).size)
    int_axes = JaxSweepBackend._FUSED_STRATEGIES[strategy].window_axes
    wins = np.concatenate(
        [np.asarray(axes[a], np.float64) for a in int_axes])
    reason = _timeshard_window_reason(
        wins, n_combos, min(lengths), n_dev, halo_bound=fam.halo_bound,
        what=f"window ({'/'.join(int_axes)})")
    if reason is not None:
        return reason
    if strategy == "sma_crossover":
        f_ = np.round(np.asarray(prod["fast"], np.float64))
        s_ = np.round(np.asarray(prod["slow"], np.float64))
        if (f_ >= s_).any():
            return "grid contains fast >= slow combos"
    if strategy in ("donchian", "donchian_hl", "stochastic"):
        # The generic channel paths poison windows beyond MAX_WINDOW to
        # NaN; keep those semantics-defining results (the fused demotion
        # rule, applied identically here).
        from ..models import donchian as donchian_mod
        from ..models import stochastic as stoch_mod

        bound = (stoch_mod.MAX_WINDOW if strategy == "stochastic"
                 else donchian_mod.MAX_WINDOW)
        if float(wins.max()) > bound:
            return (f"max window {int(wins.max())} exceeds the channel "
                    f"view bound {bound}")
    return None


def timeshard_combos(strategy: str, axes) -> tuple:
    """The per-combo static parameter tuples of a time-sharded sweep, in
    DBXM (product_grid) column order — ints for window axes, floats
    otherwise. Shared by the single-host backend and the slice worker so
    the combo order cannot drift from the metric-column contract."""
    from ..parallel import sweep as sweep_mod

    fam = JaxSweepBackend._TIMESHARD_STRATEGIES[strategy]
    prod = sweep_mod.product_grid(**axes)
    int_axes = set(JaxSweepBackend._FUSED_STRATEGIES[strategy].window_axes)
    n_combos = int(np.asarray(next(iter(prod.values()))).size)
    return tuple(
        tuple(int(round(float(np.asarray(prod[p])[i])))
              if p in int_axes else float(np.asarray(prod[p])[i])
              for p in fam.params)
        for i in range(n_combos))


class InstantBackend:
    """Completes every job immediately with an empty metric block (tests)."""

    chips = 1

    def __init__(self):
        self.seen: list[str] = []

    def process(self, jobs) -> list[Completion]:
        out = []
        from ..ops.metrics import Metrics
        empty = wire.metrics_to_bytes(
            Metrics(*(np.zeros(1, np.float32) for _ in Metrics._fields)))
        for job in jobs:
            self.seen.append(job.id)
            out.append(Completion(job.id, empty, 0.0,
                                  trace_id=job.trace_id))
        return out


class SleepBackend:
    """Fixed per-job delay — the reference stub's behavior, for liveness tests."""

    chips = 1

    def __init__(self, delay_s: float = 0.05):
        self.delay_s = delay_s

    def process(self, jobs) -> list[Completion]:
        out = []
        for job in jobs:
            time.sleep(self.delay_s)
            out.append(Completion(job.id, b"", self.delay_s,
                                  trace_id=job.trace_id))
        return out
