"""Crash-durable job journal: append-only JSONL with replay.

The reference's queue is a bare in-memory Vec — a server crash loses every
job and every completion record (its own Limitations list names this,
reference ``README.md:80``). Here every queue transition is appended to a
JSONL journal and fsync'd, and a restarting dispatcher replays the file:
``pending = enqueued - completed - failed``. Leases are deliberately NOT
journaled — a lease lost to a crash simply leaves the job pending again,
and completion is idempotent, so replay needs no lease bookkeeping.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field

from ..obs import get_registry


class JournalCorruptError(ValueError):
    """An interior (non-tail) journal line failed to decode."""


@dataclass
class ReplayState:
    """Result of replaying a journal file."""

    jobs: dict = field(default_factory=dict)        # id -> job record (dict)
    completed: set = field(default_factory=set)     # job ids
    failed: set = field(default_factory=set)        # job ids
    corrupt_lines: int = 0                          # interior decode failures
    total_lines: int = 0                            # non-empty lines seen
    # Streaming append chain: extended-panel digest -> its `delta` event
    # (parent digest, base length, delta payload). Restarts rebuild
    # extended panels by replaying the chain instead of re-journaling
    # O(T) payloads per append (last event per digest wins — the splice
    # is deterministic, so duplicates are identical anyway).
    deltas: dict = field(default_factory=dict)
    # Raw complete/fail records in order, first occurrence per id — they
    # carry worker ids and failure reasons that the id sets drop, and
    # compaction must not erase that post-mortem record.
    terminal_events: list = field(default_factory=list)

    @property
    def pending(self) -> list[str]:
        done = self.completed | self.failed
        return [j for j in self.jobs if j not in done]


class Journal:
    """Append-only JSONL journal; thread-safe; no-op when ``path`` is None.

    ``fsync=False`` trades durability for speed — the model checker
    (analysis/modelcheck) runs thousands of short-lived journals whose
    crash semantics are simulated by copying the file at append
    boundaries, so the physical fsync buys nothing there. Production
    paths never pass it.

    ``crash_hook`` is the model checker's fork point: when set, it is
    called as ``hook("pre", event, rec)`` before the record reaches the
    file and ``hook("post", event, rec)`` after the write lands —
    i.e. on either side of the exact boundary a real crash would
    partition. Called OUTSIDE ``self._lock`` (and every dispatcher
    journal append already happens outside ``JobQueue._lock``), so the
    hook may safely replay the file and interrogate live queue state.
    """

    def __init__(self, path: str | None, *, fsync: bool = True):
        self._path = path
        self._fsync = fsync
        self.crash_hook = None
        self._lock = threading.Lock()
        self._fh = open(path, "a", encoding="utf-8") if path else None
        # fsync dominates append latency and gates every durable queue
        # transition — it gets its own histogram (DESIGN.md
        # "Observability"). Resolved once; zero cost on the no-op journal.
        reg = get_registry()
        self._h_append = reg.histogram(
            "dbx_journal_append_seconds",
            help="journal append wall (write + flush + fsync)")
        self._h_fsync = reg.histogram(
            "dbx_journal_fsync_seconds", help="journal fsync wall alone")
        self._c_appends = reg.counter(
            "dbx_journal_appends_total", help="journal records appended")

    @property
    def enabled(self) -> bool:
        """False for the no-op journal — callers can skip building
        expensive payloads (``journal_form`` b64-encodes the OHLCV block)."""
        return self._fh is not None

    def append(self, event: str, **payload) -> None:
        if self._fh is None:
            return
        rec = {"ev": event, **payload}
        line = json.dumps(rec, separators=(",", ":"))
        hook = self.crash_hook
        if hook is not None:
            hook("pre", event, rec)
        t0 = time.perf_counter()
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()
            t1 = time.perf_counter()
            if self._fsync:
                os.fsync(self._fh.fileno())
        t2 = time.perf_counter()
        if hook is not None:
            hook("post", event, rec)
        self._h_fsync.observe(t2 - t1)
        self._h_append.observe(t2 - t0)
        self._c_appends.inc()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # Journal keys that carry bulk payloads; dropped from terminal jobs'
    # records at compaction (identity/grid/path survive for restart dedupe
    # and result aggregation).
    _PAYLOAD_KEYS = ("ohlcv_b64", "ohlcv2_b64")

    @staticmethod
    def compact(path: str) -> tuple[int, int]:
        """Rewrite the journal to its live state; returns (before, after)
        line counts.

        An append-only journal grows without bound across restarts and
        replay cost grows with it. Compaction keeps exactly what recovery
        and tooling need: full enqueue records for PENDING jobs, slim
        enqueue records (payload fields dropped) for completed/failed jobs
        — their ids keep completions idempotent, their paths keep restart
        dedupe working, and their grids keep ``rpc.aggregate`` joins alive
        — plus the original terminal complete/fail records (first
        occurrence per id: worker ids and failure reasons survive for
        post-mortems). A journal with nothing to shrink (no terminal jobs,
        no duplicate/torn/corrupt lines) is left untouched. The rewrite is
        atomic (tmp + fsync + rename), and MUST run before an appending
        :class:`Journal` opens the path (the open handle would keep
        writing to the replaced inode).
        """
        if not path or not os.path.exists(path):
            return (0, 0)
        state = Journal.replay(path)
        before = state.total_lines
        if (not state.completed and not state.failed
                and not state.corrupt_lines
                and before == len(state.jobs) + len(state.deltas)):
            return (before, before)   # nothing to shrink: skip the rewrite
        done = state.completed | state.failed
        tmp = f"{path}.compact.{os.getpid()}"
        after = 0
        with open(tmp, "w", encoding="utf-8") as fh:
            # Append-chain links first (each ~ΔT bars): materializing a
            # restored append job needs its chain, and chain nodes can be
            # shared by several jobs (or by future appends), so they
            # survive compaction whole.
            for rec in state.deltas.values():
                fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
                after += 1
            # Streaming chain ROOTS — parent digests that are not
            # themselves rebuilt by a delta event — must keep their
            # payloads even on completed jobs: every extended panel in
            # the chain re-materializes from a root + the ΔT deltas, so
            # slimming a root would orphan the whole chain after restart.
            chain_roots = ({r.get("pdig") for r in state.deltas.values()}
                           - set(state.deltas))
            # Scenario BASES the same way: a pending scenario job
            # re-materializes after restart by regenerating from its
            # base digest (the blob store starts empty), walking
            # scenario-of-scenario specs down to a payload-carrying
            # record — slimming that record's inline payload would fail
            # every pending scenario job at first take.
            by_digest: dict = {}
            for r in state.jobs.values():
                for dkey in ("pdig", "pdig2"):
                    if r.get(dkey):
                        by_digest.setdefault(r[dkey], r)
            scn_roots: set = set()
            stack = [state.jobs[j].get("scn", {}).get("base")
                     for j in state.pending if state.jobs[j].get("scn")]
            seen: set = set()
            while stack:
                d = stack.pop()
                if not d or d in seen:
                    continue
                seen.add(d)
                r = by_digest.get(d)
                if r is None:
                    continue
                if r.get("scn") and r.get("pdig") == d:
                    stack.append(r["scn"].get("base"))
                else:
                    scn_roots.add(d)
            protected = chain_roots | scn_roots
            for jid, rec in state.jobs.items():
                if jid in done:
                    keep = set()
                    if rec.get("pdig") in protected:
                        keep.add("ohlcv_b64")
                    if rec.get("pdig2") in protected:
                        keep.add("ohlcv2_b64")
                    rec = {k: v for k, v in rec.items()
                           if k not in Journal._PAYLOAD_KEYS or k in keep}
                fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
                after += 1
            for rec in state.terminal_events:
                fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
                after += 1
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        return (before, after)

    @staticmethod
    def replay(path: str, *, strict: bool = True) -> ReplayState:
        """Reconstruct queue state from a journal file (missing file = empty).

        Tolerates a torn *final* line (crash mid-append) — that is the only
        corruption an append+fsync discipline can produce. An undecodable
        interior line means real damage (a silently dropped ``enqueue`` would
        lose a job from recovery), so it raises :class:`JournalCorruptError`
        by default; ``strict=False`` instead counts it loudly in
        ``ReplayState.corrupt_lines``.
        """
        state = ReplayState()
        if not path or not os.path.exists(path):
            return state
        with open(path, encoding="utf-8") as fh:
            lines = [ln.strip() for ln in fh]
        while lines and not lines[-1]:
            lines.pop()
        for i, line in enumerate(lines):
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                if i == len(lines) - 1:
                    continue  # torn tail write from a crash
                if strict:
                    raise JournalCorruptError(
                        f"{path}:{i + 1}: undecodable interior journal "
                        f"line ({e}); refusing to silently drop state"
                    ) from e
                state.corrupt_lines += 1
                continue
            state.total_lines += 1
            ev = rec.get("ev")
            if ev == "enqueue":
                state.jobs[rec["id"]] = rec
            elif ev == "digest":
                # Content-address stamp from a file-backed job's first
                # materialization: merged into the enqueue record, so a
                # restart keeps dispatching by the same digest and
                # compaction folds the stamp into the rewritten enqueue
                # line (no separate event survives).
                job = state.jobs.get(rec.get("id"))
                if job is not None:
                    for k in ("pdig", "pdig2"):
                        if rec.get(k):
                            job[k] = rec[k]
            elif ev == "delta":
                # Streaming append-chain link (AppendBars): keyed by the
                # EXTENDED panel's digest so materialization can walk
                # parents back to a journaled payload source.
                if rec.get("ndig"):
                    state.deltas[rec["ndig"]] = rec
            elif ev == "complete":
                if rec["id"] not in state.completed:
                    state.terminal_events.append(rec)
                state.completed.add(rec["id"])
            elif ev == "fail":
                if rec["id"] not in state.failed:
                    state.terminal_events.append(rec)
                state.failed.add(rec["id"])
        return state
