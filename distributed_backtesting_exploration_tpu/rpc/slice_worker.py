"""Slice-level worker: one multi-host JAX slice serving the dispatcher.

The default scale-out is job-level — each host runs an independent
:class:`~.worker.Worker` (``parallel/multihost.py`` layer 1, the
reference's machines-polling-a-queue model, reference ``README.md:6-7``).
This module is layer 2 joined with the RPC plane: when a single sweep
must span more chips than one host owns, the hosts form one
``jax.distributed`` slice and serve the SAME dispatcher contract as one
logical worker.

Architecture (SPMD discipline: every process of a slice must execute the
same jitted programs in the same order, so control flow is leader-driven):

- **Leader** (process 0) owns the gRPC side entirely: it polls
  RequestJobs, decodes job payloads, reports batched completions. The
  dispatcher sees ONE worker advertising the whole slice's chip count.
- Each round the leader **broadcasts** a small control message (run /
  idle / stop) plus the decoded job group to every process
  (``jax.experimental.multihost_utils.broadcast_one_to_all`` — gloo on
  CPU slices, ICI/DCN collectives on TPU pods).
- All processes then run the identical ticker-sharded sweep over the
  GLOBAL mesh (:func:`~..parallel.sharding.sharded_sweep` — the same
  code path as the single-host mesh backend) and replicate the metrics
  with an in-program all-gather (``jit`` with replicated
  ``out_shardings``), so the leader can pack DBXM blocks host-side.

The broadcast ships the full OHLCV group to every host — the simplest
correct data plane, fine for control-plane-scale payloads (a 5y-daily
ticker is ~25 KB); a production pod would stage payloads on shared
storage and broadcast only paths. Jobs in one poll batch are grouped by
(strategy, grid, cost, ppy, bars) exactly like the single-host backend;
mixed batches run as successive groups.

Tested end-to-end in ``tests/test_multihost.py``: two OS processes with
4 virtual CPU devices each form an 8-device slice, drain a LIVE
dispatcher, and every job's stored DBXM block matches the direct
single-device sweep.
"""

from __future__ import annotations

import json
import logging
import time
import uuid

import numpy as np

from .. import obs

log = logging.getLogger("dbx.slice_worker")

_STOP = {"op": "stop"}
_IDLE = {"op": "idle"}


def _bcast_msg(msg: dict | None, arrays: list[np.ndarray] | None = None):
    """Broadcast a JSON header + f32 array block from the leader.

    Followers pass ``None`` and receive the leader's message. Two
    collectives: a fixed-shape length header, then one payload buffer
    (every process must present identical shapes to the collective).
    """
    from jax.experimental import multihost_utils as mhu

    if msg is not None:
        header = json.dumps(msg).encode()
        blob = b"".join(np.ascontiguousarray(a, np.float32).tobytes()
                        for a in (arrays or []))
        lens = np.asarray([len(header), len(blob)], np.int64)
    else:
        header = b""
        blob = b""
        lens = np.zeros(2, np.int64)
    lens = np.asarray(mhu.broadcast_one_to_all(lens))
    n_h, n_b = int(lens[0]), int(lens[1])
    buf = np.zeros(n_h + n_b, np.uint8)
    if msg is not None:
        buf[:n_h] = np.frombuffer(header, np.uint8)
        buf[n_h:] = np.frombuffer(blob, np.uint8)
    buf = np.asarray(mhu.broadcast_one_to_all(buf))
    out = json.loads(bytes(buf[:n_h]))
    payload = np.frombuffer(bytes(buf[n_h:]), np.float32)
    return out, payload


class SliceWorker:
    """A whole multi-host slice polling the dispatcher as one worker.

    Construct AFTER :func:`~..parallel.multihost.initialize`; every
    process of the slice constructs one and calls :meth:`run` — the
    leader drives, followers follow the broadcast control stream.
    """

    def __init__(self, connect: str, *, worker_id: str | None = None,
                 jobs_per_chip: int = 1, poll_interval_s: float = 0.25):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel import sharding as sharding_mod

        self._jax = jax
        self.is_leader = jax.process_index() == 0
        self.mesh = sharding_mod.make_mesh()        # the GLOBAL slice mesh
        axis = self.mesh.axis_names[0]
        self._row = NamedSharding(self.mesh, P(axis, None))
        self._rep = NamedSharding(self.mesh, P())
        # One jitted identity per worker: out_shardings=replicated makes it
        # the in-program all-gather, and a per-call lambda would retrace
        # (and recompile) the reshard program on every job group.
        self._gather = jax.jit(lambda x: x, out_shardings=self._rep)
        self.chips = jax.device_count()
        self.jobs_completed = 0
        self._poll_interval_s = poll_interval_s
        self._jobs_per_chip = jobs_per_chip
        # Long-context route: a job whose bar count exceeds this cap (the
        # single-host fused VMEM cap; env-overridable for tests) on a
        # group whose ticker axis cannot fill the slice shards its BAR
        # axis over the GLOBAL mesh via parallel.timeshard instead of
        # running ticker-sharded with every chip computing pad rows.
        import os as _os

        from .compute import JaxSweepBackend as _JSB

        self.lc_bars_cap = int(_os.environ.get(
            "DBX_SLICE_LC_CAP", _JSB._FUSED_MAX_BARS))
        self._ts_fns: dict = {}
        self._stub = None
        if self.is_leader:
            import grpc

            from . import service

            self.worker_id = worker_id or f"slice-{uuid.uuid4().hex[:8]}"
            self._channel = grpc.insecure_channel(
                connect, options=service.default_channel_options())
            self._stub = service.DispatcherStub(self._channel)
            # Leader-side RPC timing shares the worker metric family (the
            # dispatcher sees a slice as one worker; so does /metrics).
            reg = obs.get_registry()
            self._h_rpc = {
                m: reg.histogram("dbx_worker_rpc_seconds",
                                 help="worker-side RPC wall (incl. wire)",
                                 method=m)
                for m in ("RequestJobs", "CompleteJobs", "FetchPayload")}
            self._c_jobs_in = reg.counter(
                "dbx_worker_jobs_received_total", help="jobs received")
            # Dispatch-by-digest (leader side): decoded panels keyed by
            # content digest, so digest-only re-deliveries skip the wire
            # AND the decode; misses recover via FetchPayload.
            from .compute import PanelCache

            self._panel_cache = PanelCache(registry=reg)
            log.info("slice worker %s: leader of %d processes, %d chips",
                     self.worker_id, jax.process_count(), self.chips)

    # -- leader side -------------------------------------------------------

    def _poll(self) -> list:
        from . import backtesting_pb2 as pb

        with obs.timer(self._h_rpc["RequestJobs"]):
            reply = self._stub.RequestJobs(pb.JobsRequest(
                worker_id=self.worker_id, chips=self.chips,
                jobs_per_chip=self._jobs_per_chip,
                accepts_digest_only=True), timeout=10.0)
        jobs = list(reply.jobs)
        if jobs:
            self._c_jobs_in.inc(len(jobs))
        return jobs

    def _group_jobs(self, jobs):
        """Group a poll batch like the single-host backend: same strategy,
        grid, cost, ppy and bar count stack into one sharded sweep.

        Returns ``(groups, decoded, bad)``. This worker runs plain
        single-asset sweeps over the global mesh; job kinds it does not
        implement — two-legged pairs, walk-forward, on-device top-k,
        best-returns (DBXP) — land in ``bad`` and are completed with EMPTY
        metric blocks plus a
        loud error (the validated-bad discipline of the single-host
        backend): silently running a walk-forward job as a plain sweep
        would store WRONG results as a valid completion, and leaving the
        jobs leased would requeue-loop them through the slice forever.
        Route such jobs to single-host workers (``rpc/worker.py``), which
        implement all four."""
        from . import wire
        from ..utils import data as data_mod

        groups: dict[tuple, list] = {}
        decoded: dict[str, tuple] = {}
        bad: list = []
        for job in jobs:
            unsupported = (
                "pairs (two-legged)" if (job.strategy == "pairs"
                                         or job.ohlcv2
                                         or job.panel_digest2) else
                "walk-forward" if job.wf_train > 0 else
                "top-k reduction" if job.top_k > 0 else
                # best_returns must be triaged too: running it as a plain
                # sweep would complete with a full DBXM block, which
                # `aggregate --portfolio` cannot compose — a mixed fleet
                # would quietly lose this leg from the book.
                "best-returns (DBXP) reduction" if job.best_returns
                else None)
            if unsupported:
                log.error(
                    "slice worker: job %s needs %s, which the slice-level "
                    "worker does not implement; completing with empty "
                    "metrics (route it to a single-host worker)",
                    job.id, unsupported)
                bad.append(job)
                continue
            series = self._resolve_series(job)
            if series is None:
                # Unresolvable digest-only payload: leave the job leased
                # (never complete it wrong) — the lease requeues it and
                # the dispatcher, having forgotten the phantom delivery,
                # re-dispatches full bytes.
                continue
            key = (job.strategy,
                   tuple(sorted((k, v.tobytes()) for k, v in
                                wire.grid_from_proto(job.grid).items())),
                   job.cost, job.periods_per_year, series.n_bars)
            groups.setdefault(key, []).append(job)
            decoded[job.id] = series
        return groups, decoded, bad

    def _resolve_series(self, job):
        """Digest-aware decode (leader side): host panel cache -> inline
        bytes -> FetchPayload. None when a digest-only panel cannot be
        fetched — the caller leaves the job leased for requeue."""
        from ..utils import data as data_mod

        if job.panel_digest:
            s = self._panel_cache.get_series(job.panel_digest)
            if s is not None:
                return s
        raw = job.ohlcv
        if not raw and job.panel_digest:
            raw = self._fetch_payload(job.panel_digest)
        if not raw:
            log.error("slice worker: job %s payload unavailable (digest "
                      "%s); leaving it leased for requeue", job.id,
                      job.panel_digest[:16] or "?")
            return None
        s = data_mod.from_wire_bytes(raw)
        if job.panel_digest:
            self._panel_cache.put_series(job.panel_digest, s)
        return s

    def _fetch_payload(self, digest: str) -> bytes:
        from . import backtesting_pb2 as pb

        try:
            with obs.timer(self._h_rpc["FetchPayload"]):
                reply = self._stub.FetchPayload(pb.PayloadRequest(
                    worker_id=self.worker_id, digest=digest), timeout=10.0)
        except Exception:
            log.exception("slice worker: FetchPayload %s failed",
                          digest[:16])
            return b""
        return reply.payload

    def _complete(self, items) -> None:
        from . import backtesting_pb2 as pb

        batch = pb.CompleteBatch(worker_id=self.worker_id, items=items)
        # Adopt the dispatcher-minted traces stamped on the items: the
        # group's trace_context has already exited by report time, and
        # without it the report span would carry no trace ids (the RPC
        # wall would read as transport in obs.timeline).
        with obs.trace_context(obs.job_trace_pairs(items)), \
                obs.span("worker.report", jobs=len(items)), \
                obs.timer(self._h_rpc["CompleteJobs"]):
            self._stub.CompleteJobs(batch, timeout=10.0)
        self.jobs_completed += len(items)

    # -- the SPMD round ----------------------------------------------------

    def _run_group(self, msg: dict | None, flat: np.ndarray):
        """Execute one broadcast job group on the global mesh (every
        process). Returns host-resident replicated Metrics."""
        import jax.numpy as jnp

        from ..models import base as models_base
        from ..ops.metrics import Metrics
        from ..parallel import sharding as sharding_mod
        from ..parallel import sweep as sweep_mod
        from ..utils import data as data_mod

        hdr, payload = _bcast_msg(msg, [flat] if flat is not None else [])
        if hdr["op"] == "run_ts":
            with obs.span("slice.run_ts_group",
                          strategy=hdr.get("strategy", "?")):
                return hdr, self._run_ts_group(hdr, payload)
        if hdr["op"] != "run":
            return hdr, None
        n_pad, T = hdr["n_pad"], hdr["bars"]
        panel_np = payload.reshape(5, n_pad, T)
        row, rep = self._row, self._rep

        jax = self._jax
        # Every host holds the full broadcast rows; contribute this
        # process's contiguous block (the 1-D mesh orders shards by
        # jax.devices(), which lists each process's devices contiguously —
        # the same layout parallel.multihost.host_shard relies on).
        n_local = n_pad * jax.local_device_count() // jax.device_count()
        start = jax.process_index() * n_local

        def globalize(a):
            return jax.make_array_from_process_local_data(
                row, np.ascontiguousarray(a[start:start + n_local]),
                global_shape=a.shape)

        panel = data_mod.OHLCV(*(globalize(panel_np[i]) for i in range(5)))
        grid = {k: self._jax.device_put(
                    jnp.asarray(np.asarray(v, np.float32)), rep)
                for k, v in hdr["grid"].items()}
        strategy = models_base.get_strategy(hdr["strategy"])
        flat_grid = sweep_mod.product_grid(**grid)
        with obs.span("slice.run_group", strategy=hdr["strategy"]):
            m = sharding_mod.sharded_sweep(
                self.mesh, panel, strategy, flat_grid, cost=hdr["cost"],
                periods_per_year=hdr["ppy"] or 252)
            # In-program all-gather: replicate the row-sharded metrics so
            # the leader can read them host-side.
            m = Metrics(*(np.asarray(self._gather(f)) for f in m))
        return hdr, m

    def _run_ts_group(self, hdr: dict, payload: np.ndarray):
        """One long-context group: BAR axis sharded over the global mesh
        (every process). The single-host `_submit_timeshard_groups`
        discipline on the slice: histories pad right with repeat-last
        values to a mesh multiple and pass ``t_real`` so pad bars are
        dead; one jitted program per (strategy, grid, cost, ppy, bars)
        runs one composed blockwise backtest per combo."""
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..ops.metrics import Metrics
        from ..parallel import timeshard
        from .compute import JaxSweepBackend, timeshard_combos

        jax = self._jax
        strat = hdr["strategy"]
        n, T = hdr["n"], hdr["bars"]
        cost, ppy = hdr["cost"], hdr["ppy"] or 252
        fam = JaxSweepBackend._TIMESHARD_STRATEGIES[strat]
        panel = payload.reshape(len(fam.fields), n, T)
        n_dev = self.chips
        T_pad = -(-T // n_dev) * n_dev
        if T_pad > T:
            panel = np.concatenate(
                [panel, np.repeat(panel[:, :, -1:], T_pad - T, axis=2)],
                axis=2)

        axis = self.mesh.axis_names[0]
        tspec = NamedSharding(self.mesh, P(None, axis))
        # Each process contributes its contiguous TIME block (same
        # device-order assumption as the ticker-sharded path above).
        t_local = T_pad * jax.local_device_count() // jax.device_count()
        start = jax.process_index() * t_local
        fields = [jax.make_array_from_process_local_data(
                      tspec,
                      np.ascontiguousarray(
                          panel[i][:, start:start + t_local]),
                      global_shape=(n, T_pad))
                  for i in range(len(fam.fields))]

        axes = {k: np.asarray(v, np.float32)
                for k, v in sorted(hdr["grid"].items())}
        combos = timeshard_combos(strat, axes)
        t_real = None if T == T_pad else T
        key = (strat,
               tuple(sorted((k, v.tobytes()) for k, v in axes.items())),
               float(cost), int(ppy), T, T_pad)
        run = self._ts_fns.get(key)
        if run is None:
            fn = getattr(timeshard, fam.fn_name)
            mesh = self.mesh

            def run(*arrs, _tr=t_real):
                ms = [fn(mesh, *arrs, *cmb, cost=cost,
                         periods_per_year=ppy, axis_name=axis, t_real=_tr)
                      for cmb in combos]
                return Metrics(*(jnp.stack(cols, axis=-1)
                                 for cols in zip(*ms)))

            run = jax.jit(run)
            if len(self._ts_fns) >= JaxSweepBackend._MESH_FN_CAP:
                self._ts_fns.pop(next(iter(self._ts_fns)))   # FIFO evict
            self._ts_fns[key] = run
        m = run(*fields)
        # timeshard metrics are replicated across the mesh -> every
        # process can read them host-side directly.
        return Metrics(*(np.asarray(f) for f in m))

    # -- the loop ----------------------------------------------------------

    def run(self, *, max_idle_polls: int | None = None) -> None:
        """Drive the slice until ``max_idle_polls`` consecutive empty polls
        (None = forever; followers always follow the leader's stream)."""
        from . import wire
        from . import backtesting_pb2 as pb
        from ..ops.metrics import Metrics

        if self.is_leader:
            try:
                self._leader_loop(max_idle_polls)
            except BaseException:
                # Followers are (or will be) parked inside the broadcast
                # collective waiting for the next control message; dying
                # without a stop would deadlock every other process of the
                # slice. Best effort — if the collective itself is broken
                # the broadcast raises too and processes exit.
                try:
                    _bcast_msg(_STOP)
                except Exception:
                    pass
                raise
        else:
            while True:
                hdr, _ = self._run_group(None, None)
                if hdr["op"] == "stop":
                    return
                if hdr["op"] == "idle":
                    time.sleep(self._poll_interval_s)

    def _leader_loop(self, max_idle_polls: int | None) -> None:
        from . import wire
        from . import backtesting_pb2 as pb
        from ..ops.metrics import Metrics
        from ..parallel import sharding as sharding_mod

        idle = 0
        while True:
            jobs = self._poll()
            if not jobs:
                idle += 1
                if max_idle_polls is not None and idle >= max_idle_polls:
                    _bcast_msg(_STOP)
                    log.info("slice worker %s: idle for %d polls; "
                             "stopping (%d jobs completed)",
                             self.worker_id, idle, self.jobs_completed)
                    return
                _bcast_msg(_IDLE)
                time.sleep(self._poll_interval_s)
                continue
            idle = 0
            groups, decoded, bad = self._group_jobs(jobs)
            if bad:
                # Validated-bad kinds: complete with empty blocks (see
                # _group_jobs) — no broadcast round needed.
                self._complete([pb.CompleteItem(id=j.id, metrics=b"",
                                                elapsed_s=0.0,
                                                trace_id=j.trace_id)
                                for j in bad])
            # One broadcast round per group; followers need no counts in
            # advance — they simply process the control stream.
            def stack_rows(group, fields):
                return np.stack(
                    [np.stack([np.asarray(getattr(decoded[j.id], f))
                               for j in group])
                     for f in fields])

            for (strat, grid_b, cost, ppy, bars), group in groups.items():
                grid_lists = {k: np.frombuffer(v, np.float32).tolist()
                              for k, v in grid_b}
                if bars > self.lc_bars_cap and len(group) < self.chips:
                    # Long-context route: shard the BAR axis over the
                    # whole slice instead of replicating pad rows on
                    # every chip (the single-host routing rule, slice
                    # scale — one shared eligibility implementation).
                    from .compute import timeshard_route_reason

                    axes = {k: np.frombuffer(v, np.float32)
                            for k, v in grid_b}
                    ts_reason = timeshard_route_reason(
                        strat, axes, [bars], self.chips)
                    if ts_reason is None:
                        from .compute import JaxSweepBackend as _JSB

                        fam = _JSB._TIMESHARD_STRATEGIES[strat]
                        rows = stack_rows(group, fam.fields)
                        msg = {"op": "run_ts", "strategy": strat,
                               "grid": grid_lists, "cost": cost,
                               "ppy": ppy, "bars": bars,
                               "n": len(group)}
                        log.info(
                            "slice worker: jobs %s (%s) routed to the "
                            "time-sharded long-context path (%d bars "
                            "over %d chips)", [j.id for j in group],
                            strat, bars, self.chips)
                        t0 = time.perf_counter()
                        # Join the group's dispatcher-minted traces: the
                        # slice.run_ts_group span (and the report span in
                        # _complete) stitches onto each job's dispatch
                        # span like the single-host worker's chain.
                        with obs.trace_context(obs.job_trace_pairs(group)):
                            _, m = self._run_group(msg, rows.reshape(-1))
                        # The group runs as ONE sharded program, so
                        # per-job wall time does not exist; elapsed_s is
                        # the group wall divided evenly (sums correctly
                        # in aggregate accounting, per-job values are an
                        # attribution convention — same as the
                        # ticker-sharded path below).
                        per_job = (time.perf_counter() - t0) / len(group)
                        self._complete([
                            pb.CompleteItem(
                                id=job.id,
                                metrics=wire.metrics_to_bytes(Metrics(
                                    *(np.asarray(f)[i] for f in m))),
                                elapsed_s=per_job,
                                trace_id=job.trace_id)
                            for i, job in enumerate(group)])
                        continue
                    log.warning(
                        "slice worker: jobs %s (%s) are long-context "
                        "(%d bars) but not time-shardable (%s); running "
                        "ticker-sharded", [j.id for j in group], strat,
                        bars, ts_reason)
                rows = stack_rows(
                    group, ("open", "high", "low", "close", "volume"))
                n_pad = sharding_mod.pad_tickers(
                    len(group), self.mesh.devices.size)
                rows = np.stack([sharding_mod.pad_rows(r, n_pad)
                                 for r in rows])
                msg = {"op": "run", "strategy": strat,
                       "grid": grid_lists,
                       "cost": cost, "ppy": ppy, "bars": bars,
                       "n_pad": n_pad}
                t0 = time.perf_counter()
                with obs.trace_context(obs.job_trace_pairs(group)):
                    _, m = self._run_group(msg, rows.reshape(-1))
                per_job = (time.perf_counter() - t0) / len(group)
                items = []
                for i, job in enumerate(group):
                    blob = wire.metrics_to_bytes(
                        Metrics(*(np.asarray(f)[i] for f in m)))
                    items.append(pb.CompleteItem(
                        id=job.id, metrics=blob, elapsed_s=per_job,
                        trace_id=job.trace_id))
                self._complete(items)
