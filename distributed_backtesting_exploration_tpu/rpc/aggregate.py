"""Fleet-level result aggregation: read stored DBXM blocks back into
decisions — best parameters per job, fleet-wide top performers.

The reference records only a completion bit and never reads a result back
(reference ``src/server/main.rs:66-78`` — ``CompleteRequest.data`` is
ignored, and the ``jobs_completed`` map is write-only per
``src/server/main.rs:33``). Here completions carry per-job metric matrices
that the dispatcher persists (``--results-dir``); this module is the read
path: it joins those blocks with the journal's job records (strategy, grid,
source path) and reports the best parameter set per job plus a fleet-level
ranking.

Param order contract: DBXM rows are the cartesian product of grid axes
sorted by name (the worker materializes ``product_grid`` over sorted axes —
proto map iteration order is unspecified), so aggregation re-sorts the
journaled axes the same way before indexing.
"""

from __future__ import annotations

import argparse
import json
import logging
import os

import numpy as np

from . import wire
from .journal import Journal
from ..ops.metrics import Metrics, metric_sign

log = logging.getLogger("dbx.aggregate")


def _np_product_grid(axes: dict) -> dict:
    """NumPy twin of :func:`~..parallel.sweep.product_grid` (same row-major
    ``indexing="ij"`` order — golden-tested against it). Aggregation runs on
    dispatcher hosts that may have no accelerator, so this module must not
    touch jax/device state at all."""
    names = list(axes)
    mesh = np.meshgrid(*(np.asarray(axes[n]) for n in names), indexing="ij")
    return {n: m.reshape(-1) for n, m in zip(names, mesh)}


def aggregate(results_dir: str, journal_path: str, *,
              metric: str = "sharpe", top: int = 10) -> dict:
    """Join stored DBXM blocks with journaled job records.

    Returns ``{"metric", "jobs_aggregated", "jobs_missing", "best"}`` where
    ``best`` is the fleet-wide top-``top`` list of
    ``{job, strategy, path, value, mode, params}`` rows sorted best-first
    in the metric's own direction (lower-is-better metrics sort
    ascending). ``mode`` is ``"sweep"`` (``params`` = the argmax combo) or
    ``"walkforward_oos"`` (the block is one stitched out-of-sample row;
    ``params`` is empty — each refit window chose its own).
    """
    if metric not in Metrics._fields:
        raise ValueError(f"unknown metric {metric!r}; one of "
                         f"{Metrics._fields}")
    state = Journal.replay(journal_path)
    rows = []
    missing = 0
    for jid, rec in state.jobs.items():
        path = os.path.join(results_dir, f"{jid}.dbxm")
        if not os.path.exists(path):
            if jid in state.completed:
                missing += 1   # completed per journal but block not stored
            continue
        with open(path, "rb") as fh:
            blob = fh.read()
        kind = wire.result_kind(blob)
        if kind == "empty":
            continue   # validated-bad job completed with no result
        grid_idx = None
        if kind == "topk":
            # DBXS block: the worker already reduced on-device; rows are
            # best-first by the block's own rank metric, and the stored
            # indices map back into the job's canonical grid order.
            grid_idx, m, block_metric = wire.topk_from_bytes(blob)
            if block_metric != metric:
                # Lossy comparison: only the k best-by-block_metric rows
                # survived the reduction, so "best by `metric`" below means
                # best among those — say so once, loudly.
                log.warning(
                    "job %s: DBXS block was reduced by %r but aggregation "
                    "ranks by %r — the reported best is best among the "
                    "retained top-k rows only", jid, block_metric, metric)
        else:
            m = wire.metrics_from_bytes(blob)
        values = np.asarray(getattr(m, metric)).reshape(-1)
        if values.size == 0:
            # A structurally-valid zero-row block (e.g. a job enqueued with
            # an empty grid axis): nothing to rank; skipping beats aborting
            # the whole fleet report on np.argmax of an empty array.
            log.warning("job %s: result block has zero param rows; skipped",
                        jid)
            continue
        sign_ = metric_sign(metric)
        # NaN ranks last (numpy argmax would rank it FIRST — NaN wins every
        # comparison), matching the worker-side _topk_reduce discipline; a
        # DBXS block where fewer than k combos have a finite metric must not
        # report a NaN row as the job's best while finite rows exist.
        score = np.where(np.isnan(values), -np.inf, sign_ * values)
        idx = int(np.argmax(score))
        row = {
            "job": jid,
            "strategy": rec.get("strategy"),
            "path": rec.get("path"),
            "value": float(values[idx]),
        }
        if rec.get("wf"):
            # Walk-forward block: ONE stitched out-of-sample row, not a
            # per-combo matrix — there is no single "best param" (each
            # refit window chose its own); labeling it with grid combo 0
            # would be wrong. No grid materialization needed either.
            row["mode"] = "walkforward_oos"
            row["params"] = {}
        else:
            axes = {k: np.asarray(v, np.float32)
                    for k, v in sorted(rec.get("grid", {}).items())}
            grid = _np_product_grid(axes) if axes else {}
            row["mode"] = "sweep" if kind == "metrics" else "sweep_topk"
            combo = int(grid_idx[idx]) if grid_idx is not None else idx
            row["params"] = {k: float(v[combo]) for k, v in grid.items()}
        rows.append(row)
    sign = metric_sign(metric)
    # Same NaN-last discipline fleet-wide: an all-NaN job sorts below every
    # finite job instead of landing at an arbitrary position (Python sort
    # with NaN keys is order-dependent).
    rows.sort(key=lambda r: -np.inf if np.isnan(r["value"])
              else sign * r["value"], reverse=True)
    return {
        "metric": metric,
        "jobs_aggregated": len(rows),
        "jobs_missing": missing,
        "best": rows[:top],
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="dbx aggregate: best params per job from stored results")
    ap.add_argument("--results-dir", required=True,
                    help="directory of <job-id>.dbxm blocks (dispatcher "
                         "--results-dir)")
    ap.add_argument("--journal", required=True,
                    help="dispatcher journal (maps job ids to specs)")
    ap.add_argument("--metric", default="sharpe",
                    choices=list(Metrics._fields))
    ap.add_argument("--top", type=int, default=10)
    args = ap.parse_args(argv)
    out = aggregate(args.results_dir, args.journal, metric=args.metric,
                    top=args.top)
    # All-NaN jobs are retained in `best` (ranked last); json.dumps would
    # emit non-standard NaN/Infinity tokens for them, breaking strict
    # parsers downstream — serialize non-finite values as null instead
    # (allow_nan=False rejects inf too, so isfinite is the right gate).
    for row in out["best"]:
        if not np.isfinite(row["value"]):
            row["value"] = None
    print(json.dumps(out, indent=2, allow_nan=False))


if __name__ == "__main__":
    main()
