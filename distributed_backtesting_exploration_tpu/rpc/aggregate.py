"""Fleet-level result aggregation: read stored DBXM blocks back into
decisions — best parameters per job, fleet-wide top performers.

The reference records only a completion bit and never reads a result back
(reference ``src/server/main.rs:66-78`` — ``CompleteRequest.data`` is
ignored, and the ``jobs_completed`` map is write-only per
``src/server/main.rs:33``). Here completions carry per-job metric matrices
that the dispatcher persists (``--results-dir``); this module is the read
path: it joins those blocks with the journal's job records (strategy, grid,
source path) and reports the best parameter set per job plus a fleet-level
ranking.

Param order contract: DBXM rows are the cartesian product of grid axes
sorted by name (the worker materializes ``product_grid`` over sorted axes —
proto map iteration order is unspecified), so aggregation re-sorts the
journaled axes the same way before indexing.
"""

from __future__ import annotations

import argparse
import json
import logging
import os

import numpy as np

from . import wire
from .journal import Journal
from ..ops.metrics import Metrics, metric_sign

log = logging.getLogger("dbx.aggregate")


def _np_product_grid(axes: dict) -> dict:
    """NumPy twin of :func:`~..parallel.sweep.product_grid` (same row-major
    ``indexing="ij"`` order — golden-tested against it). Aggregation runs on
    dispatcher hosts that may have no accelerator, so this module must not
    touch jax/device state at all."""
    names = list(axes)
    mesh = np.meshgrid(*(np.asarray(axes[n]) for n in names), indexing="ij")
    return {n: m.reshape(-1) for n, m in zip(names, mesh)}


def aggregate(results_dir: str, journal_path: str, *,
              metric: str = "sharpe", top: int = 10) -> dict:
    """Join stored DBXM blocks with journaled job records.

    Returns ``{"metric", "jobs_aggregated", "jobs_missing", "best"}`` where
    ``best`` is the fleet-wide top-``top`` list of
    ``{job, strategy, path, value, mode, params}`` rows sorted best-first
    in the metric's own direction (lower-is-better metrics sort
    ascending). ``mode`` is ``"sweep"`` (``params`` = the argmax combo) or
    ``"walkforward_oos"`` (the block is one stitched out-of-sample row;
    ``params`` is empty — each refit window chose its own).
    """
    if metric not in Metrics._fields:
        raise ValueError(f"unknown metric {metric!r}; one of "
                         f"{Metrics._fields}")
    state = Journal.replay(journal_path)
    rows = []
    missing = 0
    for jid, rec in state.jobs.items():
        path = os.path.join(results_dir, f"{jid}.dbxm")
        if not os.path.exists(path):
            if jid in state.completed:
                missing += 1   # completed per journal but block not stored
            continue
        with open(path, "rb") as fh:
            blob = fh.read()
        kind = wire.result_kind(blob)
        if kind == "empty":
            continue   # validated-bad job completed with no result
        grid_idx = None
        if kind == "topk":
            # DBXS block: the worker already reduced on-device; rows are
            # best-first by the block's own rank metric, and the stored
            # indices map back into the job's canonical grid order.
            grid_idx, m, block_metric = wire.topk_from_bytes(blob)
            if block_metric != metric:
                # Lossy comparison: only the k best-by-block_metric rows
                # survived the reduction, so "best by `metric`" below means
                # best among those — say so once, loudly.
                log.warning(
                    "job %s: DBXS block was reduced by %r but aggregation "
                    "ranks by %r — the reported best is best among the "
                    "retained top-k rows only", jid, block_metric, metric)
        elif kind == "returns":
            # DBXP block: one best row (k=1 by the block's own rank
            # metric) + the return series, which this ranking path does
            # not need (`--portfolio` is the series read path).
            gi, m_row, _ret, block_metric = wire.best_returns_from_bytes(
                blob)
            grid_idx = np.asarray([gi])
            m = Metrics(*(np.asarray([v], np.float32) for v in m_row))
            if block_metric != metric:
                log.warning(
                    "job %s: DBXP block kept only the best-by-%r combo; "
                    "ranking by %r compares those single survivors",
                    jid, block_metric, metric)
        else:
            m = wire.metrics_from_bytes(blob)
        values = np.asarray(getattr(m, metric)).reshape(-1)
        if values.size == 0:
            # A structurally-valid zero-row block (e.g. a job enqueued with
            # an empty grid axis): nothing to rank; skipping beats aborting
            # the whole fleet report on np.argmax of an empty array.
            log.warning("job %s: result block has zero param rows; skipped",
                        jid)
            continue
        sign_ = metric_sign(metric)
        # NaN ranks last (numpy argmax would rank it FIRST — NaN wins every
        # comparison), matching the worker-side _topk_reduce discipline; a
        # DBXS block where fewer than k combos have a finite metric must not
        # report a NaN row as the job's best while finite rows exist.
        score = np.where(np.isnan(values), -np.inf, sign_ * values)
        idx = int(np.argmax(score))
        row = {
            "job": jid,
            "strategy": rec.get("strategy"),
            "path": rec.get("path"),
            "value": float(values[idx]),
        }
        if rec.get("wf"):
            # Walk-forward block: ONE stitched out-of-sample row, not a
            # per-combo matrix — there is no single "best param" (each
            # refit window chose its own); labeling it with grid combo 0
            # would be wrong. No grid materialization needed either.
            row["mode"] = "walkforward_oos"
            row["params"] = {}
        else:
            axes = {k: np.asarray(v, np.float32)
                    for k, v in sorted(rec.get("grid", {}).items())}
            grid = _np_product_grid(axes) if axes else {}
            row["mode"] = {"metrics": "sweep", "topk": "sweep_topk",
                           "returns": "sweep_best_returns"}[kind]
            combo = int(grid_idx[idx]) if grid_idx is not None else idx
            row["params"] = {k: float(v[combo]) for k, v in grid.items()}
        rows.append(row)
    sign = metric_sign(metric)
    # Same NaN-last discipline fleet-wide: an all-NaN job sorts below every
    # finite job instead of landing at an arbitrary position (Python sort
    # with NaN keys is order-dependent).
    rows.sort(key=lambda r: -np.inf if np.isnan(r["value"])
              else sign * r["value"], reverse=True)
    return {
        "metric": metric,
        "jobs_aggregated": len(rows),
        "jobs_missing": missing,
        "best": rows[:top],
    }


def _np_portfolio_metrics(returns: np.ndarray,
                          periods_per_year: int = 252) -> dict:
    """NumPy twin of the returns/equity subset of
    ``ops.metrics.summary_metrics`` for ONE return series (same formulas:
    population moments, additive equity ``1 + cumsum``, peak-relative
    drawdown). Golden-tested against the jax version. The position-derived
    fields (hit_rate, n_trades, turnover) need per-leg exposures that DBXP
    blocks deliberately do not carry, so they are absent here."""
    r = np.asarray(returns, np.float64)
    n = max(r.shape[-1], 1)
    eps = 1e-12
    mean = r.sum() / n
    std = np.sqrt(max(np.square(r).sum() / n - mean * mean, 0.0))
    downside = np.minimum(r, 0.0)
    dstd = np.sqrt(np.square(downside).sum() / n)
    ann = np.sqrt(periods_per_year)
    equity = 1.0 + np.cumsum(r)
    peak = np.maximum.accumulate(equity)
    mdd = float(np.max((peak - equity) / np.maximum(peak, eps)))
    years = max(n / periods_per_year, eps)
    final = max(equity[-1], eps)
    return {
        "sharpe": float(mean / (std + eps) * ann),
        "sortino": float(mean / (dstd + eps) * ann),
        "max_drawdown": mdd,
        "total_return": float(equity[-1] - 1.0),
        "cagr": float(final ** (1.0 / years) - 1.0),
        "volatility": float(std * ann),
    }


_MINVAR_SHRINK = 0.1   # covariance shrinkage toward the diagonal


def _min_variance_weights(R: np.ndarray, live: np.ndarray) -> np.ndarray:
    """Correlation-aware minimum-variance weights over leg return rows.

    The unconstrained minimum of ``w'Σw`` s.t. ``w'1 = 1`` is
    ``w ∝ Σ⁻¹1``; Σ is shrunk ``(1-λ)Σ + λ diag(Σ)`` (λ=0.1) so two
    near-duplicate legs cannot blow the solve up into huge offsetting
    ±weights. Dead legs (zero variance) get weight 0; fewer than two live
    legs degrades to inverse-vol/equal exactly like that scheme's
    fallbacks. Callers normalize to unit gross exposure afterwards."""
    n = R.shape[0]
    k = int(live.sum())
    if k >= 2:
        Rl = R[live]
        cov = np.cov(Rl)
        cov = (1.0 - _MINVAR_SHRINK) * cov + _MINVAR_SHRINK * np.diag(
            np.diag(cov))
        try:
            wl = np.linalg.solve(cov, np.ones(k))
        except np.linalg.LinAlgError:
            # Singular even after shrinkage (e.g. bit-identical legs):
            # inverse-vol is the diagonal-only special case.
            wl = 1.0 / (Rl.std(axis=-1) + 1e-12)
        w = np.zeros(n)
        w[live] = wl
        return w
    if live.any():
        return np.where(live, 1.0 / (R.std(axis=-1) + 1e-12), 0.0)
    return np.ones(n)


def portfolio(results_dir: str, journal_path: str, *,
              weights: str = "equal",
              periods_per_year: int = 252, top: int = 10) -> dict:
    """Compose stored DBXP best-return series into the true fleet book.

    This is the read-path half of ``JobSpec.best_returns``: each job shipped
    its winning combo's per-bar net returns, so the fleet-level portfolio —
    which per-job metric ROWS cannot produce (cross-ticker correlations are
    lost in a scalar) — is a weighted sum of stored series. ``weights`` is
    ``"equal"``, ``"inverse_vol"`` (per-leg 1/std of its net returns), or
    ``"min_variance"`` (correlation-aware: the inverse-covariance
    minimum-variance solution ``w ∝ Σ⁻¹1`` on the stored series, with the
    covariance shrunk 10%% toward its diagonal so a near-singular Σ from
    highly correlated legs cannot produce wild ±weights; legs may receive
    negative weight — shorting a leg's strategy — and the book is
    normalized to unit GROSS exposure either way, like
    ``parallel.portfolio._normalize_weights``). All legs must share one
    bar count (compose over a uniform fleet; ragged legs error loudly
    with the offending lengths). Runs dispatcher-side on NumPy only — no
    jax.
    """
    if weights not in ("equal", "inverse_vol", "min_variance"):
        raise ValueError(f"unknown weights scheme {weights!r}; "
                         "one of: equal, inverse_vol, min_variance")
    state = Journal.replay(journal_path)
    legs = []
    skipped: dict[str, list] = {}
    for jid, rec in state.jobs.items():
        path = os.path.join(results_dir, f"{jid}.dbxm")
        if not os.path.exists(path):
            # Pending jobs have no block yet — routine. A job the journal
            # says COMPLETED with no stored block is a missing leg, the
            # same quietly-thinner-book failure as a wrong-kind block
            # (aggregate()'s jobs_missing discipline).
            if jid in state.completed:
                skipped.setdefault("missing", []).append(jid)
            continue
        with open(path, "rb") as fh:
            blob = fh.read()
        kind = wire.result_kind(blob)
        if kind != "returns":
            # A completed job whose stored block is not DBXP cannot
            # contribute a leg. This is NOT routine: a fleet run with
            # --best-returns should produce only DBXP blocks, so a DBXM/
            # DBXS/empty block here means some worker ran the job as the
            # wrong kind (e.g. a slice worker that predates the
            # best-returns triage) — a book quietly missing legs is the
            # exact silent failure this accounting exists to surface.
            skipped.setdefault(kind, []).append(jid)
            continue
        grid_idx, m_row, ret, rank_metric = wire.best_returns_from_bytes(blob)
        axes = {k: np.asarray(v, np.float32)
                for k, v in sorted(rec.get("grid", {}).items())}
        grid = _np_product_grid(axes) if axes else {}
        value = (float(getattr(m_row, rank_metric))
                 if rank_metric in Metrics._fields else None)
        if value is not None and not np.isfinite(value):
            # Sanitize BEFORE the sort below: a NaN sort key makes leg
            # ordering nondeterministic (NaN is truthy, so `value or 0.0`
            # stays NaN), and library callers should never see the
            # unsanitized dict either.
            value = None
        legs.append({
            "job": jid,
            "strategy": rec.get("strategy"),
            "path": rec.get("path"),
            "rank_metric": rank_metric,
            "value": value,
            "params": {k: float(v[grid_idx]) for k, v in grid.items()},
            "returns": ret,
        })
    for kind, jids in sorted(skipped.items()):
        if kind == "missing":
            log.warning(
                "portfolio: %d job(s) completed per the journal but have no "
                "stored block — the composed book is missing these jobs: "
                "%s. Was the dispatcher run without --results-dir, or were "
                "blocks deleted?", len(jids), ", ".join(sorted(jids)))
        else:
            log.warning(
                "portfolio: skipped %d stored block(s) of kind %r (not "
                "DBXP) — the composed book is missing these jobs: %s. "
                "Re-run them on a worker that implements --best-returns "
                "(single-host rpc/worker.py does; check for slice workers "
                "completing the wrong kind)", len(jids), kind,
                ", ".join(sorted(jids)))
    if not legs:
        raise ValueError(
            f"no DBXP best-returns blocks found under {results_dir!r} — "
            "was the fleet run with --best-returns?")
    lengths = {leg["returns"].shape[0] for leg in legs}
    if len(lengths) > 1:
        raise ValueError(
            "cannot compose ragged legs into one book: bar counts "
            f"{sorted(lengths)} differ across jobs")
    R = np.stack([leg["returns"] for leg in legs]).astype(np.float64)
    live = R.std(axis=-1) > 0
    if weights == "inverse_vol":
        # A never-traded leg (flat series, std = 0) must not receive
        # 1/eps ~ 1e12 weight and collapse the book to zero — dead legs
        # get weight 0 (all-dead falls back to equal).
        if live.any():
            w = np.where(live, 1.0 / (R.std(axis=-1) + 1e-12), 0.0)
        else:
            w = np.ones(R.shape[0])
    elif weights == "min_variance":
        w = _min_variance_weights(R, live)
    else:
        w = np.ones(R.shape[0])
    w = w / max(np.abs(w).sum(), 1e-12)
    port = w @ R
    # Diversification scalar: mean off-diagonal correlation. Zero-variance
    # legs produce NaN rows in corrcoef; exclude them rather than
    # poisoning the mean.
    if int(live.sum()) >= 2:
        corr = np.corrcoef(R[live])
        k = corr.shape[0]
        avg_corr = float((corr.sum() - np.trace(corr)) / (k * (k - 1)))
    else:
        avg_corr = None
    for leg, wi in zip(legs, w):
        leg["weight"] = float(wi)
        del leg["returns"]
    legs.sort(key=lambda r: (r["value"] is None, -(r["value"] or 0.0)))
    return {
        "weights": weights,
        "legs_composed": len(legs),
        "blocks_skipped": sum(len(v) for v in skipped.values()),
        "bars": int(R.shape[1]),
        "avg_pairwise_correlation": avg_corr,
        "portfolio": _np_portfolio_metrics(port, periods_per_year),
        "legs": legs[:top],
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="dbx aggregate: best params per job from stored results")
    ap.add_argument("--results-dir", required=True,
                    help="directory of <job-id>.dbxm blocks (dispatcher "
                         "--results-dir)")
    ap.add_argument("--journal", required=True,
                    help="dispatcher journal (maps job ids to specs)")
    ap.add_argument("--metric", default="sharpe",
                    choices=list(Metrics._fields))
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--portfolio", nargs="?", const="equal", default=None,
                    choices=["equal", "inverse_vol", "min_variance"],
                    help="compose stored DBXP best-return series (jobs run "
                         "with --best-returns) into the fleet book with "
                         "this weighting; prints portfolio metrics + the "
                         "diversification scalar instead of the ranking")
    args = ap.parse_args(argv)
    if args.portfolio:
        out = portfolio(args.results_dir, args.journal,
                        weights=args.portfolio, top=args.top)
        # Same non-finite discipline as the ranking path: a NaN bar in any
        # stored series (NaN source prices) NaNs every composed metric, and
        # json.dumps(allow_nan=False) would raise instead of reporting.
        for leg in out["legs"]:
            if leg["value"] is not None and not np.isfinite(leg["value"]):
                leg["value"] = None
        out["portfolio"] = {k: (v if np.isfinite(v) else None)
                            for k, v in out["portfolio"].items()}
        ac = out["avg_pairwise_correlation"]
        if ac is not None and not np.isfinite(ac):
            out["avg_pairwise_correlation"] = None
        print(json.dumps(out, indent=2, allow_nan=False))
        return
    out = aggregate(args.results_dir, args.journal, metric=args.metric,
                    top=args.top)
    # All-NaN jobs are retained in `best` (ranked last); json.dumps would
    # emit non-standard NaN/Infinity tokens for them, breaking strict
    # parsers downstream — serialize non-finite values as null instead
    # (allow_nan=False rejects inf too, so isfinite is the right gate).
    for row in out["best"]:
        if not np.isfinite(row["value"]):
            row["value"] = None
    print(json.dumps(out, indent=2, allow_nan=False))


if __name__ == "__main__":
    main()
