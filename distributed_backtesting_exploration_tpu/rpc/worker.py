"""The worker: poll loop + compute thread, bridged by bounded queues.

Same shape as the reference's worker — an I/O loop polling the dispatcher on
a tick, a dedicated compute thread so device-bound work never starves the
control plane, and bounded channels between them (reference
``src/worker/main.rs:24-85``) — with its sharp edges removed:

- the worker stops *requesting* jobs while its compute queue is full (the
  reference kept polling every 250 ms regardless, hoarding up to 1024
  batches in its channel; reference ``src/worker/handlers.rs:54-58``);
- a failed completion RPC is retried with backoff, not ``.unwrap()``-panicked
  (reference ``src/worker/main.rs:82``);
- startup connect failures retry instead of exiting (reference
  ``src/worker/main.rs:50-55``);
- shutdown is graceful: in-flight work drains before exit (a reference
  Limitations item, reference ``README.md:85``).

Round 14 adds the **pipelined executor**: for two-phase (submit/collect)
backends the compute side runs as a bounded two-thread pipeline —
this module's submit thread decodes and launches batch N+1 while a
collector thread drains batch N's device results — with the control
loop prefetching payloads/compile-cache entries for batches still
queued behind the pipeline. ``DBX_PIPELINE=0`` falls back to the
strictly serial loop (the bit-identity reference); see
DESIGN.md "Pipelined executor (round 14)".
"""

from __future__ import annotations

import argparse
import logging
import os
import queue as queue_mod
import threading
import time
import uuid

import grpc

from . import backtesting_pb2 as pb
from . import compute, service
from .. import obs
from ..obs import fleet as obs_fleet
from ..obs import flight as obs_flight
from ..runtime import _core as native_core

log = logging.getLogger("dbx.worker")


class _Channel:
    """Bounded channel bridging the control and compute threads.

    Backed by the native C++ MPMC queue when the core is available — the
    role flume's bounded channels play in the reference worker (reference
    ``src/worker/main.rs:32-42``; SURVEY.md §2.2 native ledger) — and by
    ``queue.Queue`` otherwise. Items cross the boundary as proto bytes via
    the ``enc``/``dec`` pair, so the native queue stays a plain blob queue.

    Capacity semantics: the native queue is always bounded, so an
    "unbounded" channel gets the ``_UNBOUNDED`` sentinel capacity — past it
    the producer *blocks* (backpressure), whereas the pure-Python
    ``queue.Queue(0)`` fallback never would. At 2^20 undrained completions
    that divergence only triggers after the control thread has been wedged
    for far longer than the dispatcher's prune window, at which point
    backpressure on the compute thread is the safer behavior anyway.
    """

    _UNBOUNDED = 1 << 20

    def __init__(self, capacity: int | None, enc, dec):
        self._enc, self._dec = enc, dec
        self._capacity = capacity
        self._nq = None
        if native_core.available():
            try:
                self._nq = native_core.NativeQueue(
                    capacity or self._UNBOUNDED)
            except RuntimeError:
                self._nq = None
        self._pq: queue_mod.Queue | None = (
            None if self._nq is not None else queue_mod.Queue(capacity or 0))
        self.backend = "native" if self._nq is not None else "python"

    def put(self, item) -> None:
        if self._nq is not None:
            self._nq.push(self._enc(item))
        else:
            self._pq.put(item)

    def get(self):
        if self._nq is not None:
            return self._dec(self._nq.pop())
        return self._pq.get()

    def get_nowait(self):
        if self._nq is not None:
            b = self._nq.pop(timeout_ms=0)
            if b is None:
                raise queue_mod.Empty
            return self._dec(b)
        return self._pq.get_nowait()

    def full(self) -> bool:
        if self._nq is not None:
            return self._capacity is not None and len(self._nq) >= self._capacity
        return self._pq.full()

    def empty(self) -> bool:
        if self._nq is not None:
            return len(self._nq) == 0
        return self._pq.empty()

    def depth(self) -> int:
        """Approximate occupancy (observability gauge; racy by nature)."""
        if self._nq is not None:
            return len(self._nq)
        return self._pq.qsize()


def _pb_size(msg) -> int:
    """Serialized size of a proto message; 0 for test doubles that stand
    in for replies without implementing ByteSize."""
    size = getattr(msg, "ByteSize", None)
    return size() if callable(size) else 0


class _SyncLegFailed(Exception):
    """One tune-sync RPC leg failed (already counted under its own
    method label); the tick aborts and retries on the next interval."""


def pipeline_enabled() -> bool:
    """``DBX_PIPELINE`` (default on): run two-phase backends through the
    double-buffered submit/collect pipeline. ``0`` keeps the strictly
    serial loop — the bit-identity reference for the pipelined path.
    Read lazily (per worker run), never at import time."""
    return os.environ.get("DBX_PIPELINE", "1").lower() not in (
        "0", "off", "false")


def pipeline_depth() -> int:
    """``DBX_PIPELINE_DEPTH`` (default 2): submitted-but-uncollected
    batches the pipeline holds before the submit thread blocks. Depth 2
    is classic double buffering (one batch on device, one staging);
    deeper mostly grows queue wait, not overlap."""
    return max(int(os.environ.get("DBX_PIPELINE_DEPTH", "2")), 1)


def prefetch_enabled() -> bool:
    """``DBX_PREFETCH`` (default on): the control loop stages inputs for
    batches still queued behind the compute pipeline (payload decode,
    device page warm-up, compile-cache pull-forward)."""
    return os.environ.get("DBX_PREFETCH", "1").lower() not in (
        "0", "off", "false")


_BATCH_SENTINEL = b"S"


def _encode_batch(batch) -> bytes:
    if batch is None:
        return _BATCH_SENTINEL
    return b"B" + pb.JobsReply(jobs=batch).SerializeToString()


def _decode_batch(data: bytes):
    if data[:1] == _BATCH_SENTINEL:
        return None
    reply = pb.JobsReply()
    reply.ParseFromString(data[1:])
    return list(reply.jobs)


def _encode_completion(c: compute.Completion) -> bytes:
    # trace_id rides the channel envelope too: a completion crossing the
    # native MPMC queue must come out stitchable (proto CompleteRequest is
    # the envelope, so the wire field doubles as the channel field).
    return pb.CompleteRequest(
        id=c.job_id, metrics=c.metrics,
        elapsed_s=c.elapsed_s, trace_id=c.trace_id).SerializeToString()


def _decode_completion(data: bytes) -> compute.Completion:
    req = pb.CompleteRequest()
    req.ParseFromString(data)
    return compute.Completion(req.id, req.metrics, req.elapsed_s,
                              trace_id=req.trace_id)


class Worker:
    """Polls a dispatcher, runs a compute backend, reports completions."""

    def __init__(self, target: str, backend: compute.ComputeBackend, *,
                 worker_id: str | None = None,
                 poll_interval_s: float = 0.25,
                 status_interval_s: float = 1.0,
                 jobs_per_chip: int = 1,
                 max_inflight_batches: int = 2,
                 registry: "obs.Registry | None" = None):
        self.target = target
        self.backend = backend
        self.worker_id = worker_id or f"worker-{uuid.uuid4().hex[:8]}"
        self.poll_interval_s = poll_interval_s
        self.status_interval_s = status_interval_s
        self.jobs_per_chip = jobs_per_chip
        self._in = _Channel(max_inflight_batches, _encode_batch,
                            _decode_batch)
        self._out = _Channel(None, _encode_completion, _decode_completion)
        self._stop = threading.Event()
        self._busy = threading.Event()
        # Pipelined executor state (round 14): batches taken from the
        # channel but not yet fully collected. The counter (guarded by
        # its own lock — it is shared by the submit and collector
        # threads) drives the busy flag, so idle-exit and status
        # reporting see the WHOLE pipeline, not just the submit half.
        self._pipeline_lock = threading.Lock()
        self._pipeline_inflight = 0
        self._pipeline_done = threading.Event()
        # Compile-cache prefetch memo: (strategy, payload-size-bucket)
        # signatures whose arrival already pulled a tune-sync forward.
        self._prefetch_seen: set = set()
        # Backend warm-up runs on its own daemon thread (started lazily,
        # stopped in _shutdown): the page warm-up can upload device
        # pages — whose first-call scatter compile takes seconds per
        # pow2 shape class — and THIS thread owns the SendStatus
        # heartbeat; a stalled heartbeat gets a healthy worker pruned
        # mid-drain (the deferred-completion lesson).
        self._prefetch_q: queue_mod.Queue | None = None
        self._prefetch_thread: threading.Thread | None = None
        self._connected = True  # edge-triggered logging, reference CONNECTED
        self.jobs_completed = 0
        self.completions_dropped = 0
        self._compute_thread: threading.Thread | None = None
        # Failed completion RPCs park here with a due time instead of
        # sleep-retrying on the control thread (advisor finding: inline
        # backoff sleeps starved SendStatus past the dispatcher's prune
        # window, getting a healthy worker pruned mid-drain).
        self._deferred: list[tuple[float, int, compute.Completion]] = []
        self._next_status = 0.0
        # Observability: client-side RPC latency histograms + poll/error
        # counters (pre-resolved — the poll loop is a hot path), channel
        # occupancy and retry backlog as scrape-time gauges (labeled by
        # worker_id: several workers can share one process, e.g. bench's
        # control-plane saturation config).
        self.obs = registry or obs.get_registry()
        self._h_rpc = {
            m: self.obs.histogram("dbx_worker_rpc_seconds",
                                  help="worker-side RPC wall (incl. wire)",
                                  method=m)
            for m in ("RequestJobs", "SendStatus", "CompleteJobs",
                      "FetchPayload", "FetchCompiled", "OfferCompiled",
                      "GetStats")}
        self._c_rpc_errors = {
            m: self.obs.counter("dbx_worker_rpc_errors_total",
                                help="failed worker RPC attempts", method=m)
            for m in ("RequestJobs", "SendStatus", "CompleteJobs",
                      "FetchPayload", "FetchCompiled", "OfferCompiled",
                      "GetStats")}
        # Wire accounting (serialized proto bytes, pre-compression): the
        # bench's `wire_bytes_per_job` column and the dispatch-by-digest
        # A/B read these deltas.
        self._c_wire = {
            (m, d): self.obs.counter(
                "dbx_worker_wire_bytes_total",
                help="serialized proto bytes over worker RPCs",
                method=m, direction=d)
            for m in ("RequestJobs", "CompleteJobs", "FetchPayload")
            for d in ("request", "reply")}
        self._c_fetches = self.obs.counter(
            "dbx_worker_payload_fetches_total",
            help="FetchPayload recoveries for digest-only jobs")
        self._c_polls = self.obs.counter(
            "dbx_worker_polls_total", help="RequestJobs polls sent")
        self._c_idle_polls = self.obs.counter(
            "dbx_worker_idle_polls_total", help="polls answered empty")
        self._c_jobs_in = self.obs.counter(
            "dbx_worker_jobs_received_total", help="jobs received")
        self._c_dropped = self.obs.counter(
            "dbx_worker_completions_dropped_total",
            help="completions dropped after retry exhaustion")
        # Every per-worker-labeled metric (the jobs/sec gauge and the
        # collector-maintained channel/deferred/busy gauges) is created in
        # run() and removed in its finally — a constructed-but-never-run
        # Worker must leak neither a collector closing over itself nor a
        # uuid-labeled gauge child.
        self._jobs_rate = obs.StepTimer()
        self._gauges: dict | None = None
        # Substrate-autotuner + fleet-compile-cache sync (tune/, round
        # 11): attached in run() only for backends that expose a schedule
        # registry (the jax backend) — the instant/sleep fakes neither
        # tune nor compile. New local schedule entries piggyback on
        # JobsRequest.schedule_json (zero-cost when clean); the pull leg
        # (fleet registry via GetStats + compile-cache exchange) runs on
        # its own tick — 10s default: schedules and compiles change on
        # first-contact timescales, and each GetStats makes the
        # dispatcher build its full obs summary.
        self.tune_sync_interval_s = 10.0
        self._compile_sync = None
        self._next_tune_sync = 0.0
        # Fleet telemetry gossip (obs/fleet.py, round 15): one compact
        # frame per poll on JobsRequest.telemetry_json when something
        # changed (or the heartbeat elapsed) — built in run() so the
        # generation id marks THIS run, DBX_FLEET_TELEMETRY=0 disables.
        self._telemetry: "obs_fleet.WorkerTelemetry | None" = None

    def _telemetry_stats(self) -> dict:
        """Counter snapshot for the fleet telemetry frame (obs/fleet.py
        reads through this hook instead of reaching into worker
        internals). The inflight read takes the pipeline lock — the same
        leaf lock the busy flag rides."""
        with self._pipeline_lock:
            inflight = self._pipeline_inflight
        return {"jobs_completed": self.jobs_completed,
                "completions_dropped": self.completions_dropped,
                "polls": int(self._c_polls.value),
                "busy": 1 if self._busy.is_set() else 0,
                "inflight": inflight,
                "pipeline_on": (hasattr(self.backend, "submit")
                                and pipeline_enabled()),
                "pipeline_depth": pipeline_depth()}

    def _collect_gauges(self, reg: "obs.Registry") -> None:
        # Sets the children PRE-CREATED in run() (held on self._gauges)
        # instead of get-or-create per scrape: a scrape racing run()'s
        # cleanup then merely sets detached objects and cannot re-register
        # the just-removed uuid-labeled children.
        g = self._gauges
        if g is None:
            return
        g["in"].set(self._in.depth())
        g["out"].set(self._out.depth())
        g["deferred"].set(len(self._deferred))
        g["busy"].set(1 if self._busy.is_set() else 0)

    # -- compute side ------------------------------------------------------

    def _compute_loop(self) -> None:
        if (hasattr(self.backend, "submit")
                and hasattr(self.backend, "collect")
                and pipeline_enabled()):
            self._compute_loop_pipelined()
        else:
            # The strictly serial path (and every process-only backend):
            # one batch runs decode -> compute -> d2h to completion
            # before the next is touched. DBX_PIPELINE=0 routes two-phase
            # backends here too — the bit-identity reference the
            # pipelined path is verified against.
            self._compute_loop_simple()

    def _compute_loop_simple(self) -> None:
        while True:
            batch = self._in.get()
            if batch is None:
                return
            # The shared pipeline accounting drives the busy flag here
            # too (one batch in flight at a time on this loop), so every
            # `_busy` mutation stays under the one lock.
            self._pipeline_batch_begin()
            try:
                # Adopt the batch's dispatcher-minted traces: the process
                # span (and everything the backend spans beneath it) joins
                # each job's trace as a child of its dispatch span.
                with obs.trace_context(obs.job_trace_pairs(batch)), \
                        obs.span("worker.process", jobs=len(batch),
                                 worker=self.worker_id):
                    for completion in self.backend.process(batch):
                        self._out.put(completion)
            except Exception as e:
                log.exception("backend failed on a %d-job batch; jobs will "
                              "be re-queued by lease expiry", len(batch))
                obs_flight.trigger("collect_fail", subject=self.worker_id,
                                   jobs=len(batch), reason=repr(e))
            finally:
                self._pipeline_batch_end()

    def _compute_loop_pipelined(self) -> None:
        """Double-buffered compute pipeline: THIS thread decodes, builds
        page tables, and launches batch N+1 while the collector thread
        blocks on batch N's device drain.

        The reference worker's loop is fully serial — one job finishes
        before the next is touched (reference ``src/worker/process.rs:21-25``);
        SURVEY.md §2.3 (PP row) and §7 hard part (e) prescribe this
        decode -> H2D -> compute overlap instead. Submitted batches hand
        off through a queue whose depth a slot semaphore bounds at
        ``DBX_PIPELINE_DEPTH`` (default 2 — classic double buffering);
        the slot acquire is the backpressure that also stops the control
        thread's polls once the input channel fills behind it. The
        shutdown sentinel flows
        through both stages in order, so every batch taken before it is
        submitted AND collected before the pipeline exits — the
        finish-or-requeue drain contract (whatever a hard kill strands
        is re-queued by lease expiry, never silently lost).
        """
        handoff: queue_mod.Queue = queue_mod.Queue()
        # Depth is enforced by slot reservation BEFORE the submit
        # dispatches device work — bounding the handoff queue instead
        # would let depth+2 submitted batches live on device (the
        # just-submitted one blocked in put, plus the collector's).
        # Depth counts submitted-but-uncollected batches INCLUSIVE of
        # the one being collected: 2 really is one batch on device, one
        # staging — the old opportunistic loop's bound.
        slots = threading.BoundedSemaphore(pipeline_depth())
        self._pipeline_done.clear()
        collector = threading.Thread(target=self._collect_loop,
                                     args=(handoff, slots),
                                     name="dbx-collect", daemon=True)
        collector.start()
        try:
            while True:
                batch = self._in.get()
                if batch is None:
                    return
                slots.acquire()
                self._pipeline_batch_begin()
                pending = self._try_submit(batch)
                if pending is None:
                    # Failed submit: the batch is already logged and left
                    # to its lease; nothing enters the pipeline.
                    self._pipeline_batch_end()
                    slots.release()
                    continue
                handoff.put((pending, time.time()))
        finally:
            # Ordered drain: the sentinel lands BEHIND every submitted
            # batch, so the collector finishes them all before exiting —
            # run()'s completion flush then sees the full pipeline.
            handoff.put(None)
            self._pipeline_done.set()
            collector.join()

    def _collect_loop(self, handoff: queue_mod.Queue, slots) -> None:
        """Collector half of the pipeline: drain submitted batches in
        submission order and stream their completions into the out
        channel. Runs on its own thread so the blocking device drain
        (the d2h wait) overlaps the submit thread's host work."""
        while True:
            try:
                # Bounded wait (dbxlint blocking-call: allowlisted
                # pipeline queue wait): the sentinel is the exit
                # protocol; the timeout only guards against a submit
                # thread that died without posting it.
                item = handoff.get(timeout=0.25)
            except queue_mod.Empty:
                if self._pipeline_done.is_set():
                    return
                continue
            if item is None:
                return
            pending, submitted_wall = item
            # The submit-return -> collect-start window: the batch is in
            # flight on the device (jax dispatched eagerly) while the
            # submit thread works on the NEXT batch. Without a span the
            # timeline analyzer would charge this window to transport
            # (uncovered-gap rule); it maps to execute at envelope
            # priority (obs.timeline SPAN_STAGE).
            wait_s = time.time() - submitted_wall
            if wait_s > 0:
                obs.emit_span("worker.inflight", submitted_wall, wait_s,
                              pairs=obs.job_trace_pairs(pending[1]),
                              jobs=len(pending[1]))
            self._collect_into_out(pending)
            self._pipeline_batch_end()
            slots.release()

    def _pipeline_batch_begin(self) -> None:
        with self._pipeline_lock:
            self._pipeline_inflight += 1
            self._busy.set()

    def _pipeline_batch_end(self) -> None:
        with self._pipeline_lock:
            self._pipeline_inflight -= 1
            if self._pipeline_inflight == 0:
                self._busy.clear()

    def _try_submit(self, batch):
        try:
            # The per-batch span chain (worker.submit -> worker.collect ->
            # worker.report): submit covers decode + H2D + kernel launch,
            # collect the device drain + d2h wait, report the completion
            # RPC — the decode->compute->report attribution the JSONL
            # event log reconstructs per batch. The trace context adopts
            # every job's dispatcher-minted (trace_id, dispatch span) pair
            # so the chain stitches cross-process.
            with obs.trace_context(obs.job_trace_pairs(batch)), \
                    obs.span("worker.submit", jobs=len(batch),
                             worker=self.worker_id):
                return (self.backend.submit(batch), batch)
        except Exception as e:
            log.exception("backend failed submitting a %d-job batch; jobs "
                          "will be re-queued by lease expiry", len(batch))
            obs_flight.trigger("collect_fail", subject=self.worker_id,
                               jobs=len(batch), reason=repr(e))
            return None

    def _collect_into_out(self, pending) -> None:
        handle, batch = pending
        try:
            with obs.trace_context(obs.job_trace_pairs(batch)), \
                    obs.span("worker.collect", jobs=len(batch),
                             worker=self.worker_id):
                for completion in self.backend.collect(handle):
                    self._out.put(completion)
        except Exception as e:
            log.exception("backend failed on a %d-job batch; jobs will "
                          "be re-queued by lease expiry", len(batch))
            obs_flight.trigger("collect_fail", subject=self.worker_id,
                               jobs=len(batch), reason=repr(e))

    # -- control side ------------------------------------------------------

    def run(self, *, max_idle_polls: int | None = None) -> None:
        """Run until stopped (or until ``max_idle_polls`` empty polls).

        ``max_idle_polls`` gives batch-style runs a natural exit: stop after
        that many consecutive empty replies once at least one job was seen.
        """
        channel = grpc.insecure_channel(
            self.target, options=service.default_channel_options(),
            compression=grpc.Compression.Gzip)
        stub = service.DispatcherStub(channel)
        if getattr(self.backend, "panel_cache", None) is not None:
            # Compute-thread recovery hook for the evicted-between-poll-
            # and-decode race (gRPC channels are thread-safe); the primary
            # resolution happens in _poll_jobs on this thread.
            self.backend.payload_fetcher = (
                lambda digest: self._fetch_payload(stub, digest))
        if getattr(self.backend, "schedule_registry", None) is not None:
            # Fleet compile-cache exchange rides the jax persistent cache
            # dir this process already configured (a harness's choice is
            # respected); best-effort — None degrades to uncached.
            from .. import tune as tune_mod

            self._compile_sync = tune_mod.attach(registry=self.obs)
        if obs_fleet.telemetry_enabled():
            # Fleet telemetry (round 15): frames ride _poll_jobs; the
            # generation id minted here marks THIS run, so a restarted
            # worker's frames supersede its predecessor's at the
            # dispatcher instead of interleaving with them.
            self._telemetry = obs_fleet.WorkerTelemetry(
                self.worker_id, stats_fn=self._telemetry_stats,
                backend=self.backend, registry=self.obs)
        # Fresh timer epoch: the rate is "since the worker STARTED", not
        # since it was constructed (a harness may build workers long
        # before running them).
        # The per-worker label set is a deliberate, BOUNDED exception to
        # the obs-cardinality rule: one process hosts a handful of workers
        # and every uuid-labeled child is removed in this method's finally
        # (lifecycle hygiene below), so the series count tracks LIVE
        # workers, not all workers ever seen.
        # dbxlint: disable=obs-cardinality -- lifecycle-managed: removed in run()'s finally
        self._jobs_rate = obs.StepTimer(self.obs.gauge(
            "dbx_worker_jobs_per_sec",
            help="accepted completions/s since worker start",
            worker=self.worker_id))
        wid = self.worker_id
        self._gauges = {
            # dbxlint: disable=obs-cardinality -- lifecycle-managed: removed in run()'s finally
            "in": self.obs.gauge("dbx_worker_channel_depth", worker=wid,
                                 channel="in"),
            # dbxlint: disable=obs-cardinality -- lifecycle-managed: removed in run()'s finally
            "out": self.obs.gauge("dbx_worker_channel_depth", worker=wid,
                                  channel="out"),
            # dbxlint: disable=obs-cardinality -- lifecycle-managed: removed in run()'s finally
            "deferred": self.obs.gauge("dbx_worker_deferred_completions",
                                       worker=wid),
            # dbxlint: disable=obs-cardinality -- lifecycle-managed: removed in run()'s finally
            "busy": self.obs.gauge("dbx_worker_busy", worker=wid)}
        self.obs.add_collector(f"worker-{wid}", self._collect_gauges)
        self._compute_thread = threading.Thread(
            target=self._compute_loop, name="dbx-compute", daemon=True)
        self._compute_thread.start()

        idle_polls = 0
        saw_work = False
        next_poll = 0.0
        try:
            while not self._stop.is_set():
                now = time.monotonic()
                if now >= self._next_status:
                    self._next_status = now + self.status_interval_s
                    self._send_status(stub)
                if (now >= self._next_tune_sync
                        and getattr(self.backend, "schedule_registry",
                                    None) is not None):
                    self._next_tune_sync = now + self.tune_sync_interval_s
                    self._sync_tune(stub)
                if now >= next_poll:
                    next_poll = now + self.poll_interval_s
                    got = self._poll_jobs(stub)
                    if got is not None:
                        if got:
                            saw_work = True
                            idle_polls = 0
                        elif (not self._busy.is_set() and self._out.empty()
                                and not self._deferred):
                            idle_polls += 1
                self._drain_completions(stub)
                if (max_idle_polls is not None and saw_work
                        and idle_polls >= max_idle_polls):
                    log.info("idle for %d polls; draining and exiting",
                             idle_polls)
                    break
                time.sleep(min(self.poll_interval_s, 0.05))
            self._shutdown(stub)
        finally:
            if getattr(self.backend, "panel_cache", None) is not None:
                # The fetcher closes over THIS run's channel/stub; a
                # backend outliving the worker loop must not keep (or
                # call) a hook bound to a closed channel.
                self.backend.payload_fetcher = None
            channel.close()
            # Lifecycle hygiene: a long-lived process constructing many
            # Workers (bench's control-plane saturation config) must not
            # accumulate dead collectors or uuid-labeled gauge children —
            # every scrape, GetStats payload, and BENCH obs blob would
            # carry them forever.
            self.obs.remove_collector(f"worker-{self.worker_id}")
            self._jobs_rate.bind_gauge(None)
            wid = self.worker_id
            self.obs.remove_child("dbx_worker_jobs_per_sec", worker=wid)
            for ch in ("in", "out"):
                self.obs.remove_child("dbx_worker_channel_depth",
                                      worker=wid, channel=ch)
            self.obs.remove_child("dbx_worker_deferred_completions",
                                  worker=wid)
            self.obs.remove_child("dbx_worker_busy", worker=wid)

    def stop(self) -> None:
        self._stop.set()

    def _shutdown(self, stub) -> None:
        """Graceful drain: finish queued batches, flush completions.

        The shutdown sentinel traverses the WHOLE pipeline in order —
        input channel, submit stage, handoff queue, collect stage — so
        joining the compute thread here waits for every taken batch to
        be submitted AND collected (``_compute_loop_pipelined``'s
        finally joins its collector); nothing produces into the
        completion queue afterwards and a non-blocking drain is
        exhaustive. A pipeline that cannot finish inside the join budget
        (wedged device) is abandoned with its batches still leased —
        finish-or-requeue, never a silently lost completion. Deferred
        (previously failed) completions get their remaining retry
        attempts inside a bounded exit budget; whatever still fails is
        re-queued by lease expiry dispatcher-side.
        """
        if self._prefetch_q is not None:
            # Best-effort thread: no drain needed, just a clean exit (a
            # straggling warm-up is abandoned with the daemon thread).
            self._prefetch_q.put(None)
            self._prefetch_thread.join(timeout=5.0)
            self._prefetch_q = None
            self._prefetch_thread = None
        self._in.put(None)
        if self._compute_thread is not None:
            self._compute_thread.join(timeout=60.0)
            if self._compute_thread.is_alive():
                log.error("compute pipeline did not drain within the exit "
                          "budget; in-flight batches stay leased and will "
                          "be re-queued by lease expiry")
        deadline = time.monotonic() + 8.0
        self._drain_completions(stub, ignore_status_deadline=True)
        while self._deferred and time.monotonic() < deadline:
            time.sleep(0.1)
            self._drain_completions(stub, ignore_status_deadline=True)
        if self._deferred:
            log.error("exiting with %d undelivered completions "
                      "(leases will re-queue them)", len(self._deferred))

    def _send_status(self, stub) -> None:
        status = (pb.WORKER_STATUS_RUNNING if self._busy.is_set()
                  else pb.WORKER_STATUS_IDLE)
        try:
            with obs.timer(self._h_rpc["SendStatus"]):
                stub.SendStatus(pb.StatusRequest(
                    worker_id=self.worker_id, status=status), timeout=5.0)
            self._log_reconnected()
        except grpc.RpcError as e:
            self._c_rpc_errors["SendStatus"].inc()
            self._log_disconnected(e)

    def _sync_tune(self, stub) -> None:
        """One tuned-schedule / compile-cache sync tick (control thread,
        never sleeps, every leg best-effort — a flaky dispatcher costs a
        tick, never a job):

        - offer cache entries this worker's own compiles just wrote;
        - poll the fleet listing, fetch + install entries we lack (the
          cold-start compile skip);
        - adopt the merged fleet schedule registry from GetStats (the
          push leg rides JobsRequest.schedule_json in `_poll_jobs`).
        """
        sync = self._compile_sync
        try:
            if sync is not None:
                fresh = sync.poll_new()
                if fresh:
                    req = pb.CompiledOffer(
                        worker_id=self.worker_id,
                        entries=[pb.CompiledEntry(key=k, name=n,
                                                  payload=p)
                                 for k, n, p in fresh])
                    try:
                        with obs.timer(self._h_rpc["OfferCompiled"]):
                            stub.OfferCompiled(req, timeout=30.0)
                    except grpc.RpcError as e:
                        # A lost offer must not drop a paid compile wall
                        # from fleet sharing: un-mark so the next poll
                        # re-offers (the remark_dirty twin).
                        self._c_rpc_errors["OfferCompiled"].inc()
                        sync.unmark(fresh)
                        raise _SyncLegFailed from e
                try:
                    with obs.timer(self._h_rpc["FetchCompiled"]):
                        listing = stub.FetchCompiled(pb.CompiledRequest(
                            worker_id=self.worker_id), timeout=10.0)
                    miss = sync.missing(listing.known_keys)
                    # Chunked fetches: one bulk reply for a full store
                    # could exceed the channel's message cap; remaining
                    # keys stay missing and ride the next tick.
                    for i in range(0, len(miss),
                                   self._COMPILE_FETCH_BATCH):
                        chunk = miss[i:i + self._COMPILE_FETCH_BATCH]
                        with obs.timer(self._h_rpc["FetchCompiled"]):
                            got = stub.FetchCompiled(pb.CompiledRequest(
                                worker_id=self.worker_id, keys=chunk),
                                timeout=60.0)
                        installed = sync.install(
                            (e.key, e.name, e.payload)
                            for e in got.entries)
                        sync.count_fleet_misses(len(chunk) - installed)
                except grpc.RpcError as e:
                    self._c_rpc_errors["FetchCompiled"].inc()
                    raise _SyncLegFailed from e
            try:
                with obs.timer(self._h_rpc["GetStats"]):
                    stats = stub.GetStats(pb.StatsRequest(), timeout=10.0)
            except grpc.RpcError as e:
                self._c_rpc_errors["GetStats"].inc()
                raise _SyncLegFailed from e
            if stats.schedule_json:
                self.backend.schedule_registry.merge_json(
                    stats.schedule_json)
            self._log_reconnected()
        except _SyncLegFailed as e:
            self._log_disconnected(e.__cause__)
        except Exception:
            log.exception("tune sync tick failed; will retry next tick")

    def _poll_jobs(self, stub):
        """Request a batch if the compute queue has room; None on RPC error."""
        if self._in.full():
            return None
        self._c_polls.inc()
        schedule_json = ""
        reg = getattr(self.backend, "schedule_registry", None)
        if reg is not None:
            # Gossip-up leg: entries tuned since the last poll (usually
            # empty — zero wire cost on a clean poll).
            schedule_json = reg.take_dirty_json()
        telemetry_json = ""
        if self._telemetry is not None:
            # Fleet telemetry leg: empty when nothing changed inside the
            # heartbeat interval — the same dirty-bit discipline.
            telemetry_json = self._telemetry.take_frame_json()
        req = pb.JobsRequest(
            worker_id=self.worker_id, chips=self.backend.chips,
            jobs_per_chip=self.jobs_per_chip,
            # Digest-only dispatch is safe for ANY backend this worker
            # hosts: backends with a panel cache resolve digests, and
            # payload-less fakes (instant/sleep) never read ohlcv at all.
            accepts_digest_only=True,
            # Spec-batch scenario jobs need a backend that can regenerate
            # panels in-trace; only the JAX backend declares it (and only
            # while the DBX_SCENARIO_FUSED kill switch is up).
            accepts_scenario_batch=bool(
                getattr(self.backend, "accepts_scenario_batch", False)),
            schedule_json=schedule_json,
            telemetry_json=telemetry_json)
        try:
            with obs.timer(self._h_rpc["RequestJobs"]):
                reply = stub.RequestJobs(req, timeout=30.0)
            self._log_reconnected()
        except grpc.RpcError as e:
            self._c_rpc_errors["RequestJobs"].inc()
            self._log_disconnected(e)
            if schedule_json and reg is not None:
                # The drained dirty entries never reached the dispatcher:
                # re-mark them so the next successful poll pushes them.
                reg.remark_dirty(schedule_json)
            if telemetry_json and self._telemetry is not None:
                # The frame never arrived: resend on the next poll.
                self._telemetry.remark_dirty()
            return None
        self._c_wire[("RequestJobs", "request")].inc(_pb_size(req))
        self._c_wire[("RequestJobs", "reply")].inc(_pb_size(reply))
        jobs = list(reply.jobs)
        if jobs:
            log.info("received %d jobs", len(jobs))
            self._c_jobs_in.inc(len(jobs))
            self._resolve_payloads(stub, jobs)
            if prefetch_enabled():
                self._prefetch(jobs)
            self._in.put(jobs)
        else:
            self._c_idle_polls.inc()
        return jobs

    def _prefetch(self, jobs) -> None:
        """``DBX_PREFETCH`` (default on): stage a just-received batch's
        inputs on THIS thread while the compute pipeline runs earlier
        batches — the control-loop half of the round-14 stage overlap.

        Two legs, both best-effort and bounded by the batch:

        - **backend warm-up** (``backend.prefetch``, handed to the
          dedicated prefetch thread — page uploads can first-call-
          compile their scatter for seconds, and THIS thread owns the
          SendStatus heartbeat the prune window watches): decode payload
          bytes into the host panel cache and pre-stage device pages, so
          the compute thread's decode becomes a cache hit (the payload
          resolution itself already ran in ``_resolve_payloads`` — the
          PR-5 per-batch fetch memo this leg rides);
        - **compile-cache pull-forward**: first contact with a new
          (strategy, payload-size-bucket) signature pulls the next
          tune-sync tick to NOW, so the FetchCompiled legs (round 10)
          run before the batch's first compile instead of on the 10 s
          timer — a fleet-cached compile stops stalling the compute
          thread for the wall the first worker already paid.
        """
        if getattr(self.backend, "prefetch", None) is not None:
            # Hand the warm-up to the prefetch thread: page uploads and
            # their first-call scatter compiles must not park the
            # heartbeat this thread owns past the dispatcher's prune
            # window.
            if self._prefetch_thread is None:
                self._prefetch_q = queue_mod.Queue()
                self._prefetch_thread = threading.Thread(
                    target=self._prefetch_loop, name="dbx-prefetch",
                    daemon=True)
                self._prefetch_thread.start()
            self._prefetch_q.put(jobs)
        if self._compile_sync is not None:
            fresh = {(j.strategy,
                      (len(j.ohlcv) or j.panel_bytes_len).bit_length())
                     for j in jobs}
            if not fresh <= self._prefetch_seen:
                if len(self._prefetch_seen) > 4096:  # long-lived bound
                    self._prefetch_seen.clear()
                self._prefetch_seen |= fresh
                self._next_tune_sync = 0.0

    def _prefetch_loop(self) -> None:
        """Prefetch thread: best-effort backend warm-ups off the control
        thread. Every warmed path re-resolves through the same caches on
        the compute thread, so racing (or trailing) the batch it staged
        costs nothing but the overlap."""
        while True:
            jobs = self._prefetch_q.get()
            if jobs is None:
                return
            warm = getattr(self.backend, "prefetch", None)
            if warm is None:
                continue
            t0_wall, t0 = time.time(), time.perf_counter()
            try:
                warmed = warm(jobs)
            except Exception:
                log.exception("backend prefetch failed; the compute "
                              "thread will decode inline")
                continue
            if warmed:
                # Prefetched decode IS decode work, done early: the span
                # keeps obs.timeline's decode attribution honest when
                # the compute-side decode span reports a cache hit.
                obs.emit_span("worker.prefetch", t0_wall,
                              time.perf_counter() - t0,
                              pairs=obs.job_trace_pairs(jobs),
                              jobs=len(jobs), warmed=warmed)

    def _resolve_payloads(self, stub, jobs) -> None:
        """Dispatch-by-digest intake: a digest-only job whose panel is not
        already in the backend's cache fetches the bytes by content
        address BEFORE the batch crosses to the compute thread (miss ->
        fetch -> full job). An unfetchable digest leaves the job
        payloadless — the backend then errors the batch loudly and the
        lease requeues it, by which point the dispatcher has forgotten the
        phantom delivery and re-dispatches full bytes. Backends without a
        panel cache (instant/sleep fakes) never decode, so their
        digest-only jobs need no bytes at all."""
        cache = getattr(self.backend, "panel_cache", None)
        if cache is None:
            return
        # Per-batch blob memo: one reply can carry MANY digest-only jobs
        # of one panel (jobs_per_chip > 1 on a shared-panel sweep, where
        # the dispatcher marks the digest delivered at the batch's FIRST
        # job) — the bytes must cross once per batch, not once per job.
        # Seed it with bytes already riding sibling jobs, then fetch each
        # remaining digest at most once.
        blobs: dict[str, bytes] = {}
        for job in jobs:
            if job.panel_digest and job.ohlcv:
                blobs.setdefault(job.panel_digest, job.ohlcv)
            if job.panel_digest2 and job.ohlcv2:
                blobs.setdefault(job.panel_digest2, job.ohlcv2)
        for job in jobs:
            for digest, has_raw, field in (
                    (job.panel_digest, bool(job.ohlcv), "ohlcv"),
                    (job.panel_digest2, bool(job.ohlcv2), "ohlcv2")):
                if not digest or has_raw or cache.contains_series(digest):
                    continue
                if (field == "ohlcv" and job.append_parent_digest
                        and job.append_delta
                        and cache.contains_series(
                            job.append_parent_digest)):
                    # Delta-only append dispatch: the compute path splices
                    # the cached base + append_delta itself; fetching the
                    # full extended panel here would undo the O(ΔT) wire
                    # saving.
                    continue
                blob = blobs.get(digest)
                if blob is None:
                    blob = self._fetch_payload(stub, digest)
                    if blob:
                        blobs[digest] = blob
                if blob:
                    setattr(job, field, blob)

    def _fetch_payload(self, stub, digest: str) -> bytes:
        """One FetchPayload attempt; empty bytes when the dispatcher
        cannot serve the digest (or the RPC fails) — the caller degrades
        to the lease-requeue path, never a failed job."""
        req = pb.PayloadRequest(worker_id=self.worker_id, digest=digest)
        try:
            with obs.timer(self._h_rpc["FetchPayload"]):
                reply = stub.FetchPayload(req, timeout=30.0)
            self._log_reconnected()
        except grpc.RpcError as e:
            self._c_rpc_errors["FetchPayload"].inc()
            self._log_disconnected(e)
            return b""
        self._c_wire[("FetchPayload", "request")].inc(_pb_size(req))
        self._c_wire[("FetchPayload", "reply")].inc(_pb_size(reply))
        if not reply.payload:
            # Not a recovery — don't count it as one (the dispatcher's
            # dbx_payload_fetches_total{outcome="gone"} carries the
            # degraded-period signal).
            log.warning("payload fetch for digest %s came back empty; "
                        "affected jobs will be re-dispatched with full "
                        "bytes", digest[:16])
            return b""
        self._c_fetches.inc()
        return reply.payload

    # Compile-cache entries fetched per FetchCompiled RPC: bounds the
    # reply under the channel message cap even when the fleet store is
    # full (single entries are capped at 64 MB by the store; typical
    # XLA-CPU/TPU entries are KBs).
    _COMPILE_FETCH_BATCH = 32

    # Retry due-times for failed completion RPCs. Attempts are spread over
    # due windows with heartbeats flowing in between — nothing here ever
    # sleeps, so a flaky dispatcher cannot starve liveness.
    _COMPLETION_BACKOFF_S = (0.5, 1.0, 2.0)
    # Completions per CompleteJobs RPC. One unary RPC per completion
    # measured ~2 ms on a loopback Python channel — a ~500 jobs/s control-
    # plane ceiling; batching lifts it an order of magnitude.
    _COMPLETION_BATCH = 256

    def _drain_completions(self, stub, *,
                           ignore_status_deadline: bool = False) -> None:
        """Report queued + due-for-retry completions in batched RPCs.

        Never sleeps, and stops early when a status heartbeat is overdue so
        a slow/flaky dispatcher cannot starve liveness (remaining items are
        picked up on the next loop tick).
        """
        def status_overdue() -> bool:
            return (not ignore_status_deadline
                    and time.monotonic() >= self._next_status)

        now = time.monotonic()
        ready = [(a, c) for due, a, c in self._deferred
                 if due <= now or ignore_status_deadline]
        self._deferred = [d for d in self._deferred
                          if not (d[0] <= now or ignore_status_deadline)]
        while True:
            while len(ready) < self._COMPLETION_BATCH:
                try:
                    ready.append((0, self._out.get_nowait()))
                except queue_mod.Empty:
                    break
            if not ready:
                return
            if status_overdue():
                now = time.monotonic()
                self._deferred.extend((now, a, c) for a, c in ready)
                return
            chunk = ready[:self._COMPLETION_BATCH]
            ready = ready[self._COMPLETION_BATCH:]
            self._report_completions(stub, chunk)

    def _report_completions(self, stub, chunk) -> None:
        """One CompleteJobs attempt for ``chunk`` = [(attempts, completion)];
        on RPC failure each item parks for deferred retry (or is dropped
        once its attempts are exhausted — the lease re-queues the job)."""
        req = pb.CompleteBatch(worker_id=self.worker_id, items=[
            pb.CompleteItem(id=c.job_id, metrics=c.metrics,
                            elapsed_s=c.elapsed_s, trace_id=c.trace_id)
            for _, c in chunk])
        try:
            # Timeout stays under the dispatcher's default 10 s prune window:
            # only ONE batch RPC can delay the next heartbeat (status_overdue
            # yields between chunks), so 8 s bounds the worst heartbeat gap.
            # A link too slow to move a chunk in 8 s fails the attempt; items
            # park for retry and, if attempts exhaust, leases re-queue them.
            # The report span joins each completion's trace (no remote
            # parent — the dispatch span parented the compute chain; the
            # report leg is a root-level stage of the job's timeline).
            with obs.trace_context([(c.trace_id, "") for _, c in chunk]), \
                    obs.span("worker.report", jobs=len(chunk),
                             worker=self.worker_id), \
                    obs.timer(self._h_rpc["CompleteJobs"]):
                reply = stub.CompleteJobs(req, timeout=8.0)
            self._log_reconnected()
            self._c_wire[("CompleteJobs", "request")].inc(_pb_size(req))
            self._c_wire[("CompleteJobs", "reply")].inc(_pb_size(reply))
            self.jobs_completed += reply.accepted
            self._jobs_rate.add(reply.accepted)
            for jid in reply.unknown_ids:
                log.warning("completion %s rejected: unknown job", jid)
        except grpc.RpcError as e:
            self._c_rpc_errors["CompleteJobs"].inc()
            self._log_disconnected(e)
            for attempts, comp in chunk:
                if attempts >= len(self._COMPLETION_BACKOFF_S):
                    self.completions_dropped += 1
                    self._c_dropped.inc()
                    log.error("dropping completion %s after %d attempts "
                              "(lease will re-queue it)", comp.job_id,
                              attempts + 1)
                else:
                    due = (time.monotonic()
                           + self._COMPLETION_BACKOFF_S[attempts])
                    self._deferred.append((due, attempts + 1, comp))

    def _log_disconnected(self, err) -> None:
        if self._connected:
            self._connected = False
            log.error("dispatcher unreachable: %s", getattr(err, "code", err))

    def _log_reconnected(self) -> None:
        if not self._connected:
            self._connected = True
            log.info("dispatcher reachable again")


def make_backend(name: str, **kwargs) -> compute.ComputeBackend:
    if name == "jax":
        return compute.JaxSweepBackend(
            param_chunk=kwargs.get("param_chunk"),
            use_fused=kwargs.get("use_fused"),
            use_mesh=kwargs.get("use_mesh"))
    if name == "instant":
        return compute.InstantBackend()
    if name == "sleep":
        return compute.SleepBackend(kwargs.get("delay_s", 0.05))
    raise ValueError(f"unknown backend {name!r}")


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="dbx worker: poll a dispatcher and run backtest jobs")
    ap.add_argument("--connect", default="localhost:50051")
    ap.add_argument("--id", default=None, help="stable worker id")
    ap.add_argument("--backend", default="jax",
                    choices=("jax", "instant", "sleep"))
    ap.add_argument("--param-chunk", type=int, default=None)
    ap.add_argument("--fused", choices=("auto", "on", "off"), default="auto",
                    help="fused Pallas kernels (auto: on for TPU backends)")
    ap.add_argument("--mesh", choices=("auto", "on", "off"), default="auto",
                    help="shard job groups over the local chip mesh "
                         "(auto: on for multi-chip TPU hosts)")
    ap.add_argument("--poll-s", type=float, default=0.25)
    ap.add_argument("--status-s", type=float, default=1.0)
    ap.add_argument("--jobs-per-chip", type=int, default=1)
    ap.add_argument("--exit-after-idle", type=int, default=None,
                    help="exit after N consecutive empty polls (batch mode)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve Prometheus /metrics (+ /stats.json) on this "
                         "port (0 = ephemeral; omit to disable)")
    ap.add_argument("--metrics-host", default="0.0.0.0",
                    help="interface for the /metrics server (use 127.0.0.1 "
                         "to scope the scrape surface to this host)")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    # Runtime lockdep (DBX_LOCKDEP=1): install BEFORE any backend/cache
    # construction so every package lock created below is instrumented.
    from ..analysis import lockdep

    lockdep.maybe_install()
    tristate = {"auto": None, "on": True, "off": False}
    backend = make_backend(args.backend, param_chunk=args.param_chunk,
                           use_fused=tristate[args.fused],
                           use_mesh=tristate[args.mesh])
    worker = Worker(args.connect, backend, worker_id=args.id,
                    poll_interval_s=args.poll_s,
                    status_interval_s=args.status_s,
                    jobs_per_chip=args.jobs_per_chip)
    # SIGTERM/SIGINT -> worker.stop(): run() then drains the compute queue
    # and flushes completions before exiting (_shutdown), so a fleet
    # scale-down loses no finished work (the reference worker had no
    # shutdown path; reference README.md:75-88).
    import signal

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: worker.stop())
    metrics_srv = (obs.MetricsServer(args.metrics_port,
                                     bind=args.metrics_host).start()
                   if args.metrics_port is not None else None)
    log.info("worker %s -> %s (backend=%s, chips=%d)",
             worker.worker_id, args.connect, args.backend, backend.chips)
    try:
        worker.run(max_idle_polls=args.exit_after_idle)
    finally:
        if metrics_srv is not None:
            metrics_srv.stop()


if __name__ == "__main__":
    main()
