"""Binary result codec + job spec <-> proto conversion helpers.

Completions carry the full per-param metric matrix as a compact float32
block ("DBXM"). The reference's completion payload was a free-text string the
server never read (reference ``src/server/main.rs:66-78``); here the payload
is the actual product of the backtest and the dispatcher records it.

Jobs with ``JobSpec.top_k > 0`` instead complete with a "DBXS" (selected)
block: the top-k param indices into the job's canonical grid order plus
their metric values — the on-device reduction that keeps a TPU fleet's
completion leg off the DCN critical path.
"""

from __future__ import annotations

import struct
from typing import Mapping

import numpy as np

from ..obs import get_registry
from ..ops.metrics import Metrics
from . import backtesting_pb2 as pb

# Codec volume counters (pre-resolved module-level: encode runs once per
# completed job on the worker hot path — two lock-cheap increments).
_WIRE_COUNTERS = {
    (d, kind): (get_registry().counter(
                    "dbx_wire_blocks_total",
                    help="result blocks through the codec",
                    direction=d, kind=kind),
                get_registry().counter(
                    "dbx_wire_bytes_total",
                    help="result bytes through the codec",
                    direction=d, kind=kind))
    for d in ("encode", "decode")
    for kind in ("metrics", "topk", "returns")}


def _count_wire(direction: str, kind: str, n_bytes: int) -> None:
    blocks, total = _WIRE_COUNTERS[(direction, kind)]
    blocks.inc()
    total.inc(n_bytes)


_METRICS_MAGIC = b"DBXM"


def metrics_to_bytes(m: Metrics) -> bytes:
    """Pack a ``(P,)``-per-field Metrics tuple into one DBXM block."""
    fields = [np.asarray(f, dtype="<f4").reshape(-1) for f in m]
    P = fields[0].shape[0]
    if any(f.shape[0] != P for f in fields):
        raise ValueError("all metric fields must have equal length")
    head = _METRICS_MAGIC + struct.pack("<II", P, len(fields))
    out = head + b"".join(f.tobytes() for f in fields)
    _count_wire("encode", "metrics", len(out))
    return out


def metrics_from_bytes(data: bytes) -> Metrics:
    """Decode a DBXM block back into a Metrics tuple of ``(P,)`` arrays."""
    if data[:4] != _METRICS_MAGIC:
        raise ValueError("bad magic; not a DBXM metrics block")
    if len(data) < 12:
        raise ValueError(f"truncated metrics block: {len(data)} < 12-byte header")
    P, n_fields = struct.unpack_from("<II", data, 4)
    if n_fields != len(Metrics._fields):
        raise ValueError(
            f"metrics block has {n_fields} fields, expected "
            f"{len(Metrics._fields)}")
    need = 12 + 4 * n_fields * P
    if len(data) < need:
        raise ValueError(f"truncated metrics block: {len(data)} < {need}")
    out = []
    off = 12
    for _ in range(n_fields):
        out.append(np.frombuffer(data, dtype="<f4", count=P, offset=off).copy())
        off += 4 * P
    _count_wire("decode", "metrics", len(data))
    return Metrics(*out)


_TOPK_MAGIC = b"DBXS"


def topk_to_bytes(indices: "np.ndarray", m: Metrics, rank_metric: str) -> bytes:
    """Pack a top-k selection: ``(k,)`` grid-row indices + per-field values.

    ``indices`` index the job's canonical cartesian grid order (see
    :func:`grid_from_proto`), best-first by ``rank_metric`` in the metric's
    own direction. The metric name travels in the block so a reader needs
    no out-of-band context to know what "best-first" meant.
    """
    idx = np.asarray(indices, dtype="<i4").reshape(-1)
    fields = [np.asarray(f, dtype="<f4").reshape(-1) for f in m]
    k = idx.shape[0]
    if any(f.shape[0] != k for f in fields):
        raise ValueError("all metric fields must have length k")
    name = rank_metric.encode("utf-8")
    if len(name) > 255:
        raise ValueError("rank_metric name too long")
    head = _TOPK_MAGIC + struct.pack("<IIB", k, len(fields), len(name)) + name
    out = head + idx.tobytes() + b"".join(f.tobytes() for f in fields)
    _count_wire("encode", "topk", len(out))
    return out


def topk_from_bytes(data: bytes) -> tuple["np.ndarray", Metrics, str]:
    """Decode a DBXS block -> ``(indices, Metrics of (k,) arrays, metric)``."""
    if data[:4] != _TOPK_MAGIC:
        raise ValueError("bad magic; not a DBXS top-k block")
    if len(data) < 13:
        raise ValueError(f"truncated top-k block: {len(data)} < 13-byte header")
    k, n_fields, name_len = struct.unpack_from("<IIB", data, 4)
    if n_fields != len(Metrics._fields):
        raise ValueError(
            f"top-k block has {n_fields} fields, expected "
            f"{len(Metrics._fields)}")
    off = 13
    if len(data) < off + name_len:
        raise ValueError(
            f"truncated top-k block: {len(data)} < {off + name_len} (name)")
    rank_metric = data[off:off + name_len].decode("utf-8")
    off += name_len
    need = off + 4 * k + 4 * n_fields * k
    if len(data) < need:
        raise ValueError(f"truncated top-k block: {len(data)} < {need}")
    idx = np.frombuffer(data, dtype="<i4", count=k, offset=off).copy()
    off += 4 * k
    out = []
    for _ in range(n_fields):
        out.append(np.frombuffer(data, dtype="<f4", count=k,
                                 offset=off).copy())
        off += 4 * k
    _count_wire("decode", "topk", len(data))
    return idx, Metrics(*out), rank_metric


_RETURNS_MAGIC = b"DBXP"


def best_returns_to_bytes(grid_idx: int, m_row: Metrics,
                          returns: "np.ndarray", rank_metric: str) -> bytes:
    """Pack a best-param result WITH its net-return series (a "DBXP"
    portfolio block): the winning grid-row index, its 9 metric values, and
    the per-bar net strategy returns under that parameter set.

    This is what makes FLEET-level portfolio composition possible without
    re-running compute: per-job metric rows cannot be combined into a
    portfolio Sharpe (cross-ticker correlations are lost), but return
    series can — ``aggregate --portfolio`` composes the stored series into
    the true book. ~4 bytes/bar per job (~5 KB for 5y daily), the same
    order as the DBXS block and ~100x smaller than a full DBXM matrix at
    bench scale.
    """
    vals = np.asarray([float(np.asarray(f).reshape(-1)[0]) for f in m_row],
                      dtype="<f4")
    ret = np.asarray(returns, dtype="<f4").reshape(-1)
    name = rank_metric.encode("utf-8")
    if len(name) > 255:
        raise ValueError("rank_metric name too long")
    head = _RETURNS_MAGIC + struct.pack(
        "<IIIB", int(grid_idx), ret.shape[0], vals.shape[0],
        len(name)) + name
    out = head + vals.tobytes() + ret.tobytes()
    _count_wire("encode", "returns", len(out))
    return out


def best_returns_from_bytes(
        data: bytes) -> tuple[int, Metrics, "np.ndarray", str]:
    """Decode a DBXP block -> ``(grid_idx, Metrics of scalars, returns,
    rank_metric)``."""
    if data[:4] != _RETURNS_MAGIC:
        raise ValueError("bad magic; not a DBXP best-returns block")
    if len(data) < 17:
        raise ValueError(
            f"truncated best-returns block: {len(data)} < 17-byte header")
    grid_idx, T, n_fields, name_len = struct.unpack_from("<IIIB", data, 4)
    if n_fields != len(Metrics._fields):
        raise ValueError(
            f"best-returns block has {n_fields} fields, expected "
            f"{len(Metrics._fields)}")
    off = 17
    if len(data) < off + name_len:
        raise ValueError(
            f"truncated best-returns block: {len(data)} < "
            f"{off + name_len} (name)")
    rank_metric = data[off:off + name_len].decode("utf-8")
    off += name_len
    need = off + 4 * n_fields + 4 * T
    if len(data) < need:
        raise ValueError(
            f"truncated best-returns block: {len(data)} < {need}")
    vals = np.frombuffer(data, dtype="<f4", count=n_fields, offset=off)
    off += 4 * n_fields
    ret = np.frombuffer(data, dtype="<f4", count=T, offset=off).copy()
    _count_wire("decode", "returns", len(data))
    return int(grid_idx), Metrics(*(np.float32(v) for v in vals)), ret, \
        rank_metric


def result_kind(data: bytes) -> str:
    """Classify a completion payload: ``"metrics"`` (DBXM), ``"topk"``
    (DBXS), ``"returns"`` (DBXP), or ``"empty"``."""
    if not data:
        return "empty"
    if data[:4] == _METRICS_MAGIC:
        return "metrics"
    if data[:4] == _TOPK_MAGIC:
        return "topk"
    if data[:4] == _RETURNS_MAGIC:
        return "returns"
    raise ValueError("unknown result block magic")


def grid_to_proto(grid: Mapping[str, "np.ndarray"]) -> dict:
    """Param axes dict -> proto map field value dict."""
    return {k: pb.GridAxis(values=[float(v) for v in np.asarray(vs).reshape(-1)])
            for k, vs in grid.items()}


def grid_from_proto(proto_grid) -> dict[str, np.ndarray]:
    """Proto map field -> dict of float32 axis arrays, sorted by axis name.

    Proto3 map iteration order is unspecified, so the wire contract pins a
    canonical axis order: **lexicographic by axis name**. The DBXM metric
    block a completion carries is laid out row-major over the cartesian
    product in this canonical order — decoders must materialize the grid the
    same way (``product_grid(**grid_from_proto(g))``).
    """
    return {k: np.asarray(proto_grid[k].values, np.float32)
            for k in sorted(proto_grid)}


def grid_n_combos(proto_grid) -> int:
    """Cartesian-product size of a job's parameter grid (1 if empty)."""
    n = 1
    for ax in proto_grid.values():
        n *= max(len(ax.values), 1)
    return n
