"""Content-addressed panel blob store (dispatch by digest, dispatcher side).

DESIGN.md's measured control-plane ceiling pins the dispatch floor on
per-job payload marshalling — yet a grid sweep ships the SAME OHLC panel
bytes in every job of the sweep, re-reads file-backed payloads from disk
at every take (including requeues), and the worker re-decodes and
re-uploads them every time. The fix is the TPU-serving shape: keep hot
state resident and address it by handle. This module is the dispatcher's
half — a bounded LRU store of materialized DBX1 panel bytes keyed by
their blake2b-128 content digest:

- ``panel_digest()`` is THE digest function of the whole feature (the
  dispatcher stamps it on :class:`~.dispatcher.JobRecord` and the wire
  ``JobSpec.panel_digest``; the worker's cache keys on the same hex
  string — one implementation so they cannot drift);
- hot panels and requeued jobs never touch disk twice (`take`
  materializes through the store);
- ``FetchPayload`` serves cache-missing workers straight from the store.

Bounded by bytes (``DBX_PANEL_STORE_MB``, default 256): eviction drops
the least-recently-used blob. An evicted digest is not an error — the
job record still knows its source (inline bytes or path), so the store
repopulates lazily, and a worker fetching an unservable digest gets an
empty reply and falls back to full-bytes dispatch.

:class:`ByteLRU` is the one eviction/accounting implementation shared by
this store and BOTH levels of the worker's
:class:`~.compute.PanelCache`, so their semantics cannot drift.

Thread-safe: takes run on the gRPC pool, FetchPayload on another thread.
"""

from __future__ import annotations

import collections
import hashlib
import os
import threading

from .. import obs

_DEFAULT_STORE_MB = 256


def panel_digest(data: bytes) -> str:
    """blake2b-128 hex digest of a panel's wire bytes — the content
    address carried by ``JobSpec.panel_digest`` and every cache key.
    16 bytes of blake2b is collision-resistant far beyond any fleet's
    panel count and hashes >1 GB/s, so stamping at enqueue is free
    relative to the journal fsync it rides with."""
    return hashlib.blake2b(data, digest_size=16).hexdigest()


def store_max_bytes() -> int:
    """The store bound, read lazily (import-time env capture would pin
    the knob before tests/operators can set it)."""
    return int(float(os.environ.get("DBX_PANEL_STORE_MB",
                                    _DEFAULT_STORE_MB)) * 1024 * 1024)


class ByteLRU:
    """Byte-bounded LRU map of ``digest -> value``.

    NOT itself thread-safe — every owner wraps calls in its own lock.
    ``nbytes_of`` prices a value once at insert (``put`` can override
    with an explicit ``nbytes`` for values whose size is cheaper known
    by the caller, e.g. a just-launched device array). Entries larger
    than the whole bound are indexed-then-evicted — callers always get
    a valid insert, the LRU just will not retain it.
    """

    def __init__(self, max_bytes: int, nbytes_of=len):
        self.max_bytes = int(max_bytes)
        self._nbytes_of = nbytes_of
        self._entries: collections.OrderedDict = collections.OrderedDict()
        self.bytes = 0
        self.evictions = 0

    def get(self, key):
        entry = self._entries.get(key)
        if entry is None:
            return None
        self._entries.move_to_end(key)
        return entry[0]

    def put(self, key, value, nbytes: int | None = None) -> None:
        old = self._entries.pop(key, None)
        if old is not None:
            self.bytes -= old[1]
        nb = int(self._nbytes_of(value) if nbytes is None else nbytes)
        self._entries[key] = (value, nb)
        self.bytes += nb
        while self.bytes > self.max_bytes and self._entries:
            _, (_, ev_nb) = self._entries.popitem(last=False)
            self.bytes -= ev_nb
            self.evictions += 1

    def pop(self, key) -> None:
        """Drop one entry (no error if absent); accounting follows."""
        entry = self._entries.pop(key, None)
        if entry is not None:
            self.bytes -= entry[1]

    def sizes(self) -> list[tuple]:
        """``[(key, nbytes), ...]`` in LRU order, values untouched — the
        fleet telemetry sketch's feed (caller holds the owner's lock,
        like every other method here)."""
        return [(k, e[1]) for k, e in self._entries.items()]

    def __contains__(self, key) -> bool:
        return key in self._entries

    def __len__(self) -> int:
        return len(self._entries)


class PanelStore:
    """Bounded LRU map of ``digest -> DBX1 bytes``.

    ``put`` stores a reference to the caller's (immutable) bytes object —
    no copy; for inline job payloads the "store" therefore costs only the
    index entry while the record already pins the bytes. Accounting still
    charges the blob's full length against the bound: the bound is about
    what the store RETAINS for digest-only dispatch, not process RSS.
    """

    def __init__(self, max_bytes: int | None = None,
                 registry: "obs.Registry | None" = None):
        self._lock = threading.Lock()
        self._lru = ByteLRU(store_max_bytes() if max_bytes is None
                            else int(max_bytes))
        reg = registry or obs.get_registry()
        self._c_hits = reg.counter(
            "dbx_panel_store_hits_total",
            help="panel-store lookups served from memory")
        self._c_misses = reg.counter(
            "dbx_panel_store_misses_total",
            help="panel-store lookups that fell through to the source")

    @property
    def max_bytes(self) -> int:
        return self._lru.max_bytes

    @max_bytes.setter
    def max_bytes(self, v: int) -> None:
        self._lru.max_bytes = int(v)

    @property
    def evictions(self) -> int:
        return self._lru.evictions

    def put(self, data: bytes, digest: str | None = None) -> str:
        """Insert (or refresh) a blob; returns its digest."""
        d = digest or panel_digest(data)
        with self._lock:
            self._lru.put(d, data)
        return d

    def get(self, digest: str) -> bytes | None:
        """The blob for ``digest`` (LRU-touched), or None after eviction."""
        with self._lock:
            blob = self._lru.get(digest)
        if blob is not None:
            self._c_hits.inc()
        else:
            self._c_misses.inc()
        return blob

    def __contains__(self, digest: str) -> bool:
        with self._lock:
            return digest in self._lru

    def stats(self) -> dict:
        with self._lock:
            return {"panels": len(self._lru), "bytes": self._lru.bytes,
                    "evictions": self._lru.evictions,
                    "max_bytes": self._lru.max_bytes}
