# -*- coding: utf-8 -*-
# Generated protocol buffer code (message classes only; the service/stub
# layer is hand-written in service.py). Regenerated WITHOUT protoc: the
# environment lacks grpc_tools, so the serialized FileDescriptorProto below
# was produced by loading the previous descriptor, appending the new fields
# (JobsRequest.schedule_json = 5 / StatsReply.schedule_json = 10 — the
# substrate-schedule gossip legs — plus the fleet compile-cache messages
# CompiledRequest/CompiledEntry/CompiledReply/CompiledOffer and the
# FetchCompiled/OfferCompiled RPCs; previous rounds added the tenant +
# scenario fields, the streaming append-bar fields + AppendBars, the
# content-addressed panel fields + FetchPayload, and the tracing fields
# the same way) via google.protobuf.descriptor_pb2, and re-serializing.
# backtesting.proto remains the source of truth; keep the two in sync
# (dbxlint proto-drift checks structurally).
# source: backtesting.proto
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()




DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile(b'\n\x11backtesting.proto\x12\x07dbx.rpc"\x88\x01\n\x0bJobsRequest\x12\x11\n\tworker_id\x18\x01 \x01(\t\x12\r\n\x05chips\x18\x02 \x01(\x05\x12\x15\n\rjobs_per_chip\x18\x03 \x01(\x05\x12\x1b\n\x13accepts_digest_only\x18\x04 \x01(\x08\x12#\n\rschedule_json\x18\x05 \x01(\tR\x0cscheduleJson"\x1a\n\x08GridAxis\x12\x0e\n\x06values\x18\x01 \x03(\x02"\xdb\x04\n\x07JobSpec\x12\n\n\x02id\x18\x01 \x01(\t\x12\x10\n\x08strategy\x18\x02 \x01(\t\x12\r\n\x05ohlcv\x18\x03 \x01(\x0c\x12(\n\x04grid\x18\x04 \x03(\x0b2\x1a.dbx.rpc.JobSpec.GridEntry\x12\x0c\n\x04cost\x18\x05 \x01(\x02\x12\x18\n\x10periods_per_year\x18\x06 \x01(\x05\x12\x0e\n\x06ohlcv2\x18\x07 \x01(\x0c\x12\x10\n\x08wf_train\x18\x08 \x01(\x05\x12\x0f\n\x07wf_test\x18\t \x01(\x05\x12\x11\n\twf_metric\x18\n \x01(\t\x12\r\n\x05top_k\x18\x0b \x01(\x05\x12\x13\n\x0brank_metric\x18\x0c \x01(\t\x12\x14\n\x0cbest_returns\x18\r \x01(\x08\x12\x10\n\x08trace_id\x18\x0e \x01(\t\x12\x16\n\x0eparent_span_id\x18\x0f \x01(\t\x12\x14\n\x0cpanel_digest\x18\x10 \x01(\t\x12\x17\n\x0fpanel_bytes_len\x18\x11 \x01(\x03\x12\x15\n\rpanel_digest2\x18\x12 \x01(\t\x12\x18\n\x10panel_bytes_len2\x18\x13 \x01(\x03\x12\x1c\n\x14append_parent_digest\x18\x14 \x01(\t\x12\x17\n\x0fappend_base_len\x18\x15 \x01(\x03\x12\x14\n\x0cappend_delta\x18\x16 \x01(\x0c\x12\x11\n\ttenant_id\x18\x17 \x01(\t\x12\'\n\x08scenario\x18\x18 \x01(\x0b2\x15.dbx.rpc.ScenarioSpec\x1a>\n\tGridEntry\x12\x0b\n\x03key\x18\x01 \x01(\t\x12 \n\x05value\x18\x02 \x01(\x0b2\x11.dbx.rpc.GridAxis:\x028\x01"+\n\tJobsReply\x12\x1e\n\x04jobs\x18\x01 \x03(\x0b2\x10.dbx.rpc.JobSpec"I\n\rStatusRequest\x12\x11\n\tworker_id\x18\x01 \x01(\t\x12%\n\x06status\x18\x02 \x01(\x0e2\x15.dbx.rpc.WorkerStatus"!\n\x03Ack\x12\n\n\x02ok\x18\x01 \x01(\x08\x12\x0e\n\x06detail\x18\x02 \x01(\t"f\n\x0fCompleteRequest\x12\n\n\x02id\x18\x01 \x01(\t\x12\x11\n\tworker_id\x18\x02 \x01(\t\x12\x0f\n\x07metrics\x18\x03 \x01(\x0c\x12\x11\n\telapsed_s\x18\x04 \x01(\x02\x12\x10\n\x08trace_id\x18\x05 \x01(\t"P\n\x0cCompleteItem\x12\n\n\x02id\x18\x01 \x01(\t\x12\x0f\n\x07metrics\x18\x02 \x01(\x0c\x12\x11\n\telapsed_s\x18\x03 \x01(\x02\x12\x10\n\x08trace_id\x18\x04 \x01(\t"H\n\rCompleteBatch\x12\x11\n\tworker_id\x18\x01 \x01(\t\x12$\n\x05items\x18\x02 \x03(\x0b2\x15.dbx.rpc.CompleteItem";\n\x12CompleteBatchReply\x12\x10\n\x08accepted\x18\x01 \x01(\x05\x12\x13\n\x0bunknown_ids\x18\x02 \x03(\t"\x0e\n\x0cStatsRequest"\x80\x02\n\nStatsReply\x12\x14\n\x0cjobs_pending\x18\x01 \x01(\x03\x12\x13\n\x0bjobs_leased\x18\x02 \x01(\x03\x12\x16\n\x0ejobs_completed\x18\x03 \x01(\x03\x12\x15\n\rjobs_requeued\x18\x04 \x01(\x03\x12\x13\n\x0bjobs_failed\x18\x05 \x01(\x03\x12\x15\n\rworkers_alive\x18\x06 \x01(\x05\x12\x19\n\x11backtests_per_sec\x18\x07 \x01(\x01\x12\x11\n\tsubstrate\x18\x08 \x01(\t\x12\x19\n\x08obs_json\x18\t \x01(\tR\x07obsJson\x12#\n\rschedule_json\x18\n \x01(\tR\x0cscheduleJson"3\n\x0ePayloadRequest\x12\x11\n\tworker_id\x18\x01 \x01(\t\x12\x0e\n\x06digest\x18\x02 \x01(\t"/\n\x0cPayloadReply\x12\x0e\n\x06digest\x18\x01 \x01(\t\x12\x0f\n\x07payload\x18\x02 \x01(\x0c"x\n\rAppendRequest\x12\x11\n\tworker_id\x18\x01 \x01(\t\x12\x14\n\x0cpanel_digest\x18\x02 \x01(\t\x12\x10\n\x08base_len\x18\x03 \x01(\x03\x12\r\n\x05delta\x18\x04 \x01(\x0c\x12\x1d\n\x03job\x18\x05 \x01(\x0b2\x10.dbx.rpc.JobSpec"`\n\x0bAppendReply\x12\n\n\x02ok\x18\x01 \x01(\x08\x12\x0e\n\x06detail\x18\x02 \x01(\t\x12\x0e\n\x06job_id\x18\x03 \x01(\t\x12\x14\n\x0cpanel_digest\x18\x04 \x01(\t\x12\x0f\n\x07new_len\x18\x05 \x01(\x03"\x83\x01\n\x0cScenarioSpec\x12\x13\n\x0bbase_digest\x18\x01 \x01(\t\x12\x0e\n\x06n_bars\x18\x02 \x01(\x05\x12\r\n\x05block\x18\x03 \x01(\x05\x12\x0f\n\x07regimes\x18\x04 \x01(\x05\x12\x11\n\tvol_scale\x18\x05 \x01(\x02\x12\r\n\x05shock\x18\x06 \x01(\x02\x12\x0c\n\x04seed\x18\x07 \x01(\x03"B\n\x0fCompiledRequest\x12\x1b\n\tworker_id\x18\x01 \x01(\tR\x08workerId\x12\x12\n\x04keys\x18\x02 \x03(\tR\x04keys"O\n\rCompiledEntry\x12\x10\n\x03key\x18\x01 \x01(\tR\x03key\x12\x12\n\x04name\x18\x02 \x01(\tR\x04name\x12\x18\n\x07payload\x18\x03 \x01(\x0cR\x07payload"`\n\rCompiledReply\x120\n\x07entries\x18\x01 \x03(\x0b2\x16.dbx.rpc.CompiledEntryR\x07entries\x12\x1d\n\nknown_keys\x18\x02 \x03(\tR\tknownKeys"^\n\rCompiledOffer\x12\x1b\n\tworker_id\x18\x01 \x01(\tR\x08workerId\x120\n\x07entries\x18\x02 \x03(\x0b2\x16.dbx.rpc.CompiledEntryR\x07entries*A\n\x0cWorkerStatus\x12\x16\n\x12WORKER_STATUS_IDLE\x10\x00\x12\x19\n\x15WORKER_STATUS_RUNNING\x10\x012\xa3\x04\n\nDispatcher\x127\n\x0bRequestJobs\x12\x14.dbx.rpc.JobsRequest\x1a\x12.dbx.rpc.JobsReply\x122\n\nSendStatus\x12\x16.dbx.rpc.StatusRequest\x1a\x0c.dbx.rpc.Ack\x125\n\x0bCompleteJob\x12\x18.dbx.rpc.CompleteRequest\x1a\x0c.dbx.rpc.Ack\x12C\n\x0cCompleteJobs\x12\x16.dbx.rpc.CompleteBatch\x1a\x1b.dbx.rpc.CompleteBatchReply\x126\n\x08GetStats\x12\x15.dbx.rpc.StatsRequest\x1a\x13.dbx.rpc.StatsReply\x12>\n\x0cFetchPayload\x12\x17.dbx.rpc.PayloadRequest\x1a\x15.dbx.rpc.PayloadReply\x12:\n\nAppendBars\x12\x16.dbx.rpc.AppendRequest\x1a\x14.dbx.rpc.AppendReply\x12A\n\rFetchCompiled\x12\x18.dbx.rpc.CompiledRequest\x1a\x16.dbx.rpc.CompiledReply\x125\n\rOfferCompiled\x12\x16.dbx.rpc.CompiledOffer\x1a\x0c.dbx.rpc.Ackb\x06proto3')

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'backtesting_pb2', globals())
if _descriptor._USE_C_DESCRIPTORS == False:
    DESCRIPTOR._options = None
    DESCRIPTOR._serialized_options = None
# @@protoc_insertion_point(module_scope)
