"""Device-resident OHLCV page pool (ragged paged panel batching).

The worker's :class:`~.compute.PanelCache` caches whole ``(5, T)`` field
blocks per panel digest — which duplicates an append-extended panel's
entire history next to its base and shares nothing between overlapping
histories. This module is the third cache level that fixes both: field
data is stored as fixed-size **T-pages** (``DBX_PAGE_BARS`` bars each,
default 512) in ONE device-resident ``(capacity, page_bars)`` f32 pool,
and a sweep group is described by a per-job **page table** of int32 slot
indices into that pool — the paged-KV discipline of PAPERS.md "Ragged
Paged Attention" applied to OHLCV panels, with the pool kept
block-decomposed and never materialized densely per panel (the "Large
Scale Distributed Linear Algebra With TPUs" discipline).

Addressing: a page is keyed by the blake2b-64 hash of its (repeat-last
padded) bytes, and a ``(panel_digest, field)`` memo maps a panel to its
key list. Content keys are what make sharing structural rather than
special-cased:

- an append-extended panel (PR 6 delta chains) reuses **all of its
  base's full pages** — only the boundary page (whose tail changed from
  pad to real bars) and the new tail pages upload, O(ΔT/page_bars + 1)
  instead of O(T);
- two digests with overlapping histories (the same listing fetched at
  different dates, scenario twins sharing a base) share every aligned
  identical page, so device bytes grow sublinearly in ticker count.

Bounded by ``DBX_PAGE_POOL_MB`` (default 64) with LRU slot reuse; the
pool array grows geometrically up to the bound, so idle workers do not
pin the full budget. Uploads batch all of a group's missing pages into
one donated scatter (in-place on backends with buffer donation), and a
group whose working set cannot fit — or whose pages would evict each
other mid-assembly — is REJECTED (``prepare`` returns None) so the
caller falls back to the dense path instead of thrashing.

NOTE on the functional pool array: ``prepare()`` returns the pool as a
jax array; an upload donates the previous array, so callers must always
gather from the MOST RECENT returned pool (holding an older one across
a later uploading ``prepare`` raises on use — by design, not a leak).
Gathers launched before the upload are unaffected (functional arrays).

Threading contract: ``prepare()`` is single-writer — only the worker's
compute thread calls it (the same contract as the backend's submit
path). The index lock exists for the stats surface, which is scraped
from the gRPC thread; the device upload itself runs outside it so a
cold upload (or its first-call jit compile) can never stall a metrics
scrape.
"""

from __future__ import annotations

import collections
import hashlib
import os
import threading

import numpy as np

from .. import obs
from ..ops.fused import resolve_page_bars

_DEFAULT_POOL_MB = 64
_MIN_SLOTS = 8              # smallest useful pool (growth floor)
_PANEL_MEMO_CAP = 16384     # (digest, field) -> page-key lists retained


def pool_max_bytes() -> int:
    """Pool byte bound, read lazily (import-time env capture would pin
    the knob before tests/operators can set it)."""
    return int(float(os.environ.get("DBX_PAGE_POOL_MB",
                                    _DEFAULT_POOL_MB)) * 1024 * 1024)


def page_key(page_bytes: bytes) -> str:
    """blake2b-64 hex of a page's padded bytes — the pool's content
    address. Content (not (digest, page_idx)) keying is what lets an
    append chain reuse its base's full pages and overlapping histories
    share across digests."""
    return hashlib.blake2b(page_bytes, digest_size=8).hexdigest()


def paginate(values: np.ndarray, page_bars: int) -> list[np.ndarray]:
    """Split a 1-D f32 series into ``page_bars``-sized pages; the final
    partial page is repeat-last padded to full width so page content is
    canonical (two panels sharing a full-page prefix hash identically)
    and pad bars inside a page already obey the kernels' repeat-last
    discipline."""
    v = np.ascontiguousarray(np.asarray(values, np.float32))
    out = []
    for s in range(0, v.shape[0], page_bars):
        page = v[s:s + page_bars]
        if page.shape[0] < page_bars:
            page = np.concatenate(
                [page, np.full(page_bars - page.shape[0], page[-1],
                               np.float32)])
        out.append(page)
    return out


class PagePool:
    """Byte-bounded device pool of fixed-size f32 T-pages + host index."""

    def __init__(self, *, page_bars: int | None = None,
                 max_bytes: int | None = None,
                 registry: "obs.Registry | None" = None):
        self.page_bars = int(page_bars if page_bars is not None
                             else resolve_page_bars())
        self.max_bytes = (pool_max_bytes() if max_bytes is None
                          else int(max_bytes))
        page_nbytes = self.page_bars * 4
        self.capacity = max(1, self.max_bytes // page_nbytes)
        self._lock = threading.Lock()
        # Writer serialization (round 14): `prepare` now has TWO callers —
        # the compute thread's submit path and the worker control loop's
        # prefetch warm-up — so whole-prepare runs (index mutation +
        # device upload + pool swap) serialize on this outer lock. The
        # inner `_lock` still guards only the host index, so a stats
        # scrape never waits behind a device upload/compile. Acquisition
        # order is always _write_lock -> _lock.
        self._write_lock = threading.Lock()
        self._pool = None                 # (alloc, page_bars) f32 device
        self._alloc = 0                   # allocated slots (grows to cap)
        self._slots: collections.OrderedDict = collections.OrderedDict()
        #   page key -> slot, LRU-ordered (most recent last)
        self._free: list[int] = []
        self._panel_memo: collections.OrderedDict = collections.OrderedDict()
        #   (panel_digest, field) -> list[page key]
        self._scatter = None
        reg = registry or obs.get_registry()
        self._reg = reg
        # Pre-created for the full (bounded) OHLCV column vocabulary so
        # the /metrics surface is stable from the first scrape — the
        # PanelCache discipline.
        self._c_hits: dict = {}
        self._c_misses: dict = {}
        for fld in ("open", "high", "low", "close", "volume"):
            self._hit_counter(fld, True)
            self._hit_counter(fld, False)
        self._c_rejects = reg.counter(
            "dbx_page_pool_rejects_total",
            help="groups the page pool could not hold (caller fell back "
                 "to the dense path)")
        self._g_bytes = reg.gauge(
            "dbx_page_pool_bytes",
            help="bytes of live pages in the device page pool")
        self._g_pages = reg.gauge(
            "dbx_page_pool_pages", help="live pages in the device page pool")

    # Bounded label vocabulary: OHLCV column names only (the fused specs'
    # ``fields`` tuples), never runtime ids.
    def _hit_counter(self, field: str, hit: bool):
        table = self._c_hits if hit else self._c_misses
        c = table.get(field)
        if c is None:
            name = ("dbx_page_pool_hits_total" if hit
                    else "dbx_page_pool_misses_total")
            c = table[field] = self._reg.counter(
                name, help="page-pool page lookups by OHLCV field "
                           "(hit = page already device-resident)",
                field=field)
        return c

    def _publish(self) -> None:
        self._g_pages.set(len(self._slots))
        self._g_bytes.set(len(self._slots) * self.page_bars * 4)

    def _keys_for(self, digest: str, field: str, values) -> list[str]:
        """Page keys of one panel leg, memoized per (digest, field) so a
        cache-hot panel costs zero hashing. Digestless panels hash every
        time (no stable memo key — correct, just slower)."""
        memo_key = (digest, field) if digest else None
        if memo_key is not None:
            keys = self._panel_memo.get(memo_key)
            if keys is not None and keys[0] == len(values):
                self._panel_memo.move_to_end(memo_key)
                return keys[1]
        pages = paginate(values, self.page_bars)
        keys = [page_key(p.tobytes()) for p in pages]
        if memo_key is not None:
            self._panel_memo[memo_key] = (len(values), keys)
            while len(self._panel_memo) > _PANEL_MEMO_CAP:
                self._panel_memo.popitem(last=False)
        return keys

    def _ensure_alloc(self, n_slots: int):
        """Grow the device array geometrically up to ``capacity``.
        Called with ``self._lock`` HELD (a ``prepare`` helper)."""
        import jax.numpy as jnp

        if n_slots <= self._alloc:
            return
        new_alloc = max(_MIN_SLOTS, self._alloc or _MIN_SLOTS)
        while new_alloc < n_slots:
            new_alloc *= 2
        new_alloc = min(new_alloc, self.capacity)
        new = jnp.zeros((new_alloc, self.page_bars), jnp.float32)
        if self._pool is not None and self._alloc:
            new = new.at[:self._alloc].set(self._pool)
        # No suppression needed: dbxlint's interprocedural lock-discipline
        # proves every caller path (prepare/_take_slot) holds the lock.
        self._free.extend(range(self._alloc, new_alloc))
        self._pool = new
        self._alloc = new_alloc

    def _take_slot(self, pinned: set) -> int | None:
        """A free slot, growing the pool or evicting the least-recently
        used unpinned page; None when every live page is pinned (the
        current group itself cannot fit). Called with ``self._lock``
        HELD (a ``prepare`` helper)."""
        if not self._free and self._alloc < self.capacity:
            self._ensure_alloc(self._alloc + 1)
        if self._free:
            return self._free.pop()
        victim = next((k for k in self._slots if k not in pinned), None)
        if victim is None:
            return None
        return self._slots.pop(victim)

    def _upload(self, pool, slots: list[int], pages: list[np.ndarray]):
        """Batched scatter of missing pages into ``pool``; padded to a
        power-of-two page count so the jit signature set stays bounded.
        NON-donating (round 14): a caller's sweep dispatches its gather
        against the pool array its own ``prepare`` returned, OUTSIDE any
        lock — a donating scatter from the other writer (the control
        loop's prefetch warm-up) would delete that array between return
        and dispatch. The old buffer lives until its in-flight readers
        drop it; the transient double allocation is bounded by one pool.
        Runs OUTSIDE the index lock — see ``prepare``."""
        import jax
        import jax.numpy as jnp

        if self._scatter is None:
            self._scatter = jax.jit(lambda pool, s, p: pool.at[s].set(p))
        k = len(slots)
        k_pad = 1 << (k - 1).bit_length()
        slots = slots + [slots[-1]] * (k_pad - k)
        pages = pages + [pages[-1]] * (k_pad - k)
        return self._scatter(
            pool, jnp.asarray(np.asarray(slots, np.int32)),
            jnp.asarray(np.stack(pages)))

    def prepare(self, digests, series_list, fields):
        """Resolve a sweep group against the pool.

        ``digests``/``series_list`` are per-job panel digests and decoded
        panels; ``fields`` the OHLCV columns the kernel consumes. Returns
        ``(pool_array, tables, info)`` where ``tables[field]`` is the
        ``(n, max_pages)`` int32 slot table (short rows padded with their
        own last slot — the values there are dead under the assembly's
        repeat-last fix) and ``info`` counts newly uploaded pages and
        their in-page pad bars; or None when the group cannot fit
        (caller falls back to the dense path).

        Thread-safe for its two writers (compute submit + control-loop
        prefetch): whole runs serialize on ``_write_lock``, so a
        returned pool array always contains every page its tables
        reference, and concurrent warm-ups cannot interleave half an
        index update with another group's upload.
        """
        with self._write_lock:
            return self._prepare_serialized(digests, series_list, fields)

    def _prepare_serialized(self, digests, series_list, fields):
        with self._lock:
            per_field_keys: dict[str, list[list[str]]] = {f: []
                                                          for f in fields}
            needed: collections.OrderedDict = collections.OrderedDict()
            #   key -> (field, values, page_idx) for pages to build on miss
            hits: dict[str, int] = {f: 0 for f in fields}
            miss: dict[str, int] = {f: 0 for f in fields}
            for d, s in zip(digests, series_list):
                for f in fields:
                    values = np.asarray(getattr(s, f), np.float32)
                    keys = self._keys_for(d, f, values)
                    per_field_keys[f].append(keys)
                    for pi, key in enumerate(keys):
                        if key in self._slots:
                            if key not in needed:
                                hits[f] += 1
                        elif key not in needed:
                            miss[f] += 1
                        needed.setdefault(key, (f, values, pi))
            if len(needed) > self.capacity:
                self._c_rejects.inc()
                return None
            pinned = set(needed)
            # Allocate slots for misses (evicting only unpinned LRU).
            new_slots: list[int] = []
            new_keys: list[str] = []
            new_pages: list[np.ndarray] = []
            pad_new = 0
            for key, (f, values, pi) in needed.items():
                if key in self._slots:
                    self._slots.move_to_end(key)
                    continue
                slot = self._take_slot(pinned)
                if slot is None:         # cannot happen after the cap
                    self._c_rejects.inc()  # check, but stay defensive
                    for k in new_keys:   # unwind this group's part-insert
                        self._free.append(self._slots.pop(k))
                    return None
                lo = pi * self.page_bars
                page = paginate(values[lo:lo + self.page_bars],
                                self.page_bars)[0]
                pad_new += self.page_bars - min(
                    self.page_bars, len(values) - lo)
                self._slots[key] = slot
                new_slots.append(slot)
                new_keys.append(key)
                new_pages.append(page)
            for f in fields:
                if hits[f]:
                    self._hit_counter(f, True).inc(hits[f])
                if miss[f]:
                    self._hit_counter(f, False).inc(miss[f])
            if not new_slots and self._pool is None:
                self._ensure_alloc(_MIN_SLOTS)   # empty pool, warm group
            max_pages = max(
                (len(k) for ks in per_field_keys.values() for k in ks),
                default=1)
            tables = {}
            for f in fields:
                tbl = np.zeros((len(series_list), max_pages), np.int32)
                for i, keys in enumerate(per_field_keys[f]):
                    row = [self._slots[k] for k in keys]
                    tbl[i, :len(row)] = row
                    tbl[i, len(row):] = row[-1]   # dead under repeat-last
                tables[f] = tbl
            self._publish()
            pool = self._pool
        # Device upload OUTSIDE the index lock: the scatter dispatch (and
        # its first-call jit compile, seconds per pow2 shape class) must
        # not stall a concurrent /metrics or GetStats scrape blocking on
        # stats(). Safe under the pool's writer-serialization contract:
        # every prepare() holds `_write_lock` end to end, and stats()
        # never reads `_pool` — only the index updated above.
        if new_slots:
            pool = self._upload(pool, new_slots, new_pages)
            # Writer-serialized (caller holds _write_lock end to end);
            # the index lock guards stats(), which never reads the array.
            # dbxlint: disable=lock-discipline -- writer-serialized under _write_lock
            self._pool = pool
        return pool, tables, {"pages_new": len(new_slots),
                              "pad_bars_new": int(pad_new)}

    def stats(self) -> dict:
        with self._lock:
            return {"pages": len(self._slots),
                    "bytes": len(self._slots) * self.page_bars * 4,
                    "page_bars": self.page_bars,
                    "alloc_slots": self._alloc,
                    "capacity_slots": self.capacity,
                    "max_bytes": self.max_bytes}
