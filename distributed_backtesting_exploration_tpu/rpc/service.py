"""gRPC service/client stubs for the Dispatcher contract.

Hand-written equivalent of what ``grpc_tools.protoc``'s python-grpc plugin
would generate from ``backtesting.proto`` (the plugin is not available in
this environment; only message codegen is). The ``.proto`` file remains the
single source of truth for the wire contract — this module only binds the
five unary RPCs to the generated message classes, once, in one place.

The channel is gzip-compressed in both directions (the reference compressed
only the server->worker leg, reference ``src/server/main.rs:212`` /
``src/worker/main.rs:49``; with binary OHLCV blocks both directions carry
bulk payloads — jobs down, metric matrices up — so symmetric compression is
the right default).

Distributed-trace propagation rides IN the messages (``JobSpec.trace_id``
/ ``parent_span_id``, ``CompleteItem.trace_id``), not in gRPC metadata:
this hand-written stub layer registers plain unary handlers with no
interceptor chain, the worker's native channel codec re-serializes the
same protos across the compute boundary, and the journal persists them —
one carrier, visible to dbxlint's proto-drift rule, instead of a metadata
side-channel each hop would have to re-implement.
"""

from __future__ import annotations

import grpc

from . import backtesting_pb2 as pb

SERVICE_NAME = "dbx.rpc.Dispatcher"

# (method, request class, reply class) — mirrors the service block in
# backtesting.proto.
_METHODS = (
    ("RequestJobs", pb.JobsRequest, pb.JobsReply),
    ("SendStatus", pb.StatusRequest, pb.Ack),
    ("CompleteJob", pb.CompleteRequest, pb.Ack),
    ("CompleteJobs", pb.CompleteBatch, pb.CompleteBatchReply),
    ("GetStats", pb.StatsRequest, pb.StatsReply),
    ("FetchPayload", pb.PayloadRequest, pb.PayloadReply),
    ("AppendBars", pb.AppendRequest, pb.AppendReply),
    ("FetchCompiled", pb.CompiledRequest, pb.CompiledReply),
    ("OfferCompiled", pb.CompiledOffer, pb.Ack),
    ("TriggerDump", pb.DumpRequest, pb.DumpReply),
)

# Server-streaming RPCs (the live signal fan-out's Subscribe): the
# handler is a GENERATOR that yields replies for the stream's lifetime,
# so it occupies one server thread-pool slot per live subscriber
# connection — size DispatcherServer(max_workers=...) for the expected
# connection count plus unary headroom (one connection can carry many
# interests; see SubscribeRequest).
_STREAM_METHODS = (
    ("Subscribe", pb.SubscribeRequest, pb.PushUpdate),
)


class DispatcherServicer:
    """Interface for the server side; subclass and override each RPC."""

    def RequestJobs(self, request: pb.JobsRequest, context) -> pb.JobsReply:
        raise NotImplementedError

    def SendStatus(self, request: pb.StatusRequest, context) -> pb.Ack:
        raise NotImplementedError

    def CompleteJob(self, request: pb.CompleteRequest, context) -> pb.Ack:
        raise NotImplementedError

    def CompleteJobs(self, request: pb.CompleteBatch,
                     context) -> pb.CompleteBatchReply:
        raise NotImplementedError

    def GetStats(self, request: pb.StatsRequest, context) -> pb.StatsReply:
        raise NotImplementedError

    def FetchPayload(self, request: pb.PayloadRequest,
                     context) -> pb.PayloadReply:
        raise NotImplementedError

    def AppendBars(self, request: pb.AppendRequest,
                   context) -> pb.AppendReply:
        raise NotImplementedError

    def FetchCompiled(self, request: pb.CompiledRequest,
                      context) -> pb.CompiledReply:
        raise NotImplementedError

    def OfferCompiled(self, request: pb.CompiledOffer,
                      context) -> pb.Ack:
        raise NotImplementedError

    def Subscribe(self, request: pb.SubscribeRequest, context):
        """Server-streaming: yields :class:`pb.PushUpdate` messages."""
        raise NotImplementedError


def add_dispatcher_to_server(servicer: DispatcherServicer, server) -> None:
    """Register the servicer's unary + server-streaming handlers."""
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req.FromString,
            response_serializer=rep.SerializeToString,
        )
        for name, req, rep in _METHODS
    }
    handlers.update({
        name: grpc.unary_stream_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req.FromString,
            response_serializer=rep.SerializeToString,
        )
        for name, req, rep in _STREAM_METHODS
    })
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),))


class DispatcherStub:
    """Client stub; one callable per RPC, bound to ``channel``.

    Streaming stubs (``Subscribe``) return an iterator of replies; the
    call stays open until the client drops it (``.cancel()`` / channel
    close) or the server ends the stream."""

    def __init__(self, channel: grpc.Channel):
        for name, req, rep in _METHODS:
            setattr(self, name, channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=rep.FromString,
            ))
        for name, req, rep in _STREAM_METHODS:
            setattr(self, name, channel.unary_stream(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=rep.FromString,
            ))


def default_channel_options() -> list[tuple[str, object]]:
    """Channel/server options: gzip + generous message sizes for OHLCV blocks."""
    return [
        ("grpc.default_compression_algorithm", grpc.Compression.Gzip),
        ("grpc.max_send_message_length", 256 * 1024 * 1024),
        ("grpc.max_receive_message_length", 256 * 1024 * 1024),
    ]
